"""Host-side block accounting for the paged KV cache.

The device side is dumb on purpose — two preallocated pool arrays per
layer ([num_blocks, block_size, heads, head_dim]) that the decode
executable scatters into and the ragged paged-attention kernel gathers
from (znicz/paged_attention.py).  ALL placement policy lives here, on
the host, as plain integers: a free-list of physical block ids and one
page-table row per live sequence.  Admitting a sequence is a list pop,
retiring is a list push — no device traffic, no recompilation, which is
the entire point of paging (PAPERS.md "Ragged Paged Attention" /
vLLM's PagedAttention block tables).

Physical block 0 is reserved as the **trash block**: padding rows of
the page table point at it, masked-out prefill positions scatter into
it, and it is never handed to a live sequence — so a stray write can
only ever land somewhere no real sequence reads (the isolation property
tests/test_decode_serving.py asserts over random admit/retire
schedules).

Prefix caching (``prefix_caching=True``) makes the pool
**content-addressed over token prefixes**, the same sha256 dedupe idiom
``checkpoint/store.py`` proved for tensor chunks, applied to live KV
blocks.  Each FULL block of a sequence's history is keyed by a rolling
hash of (parent-block key, the block's tokens) — see :func:`key_chain` —
so equal token prefixes map to equal key chains regardless of which
sequence wrote them.  Blocks then move through three host-side domains:

- **private** (``_live``): owned by exactly one sequence, writable —
  every block starts here; partially-filled and divergent blocks never
  leave.
- **shared** (``_refs``): published under a prefix key, refcounted,
  immutable by convention (the scheduler only ever writes at positions
  beyond the resident prefix — copy-on-write happens naturally because
  the first divergent block is a fresh private block).
- **cached** (``_cached``): refcount reached 0 but the content is kept
  resident and addressable, evicted LRU only when ``alloc`` runs short.

``free`` refuses to release a shared or cached block — eviction is the
only way cached content dies, and a referenced block can never be
reclaimed (the no-free-while-referenced invariant the property tests
assert).  With ``prefix_caching=False`` (the default) none of this
machinery engages and behavior is bit-for-bit the old free-list pool.
"""

import hashlib
from collections import OrderedDict

__all__ = ["KVBlockPool", "required_blocks", "key_chain"]


def required_blocks(tokens, block_size):
    """Blocks a sequence of ``tokens`` total tokens occupies."""
    return -(-int(tokens) // int(block_size))


def key_chain(tokens, block_size, kv_dtype="f32"):
    """Rolling content keys of every FULL block of ``tokens``.

    ``keys[i] = sha256(keys[i-1] + tokens_of_block_i)`` — a block's key
    commits to the entire prefix ending at that block, so two sequences
    share ``keys[i]`` iff their first ``(i+1) * block_size`` tokens are
    identical.  Trailing partial blocks get no key (they are still
    being written).

    ``kv_dtype != "f32"`` mixes the precision into the chain seed:
    quantization is deterministic (same tokens in, same int8 bytes +
    scales out), so tagging the seed is equivalent to hashing the
    quantized bytes themselves — equal tags + equal tokens imply equal
    block content — while guaranteeing an int8 chain can never dedupe
    against an f32 chain whose device bytes differ."""
    bs = int(block_size)
    toks = [int(t) for t in tokens]
    keys = []
    parent = (b"veles-kv" if kv_dtype == "f32"
              else b"veles-kv/" + kv_dtype.encode())
    for i in range(len(toks) // bs):
        h = hashlib.sha256(parent)
        h.update(b",".join(b"%d" % t for t in toks[i * bs:(i + 1) * bs]))
        parent = h.digest()
        keys.append(parent)
    return keys


class KVBlockPool:
    """Free-list allocator over ``num_blocks`` physical blocks.

    Not thread-safe by itself — the decode scheduler's single worker
    thread owns it (the same discipline the device pools get for free
    from executable ordering).
    """

    TRASH = 0           # reserved physical block — never allocated

    def __init__(self, num_blocks, block_size, prefix_caching=False):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.prefix_caching = bool(prefix_caching)
        if self.num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved)")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        # LIFO: recently-freed blocks are reused first (warm in cache)
        self._free = list(range(self.num_blocks - 1, self.TRASH, -1))
        self._live = set()
        # prefix-caching domains (empty forever when the flag is off)
        self._refs = {}                  # block -> refcount (> 0)
        self._cached = OrderedDict()     # block -> key, LRU order
        self._key_of = {}                # block -> key (shared + cached)
        self._by_key = {}                # key -> block
        # cumulative counters (never reset; surfaced via stats())
        self.prefix_hits = 0             # admits that reused >= 1 block
        self.dedup_blocks = 0            # blocks attached already-resident
        self.published_blocks = 0
        self.evicted_blocks = 0
        # speculative-decoding rollback accounting: token positions the
        # verify pass wrote but the accept step discarded (their blocks
        # stay private and masked — never published, never readable)
        self.draft_rollbacks = 0         # spec iterations that rolled back
        self.rolled_back_tokens = 0      # positions written-then-discarded
        # demotion hook: called as on_evict(block, key) just before a
        # cached chain block is reclaimed, while its device contents are
        # still intact — the tiered KV store (veles_tpu/kvtier) captures
        # the block here and parks it in host RAM / on disk instead of
        # letting the content die with the eviction
        self.on_evict = None

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def live_blocks(self):
        """Blocks owned by live sequences (private + shared)."""
        return len(self._live) + len(self._refs)

    @property
    def cached_blocks(self):
        return len(self._cached)

    @property
    def shared_blocks(self):
        return len(self._refs)

    @property
    def capacity(self):
        """Allocatable blocks (total minus the reserved trash block)."""
        return self.num_blocks - 1

    def fits(self, tokens):
        """Whether a sequence of ``tokens`` total tokens can ever fit."""
        return required_blocks(tokens, self.block_size) <= self.capacity

    def alloc(self, n):
        """Pop ``n`` blocks, or None (allocation is all-or-nothing —
        a partial grab would deadlock two half-admitted sequences).

        Cached (refcount-0) blocks back the free list: when the free
        list runs short they are evicted oldest-first, so resident
        prefixes cost nothing until the pool is actually full."""
        n = int(n)
        if n < 1:
            raise ValueError("alloc of %d blocks" % n)
        if n > len(self._free) + len(self._cached):
            return None
        while len(self._free) < n:
            self._evict_one()
        blocks = [self._free.pop() for _ in range(n)]
        self._live.update(blocks)
        return blocks

    def _evict_one(self):
        block, key = self._cached.popitem(last=False)   # LRU
        if self.on_evict is not None:
            self.on_evict(block, key)
        del self._key_of[block]
        del self._by_key[key]
        self._free.append(block)
        self.evicted_blocks += 1

    def free(self, blocks):
        """Return a retired sequence's PRIVATE blocks to the free list.

        Shared blocks go through :meth:`release` instead — freeing a
        block some other sequence still reads is the bug class this
        guard exists for."""
        for b in blocks:
            b = int(b)
            if b == self.TRASH:
                raise ValueError("block 0 is reserved; it was never "
                                 "allocated")
            if b in self._refs:
                raise ValueError("block %d freed while referenced "
                                 "(refcount %d); use release()"
                                 % (b, self._refs[b]))
            if b in self._cached:
                raise ValueError("block %d is cached prefix content; "
                                 "only eviction reclaims it" % b)
            if b not in self._live:
                raise ValueError("double free of block %d" % b)
            self._live.discard(b)
            self._free.append(b)

    # ---------------------------------------------------------------- #
    # content addressing                                               #
    # ---------------------------------------------------------------- #

    def _need_prefix(self):
        if not self.prefix_caching:
            raise RuntimeError("pool was built with prefix_caching=False")

    def acquire_prefix(self, keys):
        """Attach to the longest resident chain prefix of ``keys``.

        Returns the matched blocks (possibly empty), each with its
        refcount incremented — cached blocks are revived to shared.
        The caller owns exactly one reference per returned block and
        must :meth:`release` them all at retire."""
        self._need_prefix()
        blocks = []
        for key in keys:
            b = self._by_key.get(key)
            if b is None:
                break
            if b in self._cached:
                del self._cached[b]
                self._refs[b] = 1
            else:
                self._refs[b] += 1
            blocks.append(b)
        if blocks:
            self.prefix_hits += 1
            self.dedup_blocks += len(blocks)
        return blocks

    def publish(self, block, key):
        """Move a private block into the shared domain under ``key``.

        Returns False (and leaves the block private) if the key is
        already resident — the caller keeps its own copy; first writer
        wins so an existing chain is never rebound under readers."""
        self._need_prefix()
        block = int(block)
        if block not in self._live:
            raise ValueError("publish of non-private block %d" % block)
        if key in self._by_key:
            return False
        self._live.discard(block)
        self._refs[block] = 1
        self._key_of[block] = key
        self._by_key[key] = block
        self.published_blocks += 1
        return True

    def release(self, blocks):
        """Drop one reference per block; refcount 0 parks the block in
        the LRU cache (content stays resident and addressable)."""
        self._need_prefix()
        for b in blocks:
            b = int(b)
            count = self._refs.get(b)
            if not count:
                raise ValueError("release of unshared block %d" % b)
            if count > 1:
                self._refs[b] = count - 1
            else:
                del self._refs[b]
                self._cached[b] = self._key_of[b]   # newest = last

    def note_draft_rollback(self, tokens):
        """Record one speculative iteration discarding ``tokens``
        written-but-rejected positions.  Pure accounting: the rollback
        itself is the scheduler not advancing the sequence length over
        them (the kernel's length masking keeps them invisible until
        overwritten), so no block ever changes domain here — which is
        exactly why rejected content can never be published or shared."""
        if tokens > 0:
            self.draft_rollbacks += 1
            self.rolled_back_tokens += int(tokens)

    def is_shared(self, block):
        return int(block) in self._refs

    def refcount(self, block):
        return self._refs.get(int(block), 0)

    def key_of(self, block):
        """Chain key a shared/cached block is published under, or None."""
        return self._key_of.get(int(block))

    def resident_keys(self):
        """Chain keys currently addressable in HBM (shared + cached)."""
        return list(self._by_key)

    # ---------------------------------------------------------------- #
    # persistence / introspection                                      #
    # ---------------------------------------------------------------- #

    def state_dict(self):
        """Picklable index state for checkpoint_kv (keys as hex)."""
        return {"free": [int(b) for b in self._free],
                "live": sorted(int(b) for b in self._live),
                "refs": {str(b): int(c) for b, c in self._refs.items()},
                "cached": [[int(b), k.hex()]
                           for b, k in self._cached.items()],
                "keys": {str(b): k.hex()
                         for b, k in self._key_of.items()}}

    def load_state(self, state):
        self._free = [int(b) for b in state["free"]]
        self._live = set(int(b) for b in state["live"])
        self._refs = {int(b): int(c)
                      for b, c in state.get("refs", {}).items()}
        self._cached = OrderedDict(
            (int(b), bytes.fromhex(k))
            for b, k in state.get("cached", []))
        self._key_of = {int(b): bytes.fromhex(k)
                        for b, k in state.get("keys", {}).items()}
        self._by_key = {k: b for b, k in self._key_of.items()}
        violations = self.check_integrity()
        if violations:
            raise ValueError("corrupt pool state: %s" % "; ".join(violations))

    def check_integrity(self):
        """List of invariant violations (empty == healthy pool)."""
        bad = []
        domains = [set(self._free), self._live,
                   set(self._refs), set(self._cached)]
        total = sum(len(d) for d in domains)
        if total != self.capacity:
            bad.append("free+live+shared+cached=%d != capacity=%d"
                       % (total, self.capacity))
        seen = set()
        for d in domains:
            if seen & d:
                bad.append("block(s) %s in two domains"
                           % sorted(seen & d))
            seen |= d
        if self.TRASH in seen:
            bad.append("trash block allocated")
        keyed = set(self._refs) | set(self._cached)
        if set(self._key_of) != keyed:
            bad.append("key index out of sync with shared+cached")
        if len(self._by_key) != len(self._key_of):
            bad.append("duplicate keys in block index")
        if any(c < 1 for c in self._refs.values()):
            bad.append("non-positive refcount")
        return bad

    def dump(self):
        """Introspection snapshot for tools/kv_inspect.py."""
        alloc_total = self.published_blocks + self.dedup_blocks
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "prefix_caching": self.prefix_caching,
            "free_blocks": self.free_blocks,
            "private_blocks": len(self._live),
            "shared": sorted(
                ({"block": b, "key": self._key_of[b].hex()[:12],
                  "refcount": c} for b, c in self._refs.items()),
                key=lambda e: e["block"]),
            "cached": [{"block": b, "key": k.hex()[:12]}
                       for b, k in self._cached.items()],
            "prefix_hits": self.prefix_hits,
            "dedup_blocks": self.dedup_blocks,
            "published_blocks": self.published_blocks,
            "evicted_blocks": self.evicted_blocks,
            "draft_rollbacks": self.draft_rollbacks,
            "rolled_back_tokens": self.rolled_back_tokens,
            "dedup_ratio": round(self.dedup_blocks / alloc_total, 4)
                           if alloc_total else 0.0,
            "integrity": self.check_integrity(),
        }

    def stats(self):
        out = {"num_blocks": self.num_blocks,
               "block_size": self.block_size,
               "free_blocks": self.free_blocks,
               "live_blocks": self.live_blocks,
               "utilization": round(
                   self.live_blocks / max(self.capacity, 1), 4)}
        if self.prefix_caching:
            out.update(shared_blocks=self.shared_blocks,
                       cached_blocks=self.cached_blocks,
                       prefix_hits=self.prefix_hits,
                       dedup_blocks=self.dedup_blocks,
                       published_blocks=self.published_blocks,
                       evicted_blocks=self.evicted_blocks)
        return out
