"""Host-side block accounting for the paged KV cache.

The device side is dumb on purpose — two preallocated pool arrays per
layer ([num_blocks, block_size, heads, head_dim]) that the decode
executable scatters into and the ragged paged-attention kernel gathers
from (znicz/paged_attention.py).  ALL placement policy lives here, on
the host, as plain integers: a free-list of physical block ids and one
page-table row per live sequence.  Admitting a sequence is a list pop,
retiring is a list push — no device traffic, no recompilation, which is
the entire point of paging (PAPERS.md "Ragged Paged Attention" /
vLLM's PagedAttention block tables).

Physical block 0 is reserved as the **trash block**: padding rows of
the page table point at it, masked-out prefill positions scatter into
it, and it is never handed to a live sequence — so a stray write can
only ever land somewhere no real sequence reads (the isolation property
tests/test_decode_serving.py asserts over random admit/retire
schedules).
"""

__all__ = ["KVBlockPool", "required_blocks"]


def required_blocks(tokens, block_size):
    """Blocks a sequence of ``tokens`` total tokens occupies."""
    return -(-int(tokens) // int(block_size))


class KVBlockPool:
    """Free-list allocator over ``num_blocks`` physical blocks.

    Not thread-safe by itself — the decode scheduler's single worker
    thread owns it (the same discipline the device pools get for free
    from executable ordering).
    """

    TRASH = 0           # reserved physical block — never allocated

    def __init__(self, num_blocks, block_size):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        if self.num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved)")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        # LIFO: recently-freed blocks are reused first (warm in cache)
        self._free = list(range(self.num_blocks - 1, self.TRASH, -1))
        self._live = set()

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def live_blocks(self):
        return len(self._live)

    @property
    def capacity(self):
        """Allocatable blocks (total minus the reserved trash block)."""
        return self.num_blocks - 1

    def fits(self, tokens):
        """Whether a sequence of ``tokens`` total tokens can ever fit."""
        return required_blocks(tokens, self.block_size) <= self.capacity

    def alloc(self, n):
        """Pop ``n`` blocks, or None (allocation is all-or-nothing —
        a partial grab would deadlock two half-admitted sequences)."""
        n = int(n)
        if n < 1:
            raise ValueError("alloc of %d blocks" % n)
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._live.update(blocks)
        return blocks

    def free(self, blocks):
        """Return a retired sequence's blocks to the free list."""
        for b in blocks:
            b = int(b)
            if b == self.TRASH:
                raise ValueError("block 0 is reserved; it was never "
                                 "allocated")
            if b not in self._live:
                raise ValueError("double free of block %d" % b)
            self._live.discard(b)
            self._free.append(b)

    def stats(self):
        return {"num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "free_blocks": self.free_blocks,
                "live_blocks": self.live_blocks,
                "utilization": round(
                    self.live_blocks / max(self.capacity, 1), 4)}
