"""The inference server: bounded queue, backpressure, drain, endpoints.

A stdlib ``ThreadingHTTPServer`` front end over a
:class:`~veles_tpu.serving.registry.ModelRegistry`.  Per-request flow:
parse (400 on malformed payloads), resolve the model (404), submit to
its bucketed scheduler — which either batches it onto a warm executable
or sheds it (:class:`SchedulerOverflow` → 429 + ``Retry-After``) — and
answer with the reference-shaped ``{"result", "output"}`` JSON.  A
failure *inside* inference is a 500 with a generic body and a server-side
log record; the traceback never leaves the process (the seed handler
returned 400 + ``str(e)`` for everything, restful_api.py:87-88).

Connections are HTTP/1.1 keep-alive with Nagle disabled — a closed-loop
client keeps one TCP connection per worker instead of paying
connect + thread-spawn per request (measured 40 ms delayed-ACK stalls
without ``TCP_NODELAY`` on loopback).

Endpoints:
    POST /api                      infer on the default model
    POST /api/<model>              infer on a named model
    POST /api/<model>/generate     autoregressive decode (token-level
                                   continuous batching; decode models)
    POST /admin/models   hot-load a model version (``enable_admin`` only)
    GET  /healthz        pure liveness + model listing
    GET  /readyz         readiness: 503 until every model's warmup
                         ladder (and decode prefill ladder) is compiled,
                         200 after — what a fleet router gates admission
                         on; the body carries the per-model load signals
    GET  /metrics        per-model latency/throughput/batching snapshot
    GET  /models         registry description

Load shedding answers 429 with a ``Retry-After`` computed from the
scheduler's queue depth and its recent batch latency (one shared helper
— the hint used to be hardcoded to ``1``).

Shutdown is a graceful drain: stop accepting, finish every queued
request, then stop the dispatch workers.
"""

import json
import logging
import threading
import time
import urllib.parse
import uuid
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import ThreadingHTTPServer

from ..httpjson import ClientError, JsonRequestHandler
from ..logger import events
from ..observability import trace as _trace
from ..observability.flight import RECORDER as _flight
from .registry import ModelRegistry
from .scheduler import (DeadlineExpired, SchedulerClosed,
                        SchedulerOverflow, deadline_expired)
from .sessions import pack_state, unpack_states

log = logging.getLogger("veles_tpu.serving")


class _ServingHandler(JsonRequestHandler):
    server_ref = None           # class attr bound per InferenceServer
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True
    # reap idle keep-alive connections; overridden per server from
    # request_timeout (single source of truth — see InferenceServer)
    timeout = 60

    # -- routes --------------------------------------------------------------
    def do_POST(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/admin/models":
            self._admin_load()
            return
        if path.startswith("/admin/sessions/"):
            self._admin_sessions(path[len("/admin/sessions/"):])
            return
        if path != "/api" and not path.startswith("/api/"):
            self.send_json(404, {"error": "not found"})
            return
        name = path[len("/api/"):] if path.startswith("/api/") else None
        if name and name.endswith("/generate"):
            self._generate(name[:-len("/generate")] or None)
        elif name == "generate":
            self._generate(None)
        else:
            self._infer(name)

    def do_GET(self):
        srv = self.server_ref
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/healthz":
            # pure liveness: answers "ok" even while warming or
            # draining — process-up is a different question from
            # accepting-traffic (that's /readyz)
            self.send_json(200, {
                "status": "draining" if srv.draining else "ok",
                "models": srv.registry.names(),
                "default_model": srv.registry.default_name,
                "uptime_s": round(time.time() - srv.started, 1)})
        elif path == "/readyz":
            ready = srv.registry.ready() and not srv.draining
            self.send_json(200 if ready else 503, {
                "ready": ready,
                "draining": srv.draining,
                "models": {name: entry.scheduler.ready
                           for name, entry in
                           ((n, srv.registry.get(n))
                            for n in srv.registry.names())
                           if entry is not None},
                "load": srv.registry.load_snapshot()})
        elif path == "/metrics":
            self.send_json(200, srv.registry.metrics_snapshot())
        elif path == "/models":
            self.send_json(200, srv.registry.describe())
        elif path.startswith("/api/") and path.endswith("/kv"):
            # live KV pool introspection (tools/kv_inspect.py): resident
            # prefixes, refcounts, dedupe ratio, integrity verdict
            name = path[len("/api/"):-len("/kv")] or None
            entry = srv.registry.get(name)
            if entry is None or not hasattr(entry.scheduler, "kv_dump"):
                self.send_json(404, {"error": "no decode model %r"
                                     % name})
                return
            try:
                self.send_json(200, entry.scheduler.kv_dump())
            except Exception as exc:  # noqa: BLE001 — draining et al.
                self.send_json(503, {"error": str(exc)})
        elif path.startswith("/api/") and path.endswith("/requests"):
            # flight-recorder ring: per-request timelines
            # (tools/request_inspect.py; the router merges these into
            # GET /fleet/requests)
            name = path[len("/api/"):-len("/requests")] or None
            query = urllib.parse.parse_qs(
                urllib.parse.urlparse(self.path).query)
            rid = (query.get("id") or [None])[0]
            self.send_json(200, {
                "requests": _flight.snapshot(trace_id=rid, model=name),
                "flight": _flight.stats()})
        elif path == "/admin/sessions" and srv.enable_admin:
            out = {}
            for name in srv.registry.names():
                entry = srv.registry.get(name)
                if entry is not None and \
                        hasattr(entry.scheduler, "session_ids"):
                    out[name] = entry.scheduler.session_ids()
            self.send_json(200, {"sessions": out})
        else:
            self.send_json(404, {"error": "not found"})

    # -- deadlines -----------------------------------------------------------
    def _deadline(self):
        """``X-Deadline-Ms`` (REMAINING budget in ms — relative, so no
        cross-process clock agreement is needed) → an absolute
        ``time.monotonic()`` deadline, or None."""
        raw = self.headers.get("X-Deadline-Ms")
        if not raw:
            return None
        try:
            ms = float(raw)
        except ValueError:
            return None
        return time.monotonic() + max(ms, 0.0) / 1e3

    def _shed_expired(self, trace_hdr=None):
        self.send_json(504, {"error": "deadline expired"},
                       headers=trace_hdr or {})
        return 504

    def _result_timeout(self, deadline):
        """How long to block on a future: the configured request
        timeout, tightened to the request's remaining deadline."""
        timeout = self.server_ref.request_timeout
        if deadline is not None:
            timeout = min(timeout, max(deadline - time.monotonic(),
                                       0.001))
        return timeout

    # -- load shedding -------------------------------------------------------
    def _shed(self, entry, message, close=False, trace_hdr=None):
        """The ONE shed-response constructor: 429 + a ``Retry-After``
        computed from the scheduler's queue depth and recent batch
        latency (was three copies of a hardcoded ``"1"``)."""
        try:
            retry = entry.scheduler.retry_after_s()
        except Exception:  # noqa: BLE001 — a hint must never 500 a shed
            retry = 1
        headers = {"Retry-After": str(int(retry)), **(trace_hdr or {})}
        if close:
            headers["Connection"] = "close"
        self.send_json(429, {"error": message, "model": entry.name,
                             "retry_after_s": int(retry)},
                       headers=headers)
        return 429

    # -- admin: versioned hot-load -------------------------------------------
    def _admin_load(self):
        """``POST /admin/models {"name", "model", "version"?,
        "default"?}`` → registry hot-load (the rolling-update hook).
        404 unless the server was built with ``enable_admin`` — a plain
        InferenceServer keeps the seed surface."""
        srv = self.server_ref
        if not srv.enable_admin:
            self.send_json(404, {"error": "not found"})
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(length))
            if not isinstance(payload, dict) or \
                    not payload.get("name") or "model" not in payload:
                raise ValueError
            name = str(payload["name"])
            spec = payload["model"]
        except ValueError:
            self.send_json(400, {
                "error": "body must be {'name': ..., 'model': "
                         "<package path or spec>, 'version'?: ...}"})
            return
        try:
            model = (srv.model_resolver(spec)
                     if srv.model_resolver is not None else spec)
            entry = srv.registry.add(
                name, model, version=payload.get("version"),
                default=bool(payload.get("default", False)))
        except Exception as exc:  # noqa: BLE001 — report, keep serving
            log.exception("admin hot-load of %r failed", name)
            self.send_json(500, {"error": "hot-load failed: %s"
                                 % str(exc)[:300], "model": name})
            return
        self.send_json(200, {"model": entry.name,
                             "version": entry.version,
                             "ready": entry.scheduler.ready})

    # -- admin: session migration --------------------------------------------
    def _decode_entries(self, model=None):
        """(name, entry) pairs whose schedulers speak the session
        protocol, optionally restricted to one model name."""
        srv = self.server_ref
        names = [model] if model else srv.registry.names()
        out = []
        for name in names:
            entry = srv.registry.get(name)
            if entry is not None and \
                    hasattr(entry.scheduler, "export_sessions"):
                out.append((name, entry))
        return out

    def _admin_sessions(self, action):
        """``POST /admin/sessions/{export,import,release}`` — the
        supervisor's migration surface (``enable_admin`` only).

        export:  {"model"?, "session_ids"?} → {"sessions": [packed]}
                 (each tagged with its model name; exported sessions
                 are PARKED here until release confirms the import)
        import:  {"sessions": [packed]} → {"imported": [...],
                 "errors": [[sid, reason], ...]} — each session lands
                 independently, so a partial failure is visible and
                 the caller restores only the failed ones
        release: {"session_ids": [...], "target"?} → completes the
                 parked futures with a redirect marker
        """
        srv = self.server_ref
        if not srv.enable_admin:
            self.send_json(404, {"error": "not found"})
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError
        except ValueError:
            self.send_json(400, {"error": "body is not a JSON object"})
            return
        try:
            if action == "export":
                sids = payload.get("session_ids")
                sessions = []
                for name, entry in self._decode_entries(
                        payload.get("model")):
                    for state in entry.scheduler.export_sessions(sids):
                        sessions.append(
                            dict(pack_state(state), model=name))
                self.send_json(200, {"sessions": sessions,
                                     "count": len(sessions)})
            elif action == "import":
                raw = payload.get("sessions") or []
                by_model = {}
                for packed in raw:
                    by_model.setdefault(
                        packed.get("model"), []).append(packed)
                imported, errors = [], []
                for model, group in by_model.items():
                    entries = self._decode_entries(model)
                    if not entries:
                        errors.extend(
                            (p.get("session_id"),
                             "no decode model %r" % model)
                            for p in group)
                        continue
                    states = unpack_states(group)
                    for s in states:
                        s.pop("model", None)
                    done, errs = entries[0][1].scheduler \
                        .import_sessions(states)
                    imported.extend(done)
                    errors.extend(errs)
                # 409 when NOTHING landed (and something was sent):
                # the exporter keeps everything and aborts the migrate
                status = 409 if raw and not imported else 200
                self.send_json(status, {
                    "imported": imported,
                    "errors": [[sid, str(reason)]
                               for sid, reason in errors]})
            elif action == "release":
                sids = payload.get("session_ids") or []
                target = payload.get("target")
                released = []
                for name, entry in self._decode_entries(
                        payload.get("model")):
                    released.extend(entry.scheduler.release_migrated(
                        sids, target=target))
                self.send_json(200, {"released": released})
            else:
                self.send_json(404, {"error": "unknown session action "
                                              "%r" % action})
        except Exception as exc:  # noqa: BLE001 — report, keep serving
            log.exception("admin session %s failed", action)
            self.send_json(500, {"error": "%s failed: %s"
                                 % (action, str(exc)[:300])})

    # -- flight-recorder lifecycle -------------------------------------------
    def _flight_open(self, ctx, name, path):
        """Open (or continue) the request's flight timeline: the trace
        id is the stitching key, the tenant tag rides as metadata."""
        tid = ctx.trace_id
        _flight.annotate(tid, model=name or "<default>",
                         tenant=self.headers.get("X-Veles-Tenant"))
        _flight.record(tid, "request.recv", path=path,
                       model=name or "<default>")

    def _flight_close(self, ctx, status):
        """Close the timeline with the anomaly triggers the response
        status implies (shed/deadline/server fault).  A 200 decode
        response was already finished by the scheduler's retire; a 307
        stays open — the destination replica finishes it."""
        tid = ctx.trace_id
        _flight.record(tid, "request.done", status=int(status))
        if status == 429:
            _flight.anomaly(tid, "shed_429")
            _flight.finish(tid, status="shed_429")
        elif status == 504:
            _flight.anomaly(tid, "deadline_504")
            _flight.finish(tid, status="deadline_504")
        elif status >= 500:
            _flight.anomaly(tid, "error", status=int(status))
            _flight.finish(tid, status="error_%d" % status)
        elif status == 200:
            _flight.finish(tid, status="ok")

    # -- the inference path --------------------------------------------------
    def _infer(self, name):
        # request → batch → executable causality: the request runs in a
        # span context (trace id from the client's X-Trace-Id header, or
        # a fresh one), the scheduler captures it at submit, and the
        # batch span links back to these request spans
        with _trace.span_context(
                trace_id=self.headers.get("X-Trace-Id") or None) as ctx:
            t0 = time.perf_counter()
            self._flight_open(ctx, name, "infer")
            status = self._infer_traced(name, ctx)
            events.span("serving.request", time.perf_counter() - t0,
                        model=name or "<default>", status=status)
            self._flight_close(ctx, status)

    def _infer_traced(self, name, ctx):
        """The request body; returns the HTTP status it answered."""
        srv = self.server_ref
        entry = srv.registry.resolve(name)
        trace_hdr = {"X-Trace-Id": ctx.trace_id}
        try:
            batch = self.read_input_payload()
            if batch.ndim == 1:
                batch = batch[None]         # single-sample convenience
            if entry is None:
                self.send_json(404, {
                    "error": "unknown model %r" % (name or "<default>"),
                    "models": srv.registry.names()}, headers=trace_hdr)
                return 404
            entry.scheduler.validate(batch)
        except ClientError as e:
            self.send_json(400, {"error": str(e)}, headers=trace_hdr)
            return 400
        except ValueError as e:             # shape mismatch et al.
            self.send_json(400, {"error": str(e)}, headers=trace_hdr)
            return 400
        deadline = self._deadline()
        if deadline_expired(deadline):
            # expired before submission: shed without touching the
            # scheduler queue at all
            entry.scheduler.metrics.record_expired()
            return self._shed_expired(trace_hdr)
        try:
            result, out = entry.infer(
                batch, timeout=self._result_timeout(deadline),
                deadline=deadline)
        except SchedulerOverflow as e:
            return self._shed(entry, "server overloaded: %s" % e,
                              trace_hdr=trace_hdr)
        except DeadlineExpired:
            return self._shed_expired(trace_hdr)
        except _FutureTimeout:
            if deadline_expired(deadline):
                return self._shed_expired(trace_hdr)
            log.warning("inference on %r exceeded request_timeout",
                        entry.name)
            self.send_json(500, {"error": "request timed out",
                                 "model": entry.name},
                           headers=trace_hdr)
            return 500
        except SchedulerClosed:
            self.send_json(503, {"error": "server is draining"},
                           headers={"Connection": "close", **trace_hdr})
            return 503
        except Exception:
            # server fault: log the traceback HERE, answer a generic
            # body — internals must not leak to the client
            error_id = uuid.uuid4().hex[:12]
            log.exception("inference failed on model %r (error id %s)",
                          entry.name, error_id)
            self.send_json(500, {"error": "internal inference error",
                                 "model": entry.name, "id": error_id},
                           headers=trace_hdr)
            return 500
        self.send_json(200, {"result": result, "output": out.tolist()},
                       headers=trace_hdr)
        return 200

    # -- the decode path -----------------------------------------------------
    def _generate(self, name):
        with _trace.span_context(
                trace_id=self.headers.get("X-Trace-Id") or None) as ctx:
            t0 = time.perf_counter()
            self._flight_open(ctx, name, "generate")
            status = self._generate_traced(name, ctx)
            events.span("serving.generate_request",
                        time.perf_counter() - t0,
                        model=name or "<default>", status=status)
            self._flight_close(ctx, status)

    def _read_generate_payload(self):
        """{"prompt": [...], "max_new_tokens": n?, "session_id": s?}
        → (prompt, n, session_id)."""
        length = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(length))
        except ValueError:
            raise ClientError("body is not valid JSON")
        if not isinstance(payload, dict) or "prompt" not in payload:
            raise ClientError(
                "body must be {'prompt': [tokens], "
                "'max_new_tokens': n?}")
        max_new = payload.get("max_new_tokens")
        if max_new is not None and not isinstance(max_new, int):
            raise ClientError("'max_new_tokens' must be an integer")
        sid = payload.get("session_id")
        if sid is not None and not isinstance(sid, str):
            raise ClientError("'session_id' must be a string")
        return payload["prompt"], max_new, sid

    def _generate_traced(self, name, ctx):
        srv = self.server_ref
        entry = srv.registry.resolve(name)
        trace_hdr = {"X-Trace-Id": ctx.trace_id}
        try:
            prompt, max_new, sid = self._read_generate_payload()
            sid = self.headers.get("X-Session-Id") or sid
            if entry is None:
                self.send_json(404, {
                    "error": "unknown model %r" % (name or "<default>"),
                    "models": srv.registry.names()}, headers=trace_hdr)
                return 404
            if not hasattr(entry, "generate"):
                self.send_json(400, {
                    "error": "model %r is not a decode model; use "
                             "POST /api/%s" % (entry.name, entry.name)},
                    headers=trace_hdr)
                return 400
            entry.scheduler.validate(
                prompt, max_new if max_new is not None
                else entry.scheduler.max_new_tokens)
        except ClientError as e:
            self.send_json(400, {"error": str(e)}, headers=trace_hdr)
            return 400
        except (ValueError, TypeError) as e:
            self.send_json(400, {"error": str(e)}, headers=trace_hdr)
            return 400
        deadline = self._deadline()
        if deadline_expired(deadline):
            entry.scheduler.metrics.record_expired()
            return self._shed_expired(trace_hdr)
        # the router's migration follow: the session should already be
        # (or shortly be) live here — attach instead of re-generating
        attach = self.headers.get("X-Veles-Attach") == "1"
        try:
            result = None
            if sid:
                result = self._session_result(entry, sid, deadline,
                                              attach)
            if result is None:
                if attach:
                    self.send_json(410, {"error": "unknown session",
                                         "session_id": sid},
                                   headers=trace_hdr)
                    return 410
                result = entry.generate(
                    prompt, max_new,
                    timeout=self._result_timeout(deadline),
                    session_id=sid, deadline=deadline)
        except SchedulerOverflow as e:
            return self._shed(entry, "server overloaded: %s" % e,
                              trace_hdr=trace_hdr)
        except DeadlineExpired:
            return self._shed_expired(trace_hdr)
        except _FutureTimeout:
            if deadline_expired(deadline):
                return self._shed_expired(trace_hdr)
            log.warning("generate on %r exceeded request_timeout",
                        entry.name)
            self.send_json(500, {"error": "request timed out",
                                 "model": entry.name},
                           headers=trace_hdr)
            return 500
        except SchedulerClosed:
            # drain: in-flight sequences finish, NEW generate submits
            # shed with retryable backpressure (429 + Retry-After), so
            # a well-behaved client re-resolves to another replica
            return self._shed(entry, "server is draining", close=True,
                              trace_hdr=trace_hdr)
        except Exception:
            error_id = uuid.uuid4().hex[:12]
            log.exception("generate failed on model %r (error id %s)",
                          entry.name, error_id)
            self.send_json(500, {"error": "internal inference error",
                                 "model": entry.name, "id": error_id},
                           headers=trace_hdr)
            return 500
        if isinstance(result, dict) and result.get("migrated"):
            # the session moved while this request was held: answer a
            # redirect the fleet router follows to the new home (the
            # generated-so-far tokens rode along, so the target answers
            # the complete, bitwise-identical sequence)
            headers = dict(trace_hdr)
            headers["X-Veles-Migrated"] = str(
                result.get("session_id") or sid or "")
            if result.get("target"):
                headers["X-Veles-Session-Target"] = str(result["target"])
            self.send_json(307, dict(result, model=entry.name),
                           headers=headers)
            return 307
        self.send_json(200, dict(result, model=entry.name),
                       headers=trace_hdr)
        return 200

    def _session_result(self, entry, sid, deadline, attach):
        """The result of an EXISTING session ``sid`` — waits on the
        live future, returns a finished result immediately, or None
        when the id is unknown (caller submits fresh).  In attach mode
        (a migration follow) it polls briefly: the redirect can land a
        beat before the target's import commits."""
        scheduler = entry.scheduler
        if not hasattr(scheduler, "attach"):
            return None
        wait_until = time.monotonic() + (
            self.server_ref.attach_wait if attach else 0.0)
        while True:
            found = scheduler.attach(sid)
            if found is not None:
                break
            if time.monotonic() >= wait_until or \
                    deadline_expired(deadline):
                return None
            time.sleep(0.02)
        kind, value = found
        if kind == "finished":
            return value
        return value.result(self._result_timeout(deadline))


class InferenceServer:
    """Serve one or more models over HTTP with dynamic batching.

    ``models``: optional mapping/iterable of (name, model) registered at
    construction; more can be added later through ``registry``.
    Scheduler tuning (``max_batch``, ``queue_limit``, ``workers``,
    ``max_wait``) applies to models registered through this server.
    """

    def __init__(self, models=None, registry=None, port=0,
                 host="127.0.0.1", request_timeout=60.0,
                 enable_admin=False, model_resolver=None,
                 attach_wait=5.0, **scheduler_defaults):
        self.registry = registry or ModelRegistry(**scheduler_defaults)
        self.request_timeout = request_timeout
        # how long an X-Veles-Attach follow waits for a migrated
        # session's import to commit before answering 410
        self.attach_wait = float(attach_wait)
        self.started = time.time()
        self.draining = False
        # the hot-load endpoint is opt-in (fleet replicas turn it on);
        # model_resolver maps an admin "model" spec to something the
        # registry accepts (the fleet replica's sleep:/package resolver)
        self.enable_admin = bool(enable_admin)
        self.model_resolver = model_resolver
        if models:
            items = models.items() if hasattr(models, "items") else models
            for name, model in items:
                self.registry.add(name, model)
        handler = type("Handler", (_ServingHandler,),
                       {"server_ref": self,
                        # the keep-alive reaper follows the configured
                        # request timeout (was a hardcoded 60)
                        "timeout": max(float(request_timeout), 1.0)})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        # in-flight handler threads are daemons; the graceful-drain
        # guarantee is the scheduler's (finish every queued request),
        # not a join on keep-alive connections that may sit idle
        self._httpd.block_on_close = False
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="veles-tpu-serving")
        self._thread.start()

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def add_model(self, name, model, **kwargs):
        return self.registry.add(name, model, **kwargs)

    def stop(self, drain=True):
        """Graceful shutdown: mark draining, finish every admitted
        request/sequence, then stop the HTTP front end.

        The schedulers close FIRST (while the HTTP listener still
        answers), so a request arriving mid-drain gets a structured
        shed — 429 + Retry-After on the generate route, 503 on the
        classic route — instead of a connection reset; only after every
        queue drains does the listener go away."""
        self.draining = True
        self.registry.close(drain=drain)
        self._httpd.shutdown()
        self._httpd.server_close()
