"""Serving observability: registry-backed counters + latency quantiles.

The training side already streams Chrome-trace events through
``logger.EventLog`` (logger.py:86); the serving side plugs into the same
channel — every executed batch becomes a ``serving.batch`` span, every
shed request a ``serving.reject`` instant — so one Perfetto timeline
shows minibatches and inference batches side by side.

Counter state lives in the process-global
:class:`~veles_tpu.observability.registry.MetricsRegistry` (labelled by
model) instead of private attributes: the SAME numbers the serving
server's JSON ``/metrics`` reports are what Prometheus scrapes from the
status server's ``/metrics`` text endpoint, next to the training
profiler's series.  :class:`ServingMetrics` keeps only what the registry
cannot express — the exact-quantile latency window and the recent-rps
completion ring — plus per-instance baselines so ``snapshot()`` stays
scoped to one scheduler's lifetime even when several same-named models
have existed in the process.
"""

import collections
import threading
import time

from ..logger import events
from ..observability.registry import REGISTRY


class LatencyWindow:
    """Sliding-window latency reservoir with tail quantiles.

    A bounded deque of the most recent ``window`` observations: cheap to
    record under load (append + O(1) eviction), exact quantiles over the
    window when summarized (sort cost paid by the /metrics reader, not
    the request path).
    """

    def __init__(self, window=4096):
        self._samples = collections.deque(maxlen=int(window))
        self._lock = threading.Lock()

    def record(self, seconds):
        with self._lock:
            self._samples.append(float(seconds))

    @staticmethod
    def _quantile(ordered, q):
        if not ordered:
            return None
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]

    def summary(self):
        with self._lock:
            ordered = sorted(self._samples)
        if not ordered:
            return {"n": 0, "p50_ms": None, "p95_ms": None, "p99_ms": None}
        to_ms = lambda s: round(s * 1e3, 3)  # noqa: E731
        return {"n": len(ordered),
                "p50_ms": to_ms(self._quantile(ordered, 0.50)),
                "p95_ms": to_ms(self._quantile(ordered, 0.95)),
                "p99_ms": to_ms(self._quantile(ordered, 0.99)),
                "mean_ms": to_ms(sum(ordered) / len(ordered)),
                "max_ms": to_ms(ordered[-1])}


def _bind_counters(registry, model, spec):
    """Declare (idempotently) and label-bind one counter child per
    ``spec`` entry.  EVERY serving metrics class — request-granularity
    :class:`ServingMetrics` and token-level :class:`DecodeMetrics`
    alike — binds through here, so running both scheduler kinds in one
    process re-declares the same families instead of colliding, and a
    second same-named scheduler (hot swap) reuses the existing series.
    Returns ({key: child}, {key: construction-baseline value})."""
    children = {key: registry.counter(name, help, ("model",))
                .labels(model=model)
                for key, (name, help) in spec.items()}
    return children, {key: child.value for key, child in children.items()}


#: registry counter families shared by every ServingMetrics instance
_COUNTERS = {
    "requests": ("veles_serving_requests_total",
                 "Completed inference requests"),
    "rows": ("veles_serving_rows_total",
             "Sample rows served"),
    "failures": ("veles_serving_failures_total",
                 "Requests answered with an internal error"),
    "rejected": ("veles_serving_rejected_total",
                 "Requests shed by backpressure (HTTP 429)"),
    "batches": ("veles_serving_batches_total",
                "Executed dispatch batches"),
    "batch_rows": ("veles_serving_batch_rows_total",
                   "Real rows across executed batches"),
    "padded_rows": ("veles_serving_padded_rows_total",
                    "Padding rows added by power-of-two bucketing"),
    "expired": ("veles_serving_deadline_expired_total",
                "Requests shed because their end-to-end deadline "
                "passed before device time was spent (HTTP 504)"),
}


class ServingMetrics:
    """Aggregate serving counters for one model.

    Thread-safe; recorded from request threads and the dispatch worker,
    read by ``GET /metrics``.  Counter semantics:

    - ``requests`` / ``rows``: completed inferences (a request may carry
      several sample rows);
    - ``failures``: requests answered with an internal error;
    - ``rejected``: requests shed by backpressure (HTTP 429);
    - ``batches`` / ``batch_rows`` / ``padded_rows``: dispatch-side view —
      fill ratio = batch_rows / (batch_rows + padded_rows).
    """

    RATE_WINDOW = 2048  # completion timestamps kept for the recent-rps view

    def __init__(self, model="default", registry=None):
        self.model = model
        self.registry = registry or REGISTRY
        self.latency = LatencyWindow()
        # dispatch-side batch wall time: what a queued request actually
        # waits per batch ahead of it — the Retry-After estimator's input
        self.batch_latency = LatencyWindow(512)
        self._lock = threading.Lock()
        self._t0 = time.time()
        # baseline at construction: the registry series are process-
        # global and monotonic (Prometheus semantics); snapshot() is
        # per-instance, so it reads deltas from here
        self._c, self._base = _bind_counters(self.registry, model,
                                             _COUNTERS)
        self._h_latency = self.registry.histogram(
            "veles_serving_request_seconds",
            "End-to-end request latency", ("model",)).labels(model=model)
        # seconds counter (floats — kept out of the int _COUNTERS
        # surface): warmup cost per model; with a warm executable cache
        # a restart's total shrinks to deserialization time (~0)
        self._c_compile_s = self.registry.counter(
            "veles_serving_compile_seconds_total",
            "Wall seconds spent producing bucket executables "
            "(fresh compiles and cache loads)",
            ("model",)).labels(model=model)
        self._base_compile_s = self._c_compile_s.value
        # scrape-time gauges derived from the exact-quantile window and
        # the fill counters (refreshed via collect_metrics just before
        # every /metrics render — Prometheus quantile gauges would be
        # stale or request-path-expensive otherwise)
        self._g_quantile = self.registry.gauge(
            "veles_serving_latency_quantile_ms",
            "Exact latency quantiles over the recent sample window",
            ("model", "quantile"))
        self._g_fill = self.registry.gauge(
            "veles_serving_batch_fill_ratio",
            "Real rows / (real + padding) across executed batches",
            ("model",)).labels(model=model)
        self.registry.register_collector(self)
        self._completions = collections.deque(maxlen=self.RATE_WINDOW)

    def _count(self, key):
        return int(round(self._c[key].value - self._base[key]))

    def __getattr__(self, name):
        # the seed exposed counters as plain attributes; keep that
        # surface (metrics.requests et al.) over the registry state
        if name in _COUNTERS:
            return self._count(name)
        raise AttributeError(name)

    # -- request-side --------------------------------------------------------
    def record_request(self, rows, seconds, ok=True):
        self.latency.record(seconds)
        self._h_latency.observe(seconds)
        self._c["requests"].inc()
        self._c["rows"].inc(int(rows))
        if not ok:
            self._c["failures"].inc()
        with self._lock:
            self._completions.append(time.time())

    def record_reject(self):
        self._c["rejected"].inc()
        events.event("serving.reject", model=self.model)

    def record_expired(self):
        self._c["expired"].inc()
        events.event("serving.deadline_expired", model=self.model)

    def record_compile(self, seconds):
        """One bucket executable produced (compile or cache load)."""
        self._c_compile_s.inc(float(seconds))

    # -- dispatch-side -------------------------------------------------------
    def record_batch(self, bucket, rows, seconds, n_requests, links=None):
        """``links``: request span ids batched into this dispatch — the
        causal glue between per-request and per-batch spans in the
        merged trace."""
        self._c["batches"].inc()
        self._c["batch_rows"].inc(int(rows))
        self._c["padded_rows"].inc(int(bucket) - int(rows))
        self.batch_latency.record(seconds)
        extra = {"links": links} if links else {}
        events.span("serving.batch", seconds, model=self.model,
                    bucket=int(bucket), rows=int(rows),
                    requests=int(n_requests), **extra)

    def collect_metrics(self):
        """Refresh the derived gauges (called by the registry at scrape
        time, holding only a weak reference to this object)."""
        s = self.latency.summary()
        for q in ("p50", "p95", "p99"):
            value = s.get("%s_ms" % q)
            if value is not None:
                self._g_quantile.labels(model=self.model,
                                        quantile=q).set(value)
        filled = self._c["batch_rows"].value
        padded = self._c["padded_rows"].value
        if filled + padded:
            self._g_fill.set(filled / (filled + padded))

    # -- reader --------------------------------------------------------------
    def snapshot(self):
        now = time.time()
        with self._lock:
            completions = list(self._completions)
        counters = {key: self._count(key) for key in _COUNTERS}
        uptime = max(now - self._t0, 1e-9)
        recent_rps = None
        if len(completions) >= 2:
            span = completions[-1] - completions[0]
            if span > 0:
                recent_rps = round((len(completions) - 1) / span, 1)
        filled = counters["batch_rows"]
        padded = counters["padded_rows"]
        out = dict(counters)
        out.update({
            "compile_seconds": round(
                self._c_compile_s.value - self._base_compile_s, 4),
            "uptime_s": round(uptime, 1),
            "lifetime_rps": round(counters["requests"] / uptime, 2),
            "recent_rps": recent_rps,
            "batch_fill": round(filled / (filled + padded), 4)
            if filled + padded else None,
            "rows_per_batch": round(filled / counters["batches"], 2)
            if counters["batches"] else None,
            "latency": self.latency.summary(),
            "batch_latency": self.batch_latency.summary(),
        })
        return out


#: registry counter families shared by every DecodeMetrics instance
_DECODE_COUNTERS = {
    "sequences": ("veles_serving_decode_sequences_total",
                  "Sequences admitted to the decode scheduler"),
    "completed": ("veles_serving_decode_completed_total",
                  "Sequences that finished generation"),
    "failed": ("veles_serving_decode_failed_total",
               "Sequences failed or cancelled before finishing"),
    "rejected": ("veles_serving_decode_rejected_total",
                 "Generate requests shed by backpressure (HTTP 429)"),
    "tokens": ("veles_serving_decode_tokens_total",
               "Tokens generated (prefill first-tokens included)"),
    "prefill_tokens": ("veles_serving_decode_prefill_tokens_total",
                       "Prompt tokens processed by prefill"),
    "steps": ("veles_serving_decode_steps_total",
              "Decode-step executions"),
    "step_rows": ("veles_serving_decode_step_rows_total",
                  "Active rows across decode steps (sum)"),
    "idle_rows": ("veles_serving_decode_idle_rows_total",
                  "Padding rows across decode steps (sum) — the "
                  "utilization the request-granularity path wastes"),
    "expired": ("veles_serving_decode_deadline_expired_total",
                "Generate requests shed because their deadline passed "
                "before prefill (HTTP 504)"),
    "migrated_out": ("veles_serving_decode_migrated_out_total",
                     "Live sessions exported to a peer or spilled"),
    "migrated_in": ("veles_serving_decode_migrated_in_total",
                    "Live sessions imported mid-generation"),
    "prefix_hits": ("veles_serving_kv_prefix_hits_total",
                    "Admits that attached to >= 1 already-resident "
                    "KV block (prefix cache hit)"),
    "dedup_blocks": ("veles_serving_kv_blocks_dedup",
                     "KV blocks attached already-resident at admit "
                     "instead of re-prefilled (cumulative)"),
    "chunks": ("veles_serving_prefill_chunks_total",
               "Prefill chunk executions (the one-executable chunked "
               "path interleaved with decode steps)"),
    "draft_tokens": ("veles_serving_spec_draft_tokens_total",
                     "Draft tokens proposed by the speculative "
                     "drafter"),
    "accepted_tokens": ("veles_serving_spec_accepted_tokens_total",
                        "Draft tokens the verify pass accepted"),
    "rejected_tokens": ("veles_serving_spec_rejected_tokens_total",
                        "Draft tokens the verify pass rejected "
                        "(their KV writes are rolled back)"),
    "verify_steps": ("veles_serving_spec_verify_steps_total",
                     "Speculative verify-pass executions"),
}

#: draft/accept outcomes kept for the per-window acceptance-rate gauge
_ACCEPT_WINDOW = 1024

#: resident-prefix fraction bands of the split TTFT histogram: how much
#: of the prompt was already cached when the sequence was admitted
_PREFIX_BANDS = ((0.5, "major"), (0.0, "minor"))


def _prefix_band(resident):
    if not resident:
        return "none"
    for floor, band in _PREFIX_BANDS:
        if resident >= floor:
            return band
    return "none"


class DecodeMetrics:
    """Per-model counters for the token-level decode scheduler.

    Same construction/baseline discipline as :class:`ServingMetrics`
    (shared :func:`_bind_counters` declaration path — both scheduler
    kinds can run in one process, or hot-swap under one name, without
    double-declaring a registry family), plus the decode-shaped
    signals: per-step latency quantiles (≈ inter-token latency),
    time-to-first-token, batch-row utilization, and KV-block occupancy.
    """

    RATE_WINDOW = 4096  # (timestamp, tokens) pairs for the recent view

    def __init__(self, model="default", registry=None):
        self.model = model
        self.registry = registry or REGISTRY
        self.step_latency = LatencyWindow()
        self.ttft = LatencyWindow()
        self._lock = threading.Lock()
        self._t0 = time.time()
        self._c, self._base = _bind_counters(self.registry, model,
                                             _DECODE_COUNTERS)
        self._h_step = self.registry.histogram(
            "veles_serving_decode_step_seconds",
            "Decode step wall time (≈ per-token latency under load)",
            ("model",)).labels(model=model)
        self._h_ttft = self.registry.histogram(
            "veles_serving_decode_ttft_seconds",
            "Submit-to-first-token latency (queue + prefill)",
            ("model",)).labels(model=model)
        # TTFT split by how much of the prompt was already resident at
        # admit — the per-band family the prefix-reuse win shows up in
        # (bands: none / minor (< 50%) / major (>= 50%))
        self._h_ttft_prefix = self.registry.histogram(
            "veles_serving_decode_ttft_by_prefix_seconds",
            "Submit-to-first-token latency split by resident-prefix "
            "fraction at admit", ("model", "resident"))
        # tiered-KV-cache families (veles_tpu/kvtier): demote/promote
        # flow per tier, byte occupancy gauges, and TTFT banded by the
        # deepest tier that served the admit's longest prefix hit
        # (hbm / host / disk / none) — the series the zero-re-prefill
        # win is visible in
        self._c_tier_demote = self.registry.counter(
            "veles_kvtier_demotions_total",
            "KV chain blocks demoted into the tier (HBM evictions land "
            "in host RAM, host-RAM overflow cascades to disk)",
            ("model", "tier"))
        self._c_tier_promote = self.registry.counter(
            "veles_kvtier_promotions_total",
            "KV chain blocks promoted out of the tier on readmit",
            ("model", "tier"))
        self._c_disk_readmit = self.registry.counter(
            "veles_kvtier_disk_readmits_total",
            "Chain blocks readmitted into HBM from the disk tier "
            "(zero re-prefill instead of recompute)",
            ("model",)).labels(model=model)
        self._g_tier_bytes = self.registry.gauge(
            "veles_kvtier_bytes",
            "Byte occupancy of the KV tier", ("model", "tier"))
        self._h_ttft_tier = self.registry.histogram(
            "veles_serving_decode_ttft_by_tier_seconds",
            "Submit-to-first-token latency split by the deepest KV "
            "tier serving the admit's longest prefix hit",
            ("model", "tier"))
        self._tier_children = {
            (kind, tier): family.labels(model=model, tier=tier)
            for kind, family in (("demotions", self._c_tier_demote),
                                 ("promotions", self._c_tier_promote))
            for tier in ("host", "disk")}
        self._tier_base = {key: child.value
                           for key, child in self._tier_children.items()}
        self._base_disk_readmit = self._c_disk_readmit.value
        self._g_chunk_queue = self.registry.gauge(
            "veles_serving_prefill_chunk_queue",
            "Sequences currently mid-chunked-prefill",
            ("model",)).labels(model=model)
        self._g_active = self.registry.gauge(
            "veles_serving_decode_active_rows",
            "Sequences currently decoding", ("model",)).labels(
                model=model)
        self._g_kv = self.registry.gauge(
            "veles_serving_kv_blocks_used_ratio",
            "Live KV blocks / allocatable blocks", ("model",)).labels(
                model=model)
        # quantized-serving gauges: byte footprint of the live blocks
        # (int8 pools shrink it ~4x at the same block count — THE
        # concurrent-sessions-at-fixed-HBM win) and the pool dtype as
        # an info gauge so dashboards can slice tok/s by precision
        self._g_kv_bytes = self.registry.gauge(
            "veles_decode_kv_bytes_resident",
            "Device bytes held by resident (live + prefix-cached) KV "
            "blocks; quantized pools shrink this at the same block "
            "count",
            ("model",)).labels(model=model)
        self._g_kv_dtype = self.registry.gauge(
            "veles_decode_kv_dtype_info",
            "KV-pool element dtype serving this model (info gauge: "
            "value 1 on the active dtype label)",
            ("model", "kv_dtype"))
        self._g_quantile = self.registry.gauge(
            "veles_serving_decode_step_quantile_ms",
            "Exact decode-step quantiles over the recent window",
            ("model", "quantile"))
        # speculation series: verify-batch-size histogram + a windowed
        # acceptance-rate gauge (refreshed at scrape time from the
        # recent (drafted, accepted) pairs — a lifetime ratio would
        # hide acceptance drifting with the workload)
        self._h_verify = self.registry.histogram(
            "veles_serving_spec_verify_batch_tokens",
            "Tokens per speculative verify pass (rows x (depth + 1))",
            ("model",)).labels(model=model)
        self._g_acceptance = self.registry.gauge(
            "veles_serving_spec_acceptance_rate",
            "Accepted / drafted tokens over the recent window",
            ("model",)).labels(model=model)
        self._acceptance = collections.deque(maxlen=_ACCEPT_WINDOW)
        self.registry.register_collector(self)
        self._emissions = collections.deque(maxlen=self.RATE_WINDOW)

    def _count(self, key):
        return int(round(self._c[key].value - self._base[key]))

    def __getattr__(self, name):
        if name in _DECODE_COUNTERS:
            return self._count(name)
        raise AttributeError(name)

    # -- recording (scheduler worker thread) ---------------------------------
    def record_admit(self, prompt_tokens, prefilled=None):
        """``prefilled``: prompt tokens the prefill actually has to
        process (prompt minus the resident prefix); defaults to the
        whole prompt."""
        self._c["sequences"].inc()
        self._c["prefill_tokens"].inc(int(
            prompt_tokens if prefilled is None else prefilled))

    def record_prefix(self, matched_blocks):
        """One admission's prefix-reuse outcome: 0 matched blocks is a
        miss, anything else a hit of that many dedup'd blocks."""
        if matched_blocks:
            self._c["prefix_hits"].inc()
            self._c["dedup_blocks"].inc(int(matched_blocks))

    def record_chunk(self):
        self._c["chunks"].inc()

    def set_chunk_queue(self, depth):
        self._g_chunk_queue.set(int(depth))

    def record_first_token(self, seconds, resident=None, tier=None):
        """TTFT for one sequence: submit -> prefill's first token.
        ``resident``: fraction of the prompt already cached at admit
        (None/0 when prefix caching is off or nothing matched).
        ``tier``: deepest KV tier the admit's longest prefix hit came
        from ('hbm' | 'host' | 'disk'); defaults from ``resident``."""
        self.ttft.record(seconds)
        self._h_ttft.observe(seconds)
        self._h_ttft_prefix.labels(
            model=self.model,
            resident=_prefix_band(resident)).observe(seconds)
        if tier is None:
            tier = "hbm" if resident else "none"
        self._h_ttft_tier.labels(model=self.model,
                                 tier=tier).observe(seconds)
        self._c["tokens"].inc()
        with self._lock:
            self._emissions.append((time.time(), 1))

    # -- tiered KV cache (veles_tpu/kvtier observer surface) -----------------
    def record_tier_demotion(self, tier, nbytes=0):
        self._tier_children[("demotions", tier)].inc()

    def record_tier_promotion(self, tier, nbytes=0):
        self._tier_children[("promotions", tier)].inc()

    def record_disk_readmit(self):
        self._c_disk_readmit.inc()

    def set_tier_bytes(self, host=0, disk=0):
        self._g_tier_bytes.labels(model=self.model,
                                  tier="host").set(int(host))
        self._g_tier_bytes.labels(model=self.model,
                                  tier="disk").set(int(disk))

    def _tier_count(self, kind, tier):
        key = (kind, tier)
        return int(round(self._tier_children[key].value
                         - self._tier_base[key]))

    def record_step(self, active_rows, max_rows, seconds):
        self.step_latency.record(seconds)
        self._h_step.observe(seconds)
        self._c["steps"].inc()
        self._c["step_rows"].inc(int(active_rows))
        self._c["idle_rows"].inc(int(max_rows) - int(active_rows))
        self._c["tokens"].inc(int(active_rows))
        with self._lock:
            self._emissions.append((time.time(), int(active_rows)))
        events.span("serving.decode", seconds, model=self.model,
                    rows=int(active_rows), max_rows=int(max_rows))

    def record_extra_tokens(self, n):
        """Tokens emitted beyond one-per-row in a speculative
        iteration (accepted drafts) — keeps the tokens counter and the
        recent-tok/s window honest about the speculation win."""
        self._c["tokens"].inc(int(n))
        with self._lock:
            self._emissions.append((time.time(), int(n)))

    def record_draft(self, rows, depth, seconds):
        """One drafter execution: ``rows`` live rows each proposed
        ``depth`` tokens."""
        self._c["draft_tokens"].inc(int(rows) * int(depth))
        events.span("serving.draft", seconds, model=self.model,
                    rows=int(rows), depth=int(depth))

    def record_verify(self, rows, span, accepted, rejected, seconds):
        """One verify pass over ``rows`` live rows x ``span`` fed
        positions; ``accepted``/``rejected`` are the batch-total draft
        outcomes the host-side accept step decided."""
        self._c["verify_steps"].inc()
        self._c["accepted_tokens"].inc(int(accepted))
        self._c["rejected_tokens"].inc(int(rejected))
        self._h_verify.observe(int(rows) * int(span))
        with self._lock:
            self._acceptance.append((int(accepted) + int(rejected),
                                     int(accepted)))
        events.span("serving.verify", seconds, model=self.model,
                    rows=int(rows), span=int(span),
                    accepted=int(accepted), rejected=int(rejected))

    def acceptance_rate(self):
        """Accepted / drafted over the recent window (None before any
        speculative step)."""
        with self._lock:
            pairs = list(self._acceptance)
        drafted = sum(d for d, _ in pairs)
        if not drafted:
            return None
        return sum(a for _, a in pairs) / drafted

    def record_complete(self, generated, ok=True):
        self._c["completed" if ok else "failed"].inc()

    def record_reject(self):
        self._c["rejected"].inc()
        events.event("serving.decode_reject", model=self.model)

    def record_expired(self):
        self._c["expired"].inc()
        events.event("serving.decode_deadline_expired", model=self.model)

    def record_migrate(self, n, direction="out"):
        self._c["migrated_out" if direction == "out"
                else "migrated_in"].inc(int(n))

    def set_occupancy(self, active_rows, kv_ratio):
        self._g_active.set(int(active_rows))
        self._g_kv.set(float(kv_ratio))

    def set_kv_bytes(self, nbytes):
        self._g_kv_bytes.set(int(nbytes))

    def set_kv_dtype(self, kv_dtype):
        self._g_kv_dtype.labels(model=self.model,
                                kv_dtype=str(kv_dtype)).set(1)

    def collect_metrics(self):
        """Scrape-time refresh of the derived quantile gauges."""
        s = self.step_latency.summary()
        for q in ("p50", "p95", "p99"):
            value = s.get("%s_ms" % q)
            if value is not None:
                self._g_quantile.labels(model=self.model,
                                        quantile=q).set(value)
        rate = self.acceptance_rate()
        if rate is not None:
            self._g_acceptance.set(rate)

    # -- reader --------------------------------------------------------------
    def snapshot(self):
        now = time.time()
        with self._lock:
            emissions = list(self._emissions)
        counters = {key: self._count(key) for key in _DECODE_COUNTERS}
        uptime = max(now - self._t0, 1e-9)
        recent_tok_s = None
        if len(emissions) >= 2:
            span = emissions[-1][0] - emissions[0][0]
            if span > 0:
                recent_tok_s = round(
                    sum(n for _, n in emissions[1:]) / span, 1)
        rows = counters["step_rows"] + counters["idle_rows"]
        out = dict(counters)
        out.update({
            "uptime_s": round(uptime, 1),
            "lifetime_tok_s": round(counters["tokens"] / uptime, 2),
            "recent_tok_s": recent_tok_s,
            "row_fill": round(counters["step_rows"] / rows, 4)
            if rows else None,
            "step_latency": self.step_latency.summary(),
            "ttft": self.ttft.summary(),
        })
        rate = self.acceptance_rate()
        if rate is not None:
            out["acceptance_rate"] = round(rate, 4)
        disk_readmits = int(round(self._c_disk_readmit.value
                                  - self._base_disk_readmit))
        tiers = {"demotions": {t: self._tier_count("demotions", t)
                               for t in ("host", "disk")},
                 "promotions": {t: self._tier_count("promotions", t)
                                for t in ("host", "disk")},
                 "disk_readmits": disk_readmits}
        if disk_readmits or any(v for d in (tiers["demotions"],
                                            tiers["promotions"])
                                for v in d.values()):
            out["kvtier"] = tiers
        return out
