"""Serving observability: per-model latency histograms and throughput.

The training side already streams Chrome-trace events through
``logger.EventLog`` (logger.py:86); the serving side plugs into the same
channel — every executed batch becomes a ``serving.batch`` span, every
shed request a ``serving.reject`` instant — so one Perfetto timeline
shows minibatches and inference batches side by side.  On top of that,
:class:`ServingMetrics` keeps the aggregate numbers a load balancer or
dashboard polls from ``GET /metrics``: request/row counts, p50/p95/p99
latency over a sliding window, queue depth, batch-fill ratio (real rows
vs padded rows — the price of power-of-two bucketing), and req/s both
lifetime and over the recent window.
"""

import collections
import threading
import time

from ..logger import events


class LatencyWindow:
    """Sliding-window latency reservoir with tail quantiles.

    A bounded deque of the most recent ``window`` observations: cheap to
    record under load (append + O(1) eviction), exact quantiles over the
    window when summarized (sort cost paid by the /metrics reader, not
    the request path).
    """

    def __init__(self, window=4096):
        self._samples = collections.deque(maxlen=int(window))
        self._lock = threading.Lock()

    def record(self, seconds):
        with self._lock:
            self._samples.append(float(seconds))

    @staticmethod
    def _quantile(ordered, q):
        if not ordered:
            return None
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]

    def summary(self):
        with self._lock:
            ordered = sorted(self._samples)
        if not ordered:
            return {"n": 0, "p50_ms": None, "p95_ms": None, "p99_ms": None}
        to_ms = lambda s: round(s * 1e3, 3)  # noqa: E731
        return {"n": len(ordered),
                "p50_ms": to_ms(self._quantile(ordered, 0.50)),
                "p95_ms": to_ms(self._quantile(ordered, 0.95)),
                "p99_ms": to_ms(self._quantile(ordered, 0.99)),
                "mean_ms": to_ms(sum(ordered) / len(ordered)),
                "max_ms": to_ms(ordered[-1])}


class ServingMetrics:
    """Aggregate serving counters for one model.

    Thread-safe; recorded from request threads and the dispatch worker,
    read by ``GET /metrics``.  Counter semantics:

    - ``requests`` / ``rows``: completed inferences (a request may carry
      several sample rows);
    - ``failures``: requests answered with an internal error;
    - ``rejected``: requests shed by backpressure (HTTP 429);
    - ``batches`` / ``batch_rows`` / ``padded_rows``: dispatch-side view —
      fill ratio = batch_rows / (batch_rows + padded_rows).
    """

    RATE_WINDOW = 2048  # completion timestamps kept for the recent-rps view

    def __init__(self, model="default"):
        self.model = model
        self.latency = LatencyWindow()
        self._lock = threading.Lock()
        self._t0 = time.time()
        self.requests = 0
        self.rows = 0
        self.failures = 0
        self.rejected = 0
        self.batches = 0
        self.batch_rows = 0
        self.padded_rows = 0
        self._completions = collections.deque(maxlen=self.RATE_WINDOW)

    # -- request-side --------------------------------------------------------
    def record_request(self, rows, seconds, ok=True):
        self.latency.record(seconds)
        with self._lock:
            self.requests += 1
            self.rows += int(rows)
            if not ok:
                self.failures += 1
            self._completions.append(time.time())

    def record_reject(self):
        with self._lock:
            self.rejected += 1
        events.event("serving.reject", model=self.model)

    # -- dispatch-side -------------------------------------------------------
    def record_batch(self, bucket, rows, seconds, n_requests):
        with self._lock:
            self.batches += 1
            self.batch_rows += int(rows)
            self.padded_rows += int(bucket) - int(rows)
        events.span("serving.batch", seconds, model=self.model,
                    bucket=int(bucket), rows=int(rows),
                    requests=int(n_requests))

    # -- reader --------------------------------------------------------------
    def snapshot(self):
        now = time.time()
        with self._lock:
            completions = list(self._completions)
            counters = {"requests": self.requests, "rows": self.rows,
                        "failures": self.failures, "rejected": self.rejected,
                        "batches": self.batches,
                        "batch_rows": self.batch_rows,
                        "padded_rows": self.padded_rows}
        uptime = max(now - self._t0, 1e-9)
        recent_rps = None
        if len(completions) >= 2:
            span = completions[-1] - completions[0]
            if span > 0:
                recent_rps = round((len(completions) - 1) / span, 1)
        filled = counters["batch_rows"]
        padded = counters["padded_rows"]
        out = dict(counters)
        out.update({
            "uptime_s": round(uptime, 1),
            "lifetime_rps": round(counters["requests"] / uptime, 2),
            "recent_rps": recent_rps,
            "batch_fill": round(filled / (filled + padded), 4)
            if filled + padded else None,
            "rows_per_batch": round(filled / counters["batches"], 2)
            if counters["batches"] else None,
            "latency": self.latency.summary(),
        })
        return out
