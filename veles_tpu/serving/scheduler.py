"""Dynamic micro-batching over shape-bucketed XLA executables.

The seed serving path (restful_api.py) paid one XLA dispatch — and, for
exported packages, one ``jax.export`` call-wrapper rebuild — per HTTP
request.  This module amortizes both the way the TPU-inference
literature does (Ragged Paged Attention, PAPERS.md: pad to buckets,
serve every bucket from one compiled program; TVM, PAPERS.md:
ahead-of-time compiled end-to-end serving):

- concurrent requests are concatenated into one batch and **padded to
  the next power-of-two bucket**, so the steady state only ever sees
  ``log2(max_batch)+1`` distinct shapes;
- every bucket is **AOT-compiled once at startup**
  (``jax.jit(...).lower(...).compile()``) — warm executables, zero
  recompilation after warmup, asserted via :meth:`BucketScheduler.stats`;
- batching is **continuous** (vLLM-style): a dispatch worker drains
  whatever is queued and executes immediately — while a batch runs, the
  next one accumulates; no fixed batching window adds latency;
- backpressure is a bounded count of outstanding requests: when full,
  :meth:`submit` raises :class:`SchedulerOverflow` and the server
  answers 429 instead of letting the queue grow without bound.

Works on any JAX backend; on the tunneled TPU the per-dispatch RTT
(~14 ms, docs/PERF.md) makes batching amortization strictly larger than
the CPU numbers recorded by tools/serve_bench.py.
"""

import queue
import threading
import time
from concurrent.futures import Future

import numpy

from ..compilecache import WarmupManifest, default_cache
from ..logger import events
from ..observability import trace as _trace
from ..observability.flight import RECORDER as _flight
from .metrics import ServingMetrics


class SchedulerOverflow(RuntimeError):
    """The bounded request queue is full — shed load (HTTP 429)."""


class SchedulerClosed(RuntimeError):
    """The scheduler is draining or stopped — no new requests."""


class DeadlineExpired(RuntimeError):
    """The request's end-to-end deadline passed before it reached the
    device — shed (HTTP 504) instead of spending batch rows on an
    answer nobody is waiting for."""


def deadline_expired(deadline, now=None):
    """True when an absolute ``time.monotonic()`` deadline has passed
    (None = no deadline)."""
    if deadline is None:
        return False
    return (time.monotonic() if now is None else now) >= deadline


def bucket_sizes(max_batch):
    """The power-of-two bucket ladder: 1, 2, 4, ... max_batch."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    sizes, b = [], 1
    while b < max_batch:
        sizes.append(b)
        b <<= 1
    sizes.append(int(max_batch))  # top bucket even when not a power of two
    return sizes


# -- model adapters ----------------------------------------------------------
# One scheduler serves any of: a live StandardWorkflow (its forward
# chain), an exported package (PackageLoader / path to the zip), or an
# opaque python callable (tests, custom runtimes).


class JaxModel:
    """A pure ``fn(params, x)`` compiled per bucket via jax.jit AOT."""

    def __init__(self, fn, params, sample_shape):
        import jax
        self._jit = jax.jit(fn)
        # params live on device once; per-dispatch host->device traffic
        # is the padded batch only
        self._params = jax.device_put(params)
        self.sample_shape = tuple(int(d) for d in sample_shape)

    def compile(self, bucket, cache=None):
        """-> (runner, cache_hit): the bucket's executable, off the
        persistent cache when one is active (hit True/False) or a plain
        AOT compile (hit None)."""
        import jax
        struct = jax.ShapeDtypeStruct((int(bucket),) + self.sample_shape,
                                      numpy.float32)
        hit = None
        if cache is not None:
            compiled, hit = cache.get_or_compile(
                self._jit, self._params, struct,
                name="serving.bucket%d" % int(bucket))
        else:
            compiled = self._jit.lower(self._params, struct).compile()
        params = self._params
        return (lambda xs: compiled(params, xs)), hit

    def jit_cache_size(self):
        """Eager-jit cache entries — stays 0 when every call went
        through a warm AOT executable (the zero-recompile assertion)."""
        try:
            return self._jit._cache_size()
        except Exception:
            return None


class OpaqueModel:
    """An opaque callable ``fn(x) -> y``; no compilation to manage."""

    def __init__(self, fn, sample_shape=None):
        self._fn = fn
        self.sample_shape = (tuple(int(d) for d in sample_shape)
                             if sample_shape is not None else None)

    def compile(self, bucket, cache=None):
        return self._fn, None

    def jit_cache_size(self):
        return None


def adapt_model(model, sample_shape=None):
    """model → adapter with ``compile(bucket)`` + ``sample_shape``.

    Accepts a package path, a PackageLoader, anything with a non-empty
    ``forwards`` chain (StandardWorkflow), or a bare callable.
    """
    if isinstance(model, (JaxModel, OpaqueModel)):
        return model                # pre-built adapter (tests, tools)
    if isinstance(model, str):
        from ..export.loader import PackageLoader
        model = PackageLoader(model)
    if hasattr(model, "deserialize") and hasattr(model, "unit_params"):
        exported = model.deserialize()
        meta = model.model_metadata
        if meta is None:
            raise ValueError("package has no model.json metadata")
        return JaxModel(lambda p, x: exported.call(p, x),
                        model.unit_params(),
                        meta["input"]["sample_shape"])
    forwards = getattr(model, "forwards", None)
    if forwards:
        from ..export.model import forward_fn
        return JaxModel(forward_fn(forwards),
                        [f.params for f in forwards],
                        forwards[0].input.shape[1:])
    if callable(model):
        return OpaqueModel(model, sample_shape)
    raise TypeError("cannot serve %r: want a package path, PackageLoader, "
                    "a workflow with forwards, or a callable" % (model,))


class _Pending:
    __slots__ = ("x", "n", "future", "enqueued", "trace", "deadline")

    def __init__(self, x, deadline=None):
        self.x = x
        self.n = int(x.shape[0])
        self.future = Future()
        self.enqueued = time.perf_counter()
        # the submitting thread's trace context (the HTTP handler's
        # request span): the dispatch worker links the batch span back
        # to every request it served
        self.trace = _trace.current()
        # absolute time.monotonic() end-to-end deadline (None = none):
        # checked at admission AND again just before batching, so work
        # that expired in the queue never reaches the executable
        self.deadline = deadline


_STOP = object()


class BucketScheduler:
    """Collect concurrent requests into padded power-of-two batches.

    ``workers`` dispatch threads pull from one queue; each drains what
    is available (continuous batching), pads to the smallest bucket
    that fits, and runs that bucket's warm executable.  ``queue_limit``
    bounds *outstanding* requests (queued + in a forming batch); beyond
    it :meth:`submit` raises :class:`SchedulerOverflow`.
    """

    def __init__(self, model, max_batch=64, queue_limit=256, workers=1,
                 max_wait=0.0, warmup=True, name="default",
                 metrics=None, sample_shape=None, cache=None,
                 manifest=None, background_warmup=None, buckets=None):
        from ..config import root
        self.name = name
        self.max_batch = int(max_batch)
        self.queue_limit = int(queue_limit)
        self.max_wait = float(max_wait)
        self.metrics = metrics or ServingMetrics(name)
        self._adapter = adapt_model(model, sample_shape)
        self.sample_shape = self._adapter.sample_shape
        # the bucket ladder is a TUNABLE SITE (serving.bucket_ladder):
        # an explicit ``buckets`` list pins it; otherwise a tuning
        # record for this max_batch picks the measured shape, and the
        # tuner-off fallback ("pow2") is byte-identical to the old
        # hard-wired bucket_sizes() ladder
        if buckets is not None:
            self.buckets = sorted({int(b) for b in buckets})
            if self.buckets[-1] != self.max_batch or self.buckets[0] < 1:
                raise ValueError(
                    "buckets %r must be >= 1 and end at max_batch %d"
                    % (buckets, self.max_batch))
            self.bucket_config = {"shape": "explicit"}
            self.config_source = "explicit"
        else:
            from ..autotune import dispatch as _autotune
            from ..autotune import space as _space
            cfg, src = _autotune.resolve(
                "serving.bucket_ladder", "mb%d" % self.max_batch,
                default={"shape": "pow2"})
            self.buckets = _space.ladder(cfg["shape"], self.max_batch)
            self.bucket_config = dict(cfg)
            self.config_source = src
        self._executables = {}
        self._compiles = 0              # fresh XLA compiles only
        self._cache_hits = 0            # executables loaded off disk
        self._compile_seconds = 0.0
        self._warmup_compiles = 0
        self._compile_lock = threading.Lock()
        # the persistent executable cache + warmup manifest (compilecache
        # subsystem): None kwargs resolve from root.common.compile_cache
        # — no configured dir means both stay off (seed behavior)
        if cache is None:
            cache = default_cache()
        self._cache = cache or None     # cache=False forces OFF
        if manifest is None:
            self._manifest = (self._cache.manifest
                              if self._cache is not None else None)
        elif isinstance(manifest, str):
            self._manifest = WarmupManifest(manifest)
        else:
            self._manifest = manifest or None
        if self._manifest is not None and self.config_source == "tuned":
            # ship the winner inside the warmup manifest: a warm
            # restart reads the SAME ladder before compiling anything,
            # so tuned geometry never causes a fresh compile
            self._manifest.record_config(
                self.name, "serving.bucket_ladder",
                dict(self.bucket_config, buckets=list(self.buckets)))
        if background_warmup is None:
            background_warmup = bool(root.common.compile_cache.get(
                "background_warmup", False))
        self._background_warmup = bool(background_warmup)
        self._warmup_thread = None
        self._queue = queue.Queue()     # unbounded; bound enforced below
        self._depth = 0                 # outstanding requests
        self._depth_lock = threading.Lock()
        self._closed = False
        if warmup:
            self.warmup()
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name="veles-serve-%s-%d" % (name, i))
            for i in range(max(int(workers), 1))]
        for t in self._workers:
            t.start()

    # -- compilation ---------------------------------------------------------
    def _warmup_order(self):
        """The ladder, warmup-manifest buckets first: a restart warms
        the shapes real traffic used before the speculative tail."""
        order = list(self.buckets)
        if self._manifest is None:
            return order
        first = [b for b in self._manifest.buckets(self.name)
                 if b in order]
        return first + [b for b in order if b not in first]

    def warmup(self, background=None):
        """Compile every bucket up front so steady state never compiles.

        Buckets the model cannot take (a static-batch package artifact)
        are dropped from the ladder instead of failing the whole model;
        at least one bucket must survive.  With ``background`` (default:
        the ``background_warmup`` knob) the tail of the ladder compiles
        on a daemon thread after the first usable bucket, so a server
        answers its first warm bucket before the tail finishes — on a
        warm cache the whole ladder is deserialization-fast anyway.
        """
        if background is None:
            background = self._background_warmup
        pending = self._warmup_order()
        usable = []
        while pending:                 # sync until one bucket works
            b = pending.pop(0)
            if self._warm_one(b):
                usable.append(b)
                break
        if not usable:
            raise ValueError(
                "model %r compiled for no bucket size" % self.name)
        if background and pending:
            self.buckets = sorted(usable + pending)
            self.max_batch = self.buckets[-1]
            self._warmup_compiles = self._compiles
            self._warmup_thread = threading.Thread(
                target=self._warmup_tail, args=(pending,), daemon=True,
                name="veles-serve-%s-warmup" % self.name)
            self._warmup_thread.start()
            return
        for b in pending:
            if self._warm_one(b):
                usable.append(b)
        self.buckets = sorted(usable)
        self.max_batch = self.buckets[-1]
        self._warmup_compiles = self._compiles

    def _warm_one(self, bucket):
        try:
            self._get_executable(bucket)
            return True
        except Exception as exc:  # noqa: BLE001 — drop, don't fail all
            events.event("serving.warmup_skip", model=self.name,
                         bucket=bucket, error=str(exc)[:200])
            return False

    def _warmup_tail(self, pending):
        """Background tail: compile the rest of the ladder, pruning
        buckets the model rejects; tail compiles count as warmup."""
        for b in pending:
            if self._closed:
                return
            ok = self._warm_one(b)
            with self._compile_lock:
                if not ok:
                    self.buckets = [x for x in self.buckets if x != b]
                    self.max_batch = self.buckets[-1]
                self._warmup_compiles = self._compiles

    def _get_executable(self, bucket):
        run = self._executables.get(bucket)
        if run is not None:
            return run
        with self._compile_lock:
            run = self._executables.get(bucket)
            if run is None:
                t0 = time.perf_counter()
                run, hit = self._adapter.compile(bucket,
                                                 cache=self._cache)
                dt = time.perf_counter() - t0
                if hit:
                    self._cache_hits += 1
                else:
                    self._compiles += 1
                self._compile_seconds += dt
                self.metrics.record_compile(dt)
                self._executables[bucket] = run
                events.span("serving.compile", dt, model=self.name,
                            bucket=int(bucket),
                            cache_hit=bool(hit) if hit is not None
                            else None)
                if self._manifest is not None:
                    self._manifest.record(self.name, bucket,
                                          self.sample_shape)
        return run

    def _bucket_for(self, rows):
        for b in self.buckets:
            if b >= rows:
                return b
        return self.buckets[-1]

    # -- request side --------------------------------------------------------
    def validate(self, x):
        """Shape-check a request batch; raises ValueError (client error)."""
        if x.ndim < 2:
            raise ValueError("input must be a batch of samples")
        if self.sample_shape is not None and \
                tuple(x.shape[1:]) != self.sample_shape:
            raise ValueError(
                "sample shape %s does not match the model's %s"
                % (list(x.shape[1:]), list(self.sample_shape)))

    def submit(self, x, deadline=None):
        """Enqueue one request batch (≤ max_batch rows) → Future of the
        output rows.  Raises SchedulerOverflow / SchedulerClosed /
        DeadlineExpired / ValueError (bad shape)."""
        x = numpy.ascontiguousarray(x, numpy.float32)
        self.validate(x)
        if x.shape[0] > self.max_batch:
            raise ValueError("request of %d rows exceeds max_batch=%d "
                             "(use infer(), which chunks)"
                             % (x.shape[0], self.max_batch))
        return self._enqueue(x, deadline)

    def _enqueue(self, x, deadline=None):
        """The validated hot path: bound check, depth accounting, queue."""
        if self._closed:
            raise SchedulerClosed("scheduler %r is shut down" % self.name)
        if deadline_expired(deadline):
            self.metrics.record_expired()
            raise DeadlineExpired(
                "deadline passed before admission to %r" % self.name)
        with self._depth_lock:
            if self._depth >= self.queue_limit:
                self.metrics.record_reject()
                raise SchedulerOverflow(
                    "queue full (%d outstanding, limit %d)"
                    % (self._depth, self.queue_limit))
            self._depth += 1
        req = _Pending(x, deadline)
        if req.trace is not None:
            _flight.record(req.trace.trace_id, "queue.enter",
                           model=self.name, rows=int(x.shape[0]))
        self._queue.put(req)
        return req.future

    def infer(self, x, timeout=None, deadline=None):
        """Blocking inference of any batch size: chunk to ≤ max_batch,
        submit, concatenate.  Returns the output as a numpy array."""
        x = numpy.ascontiguousarray(x, numpy.float32)
        self.validate(x)
        t0 = time.perf_counter()
        futures = [self._enqueue(x[i:i + self.max_batch], deadline)
                   for i in range(0, x.shape[0], self.max_batch)]
        try:
            parts = [f.result(timeout) for f in futures]
        except Exception:
            self.metrics.record_request(
                x.shape[0], time.perf_counter() - t0, ok=False)
            raise
        out = parts[0] if len(parts) == 1 else numpy.concatenate(parts)
        self.metrics.record_request(x.shape[0], time.perf_counter() - t0)
        return out

    # -- dispatch side -------------------------------------------------------
    def _take_next(self, deadline):
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            if deadline is None:
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                return self._queue.get(timeout=remaining)
            except queue.Empty:
                return None

    def _worker_loop(self):
        carry = None
        while True:
            req = carry if carry is not None else self._queue.get()
            carry = None
            if req is _STOP:
                return
            batch, rows = [req], req.n
            # optional linger (off by default): continuous batching
            # self-clocks under load — while this batch runs, the next
            # accumulates — so waiting only ever adds latency
            deadline = (time.monotonic() + self.max_wait
                        if self.max_wait > 0 else None)
            while rows < self.max_batch:
                nxt = self._take_next(deadline)
                if nxt is None:
                    break
                if nxt is _STOP:
                    carry = _STOP
                    break
                if rows + nxt.n > self.max_batch:
                    carry = nxt     # starts the next batch
                    break
                batch.append(nxt)
                rows += nxt.n
            self._execute(batch, rows)

    def _execute(self, batch, rows):
        # pre-batch deadline check: a request that expired while queued
        # is shed HERE — it never occupies a bucket row or device time
        now = time.monotonic()
        expired = [r for r in batch if deadline_expired(r.deadline, now)]
        if expired:
            exc = DeadlineExpired("deadline passed in queue")
            for r in expired:
                self.metrics.record_expired()
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(exc)
                rows -= r.n
            self._release(len(expired))
            batch = [r for r in batch if r not in expired]
            if not batch:
                return
        t0 = time.perf_counter()
        try:
            bucket = self._bucket_for(rows)
            run = self._executables.get(bucket) or \
                self._get_executable(bucket)
            if len(batch) == 1 and batch[0].n == bucket:
                xs = batch[0].x
            else:
                parts = [r.x for r in batch]
                if bucket > rows:
                    parts.append(numpy.zeros(
                        (bucket - rows,) + batch[0].x.shape[1:],
                        numpy.float32))
                xs = numpy.concatenate(parts)
            out = numpy.asarray(run(xs))
        except Exception as exc:
            for r in batch:
                if not r.future.set_running_or_notify_cancel():
                    continue
                r.future.set_exception(exc)
            self._release(len(batch))
            return
        off = 0
        for r in batch:
            if r.future.set_running_or_notify_cancel():
                r.future.set_result(out[off:off + r.n])
            off += r.n
        self._release(len(batch))
        dt = time.perf_counter() - t0
        # request span ids riding this batch (bounded: a full 64-batch
        # of tiny requests must not bloat every span record)
        links = [r.trace.span_id for r in batch
                 if r.trace is not None][:16] or None
        # per-request flight share: batch cost split by row count, so
        # co-batched requests attribute the device time fairly
        for r in batch:
            if r.trace is not None:
                _flight.record(r.trace.trace_id, "queue.admit",
                               bucket=int(bucket))
                _flight.record(r.trace.trace_id, "batch.execute",
                               seconds=round(dt * r.n / max(rows, 1),
                                             6),
                               bucket=int(bucket), rows=int(rows))
        self.metrics.record_batch(bucket, rows, dt, len(batch),
                                  links=links)

    def _release(self, n):
        with self._depth_lock:
            self._depth -= n

    # -- lifecycle / introspection -------------------------------------------
    def close(self, drain=True, timeout=10.0):
        """Stop accepting requests; by default finish everything queued
        (graceful drain), then stop the dispatch workers."""
        if self._closed:
            return
        self._closed = True
        if not drain:
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                if req is _STOP:
                    continue
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(
                        SchedulerClosed("scheduler shut down"))
                self._release(1)
        for _ in self._workers:
            self._queue.put(_STOP)
        for t in self._workers:
            t.join(timeout)
        # a submit that raced the closed flag could still be queued with
        # no worker left to serve it — fail it rather than hang its client
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is _STOP:
                continue
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(
                    SchedulerClosed("scheduler shut down"))
            self._release(1)

    @property
    def queue_depth(self):
        return self._depth

    @property
    def ready(self):
        """True once the warmup ladder is fully compiled (background
        tail included) and the scheduler is accepting — the signal
        behind ``GET /readyz`` and fleet-router admission."""
        if self._closed or not self._executables:
            return False
        t = self._warmup_thread
        return t is None or not t.is_alive()

    def load(self):
        """Cheap backpressure snapshot for routers: no locks beyond
        int reads, safe to poll at high frequency."""
        depth = self._depth
        return {"kind": "bucket",
                "queue_depth": depth,
                "queue_limit": self.queue_limit,
                "utilization": round(depth / self.queue_limit, 4)}

    def retry_after_s(self, cap=30):
        """Seconds until the current backlog plausibly drains: queued
        batches ahead x the recent per-batch wall time, spread over the
        dispatch workers.  The shed response's ``Retry-After`` — a
        computed hint instead of the old hardcoded ``1``."""
        batch_p50 = self.metrics.batch_latency.summary().get("p50_ms")
        if not batch_p50:
            return 1
        batches_ahead = -(-self._depth // self.max_batch)  # ceil
        est = batches_ahead * (batch_p50 / 1e3) / len(self._workers)
        return max(1, min(int(cap), int(est + 0.999)))

    def join_warmup(self, timeout=None):
        """Block until a background warmup tail finishes (no-op when
        warmup was synchronous).  Returns True when nothing is left
        warming."""
        t = self._warmup_thread
        if t is not None:
            t.join(timeout)
            return not t.is_alive()
        return True

    def stats(self):
        """Executable-cache accounting — the zero-recompile evidence.

        ``compiles`` counts FRESH XLA compilations only; executables
        deserialized off the persistent cache land in ``cache_hits``
        (a warm-cache restart therefore shows ``compiles == 0``).
        """
        return {
            "buckets": list(self.buckets),
            "bucket_config": dict(self.bucket_config,
                                  config_source=self.config_source),
            "executables": len(self._executables),
            "compiles": self._compiles,
            "cache_hits": self._cache_hits,
            "compile_seconds": round(self._compile_seconds, 4),
            "warmup_compiles": self._warmup_compiles,
            "post_warmup_compiles": self._compiles - self._warmup_compiles,
            "warming": (self._warmup_thread.is_alive()
                        if self._warmup_thread is not None else False),
            "jit_cache_size": self._adapter.jit_cache_size(),
            "queue_depth": self._depth,
            "queue_limit": self.queue_limit,
            "max_batch": self.max_batch,
            "workers": len(self._workers),
            "ready": self.ready,
            "closed": self._closed,
        }
