"""Multi-model registry: one server, several named models.

The reference deployment story put one model behind one Twisted site
(restful_api.py:78); a production box serves many.  The registry maps
``name -> ServedModel`` (a :class:`BucketScheduler` plus the
result-shaping transform), hot-loadable from exported package zips at
runtime (``POST /api/<name>`` routes here), with the first — or an
explicitly flagged — entry as the default for bare ``POST /api``.
"""

import threading
import time

from .metrics import ServingMetrics
from .scheduler import BucketScheduler


class ServedModel:
    """One registry entry: scheduler + answer shaping.

    ``transform`` plays the reference's ``evaluation_transform`` role
    (restful_api.py evaluation hook); without it a 2-D multi-column
    output is argmaxed (classifier convention), anything else is
    returned verbatim.
    """

    def __init__(self, name, scheduler, transform=None, source=None):
        self.name = name
        self.scheduler = scheduler
        self.transform = transform
        self.source = source
        self.created = time.time()

    def infer(self, batch, timeout=None):
        """→ (result, output) — the protocol tuple the handlers serve."""
        out = self.scheduler.infer(batch, timeout=timeout)
        if self.transform is not None:
            result = self.transform(out)
        elif out.ndim == 2 and out.shape[1] > 1:
            result = out.argmax(axis=1).tolist()
        else:
            result = out.tolist()
        return result, out

    def describe(self):
        stats = self.scheduler.stats()
        return {"source": self.source,
                "sample_shape": list(self.scheduler.sample_shape)
                if self.scheduler.sample_shape is not None else None,
                "buckets": stats["buckets"],
                "queue_depth": stats["queue_depth"],
                "queue_limit": stats["queue_limit"]}


class ModelRegistry:
    """Thread-safe name → :class:`ServedModel` map."""

    def __init__(self, **scheduler_defaults):
        self._models = {}
        self._order = []
        self._default = None
        self._lock = threading.Lock()
        self._scheduler_defaults = scheduler_defaults

    def add(self, name, model, transform=None, default=False,
            metrics=None, **scheduler_kwargs):
        """Register a model (workflow / package path / PackageLoader /
        callable) under ``name``; compiles its bucket ladder now so the
        first request is already warm."""
        source = model if isinstance(model, str) else type(model).__name__
        kwargs = dict(self._scheduler_defaults)
        kwargs.update(scheduler_kwargs)
        scheduler = BucketScheduler(
            model, name=name,
            metrics=metrics or ServingMetrics(name), **kwargs)
        entry = ServedModel(name, scheduler, transform=transform,
                            source=source)
        with self._lock:
            prior = self._models.get(name)
            self._models[name] = entry
            if name not in self._order:
                self._order.append(name)
            if default or self._default is None:
                self._default = name
        if prior is not None:     # hot swap: drain the replaced scheduler
            prior.scheduler.close(drain=True)
        return entry

    def load_package(self, name, path, **kwargs):
        """Hot-load an exported package zip under ``name``."""
        return self.add(name, str(path), **kwargs)

    def remove(self, name, drain=True):
        with self._lock:
            entry = self._models.pop(name, None)
            if name in self._order:
                self._order.remove(name)
            if self._default == name:
                self._default = self._order[0] if self._order else None
        if entry is not None:
            entry.scheduler.close(drain=drain)
        return entry is not None

    def get(self, name):
        with self._lock:
            return self._models.get(name)

    def resolve(self, name=None):
        """``None``/empty → the default entry; unknown → None."""
        with self._lock:
            if not name:
                name = self._default
            return self._models.get(name) if name else None

    def names(self):
        with self._lock:
            return list(self._order)

    @property
    def default_name(self):
        return self._default

    def describe(self):
        with self._lock:
            entries = list(self._models.items())
        return {name: entry.describe() for name, entry in entries}

    def metrics_snapshot(self):
        with self._lock:
            entries = list(self._models.items())
        return {name: {**entry.scheduler.metrics.snapshot(),
                       **entry.scheduler.stats()}
                for name, entry in entries}

    def close(self, drain=True):
        with self._lock:
            entries = list(self._models.values())
        for entry in entries:
            entry.scheduler.close(drain=drain)
