"""Multi-model registry: one server, several named models.

The reference deployment story put one model behind one Twisted site
(restful_api.py:78); a production box serves many.  The registry maps
``name -> ServedModel`` (a :class:`BucketScheduler` plus the
result-shaping transform), hot-loadable from exported package zips at
runtime (``POST /api/<name>`` routes here), with the first — or an
explicitly flagged — entry as the default for bare ``POST /api``.
"""

import threading
import time

from .decode import DecodeScheduler
from .metrics import DecodeMetrics, ServingMetrics
from .scheduler import BucketScheduler


class ServedModel:
    """One registry entry: scheduler + answer shaping.

    ``transform`` plays the reference's ``evaluation_transform`` role
    (restful_api.py evaluation hook); without it a 2-D multi-column
    output is argmaxed (classifier convention), anything else is
    returned verbatim.
    """

    def __init__(self, name, scheduler, transform=None, source=None,
                 version=None):
        self.name = name
        self.scheduler = scheduler
        self.transform = transform
        self.source = source
        self.version = version
        self.created = time.time()

    def infer(self, batch, timeout=None, deadline=None):
        """→ (result, output) — the protocol tuple the handlers serve."""
        out = self.scheduler.infer(batch, timeout=timeout,
                                   deadline=deadline)
        if self.transform is not None:
            result = self.transform(out)
        elif out.ndim == 2 and out.shape[1] > 1:
            result = out.argmax(axis=1).tolist()
        else:
            result = out.tolist()
        return result, out

    def describe(self):
        stats = self.scheduler.stats()
        return {"source": self.source,
                "version": self.version,
                "ready": stats["ready"],
                "sample_shape": list(self.scheduler.sample_shape)
                if self.scheduler.sample_shape is not None else None,
                "buckets": stats["buckets"],
                "queue_depth": stats["queue_depth"],
                "queue_limit": stats["queue_limit"]}


class DecodeServedModel:
    """A registry entry for the token-level decode path: a
    :class:`~veles_tpu.serving.decode.DecodeScheduler` behind the
    generate-style endpoint (``POST /api/<name>/generate``)."""

    kind = "decode"

    def __init__(self, name, scheduler, source=None, version=None):
        self.name = name
        self.scheduler = scheduler
        self.source = source
        self.version = version
        self.created = time.time()

    def generate(self, prompt, max_new_tokens=None, timeout=None,
                 session_id=None, deadline=None):
        """→ the result dict (tokens, ttft_s, prompt_tokens,
        session_id)."""
        return self.scheduler.generate(prompt, max_new_tokens,
                                       timeout=timeout,
                                       session_id=session_id,
                                       deadline=deadline)

    def describe(self):
        stats = self.scheduler.stats()
        return {"source": self.source,
                "version": self.version,
                "ready": stats["ready"],
                "kind": "decode",
                "max_prompt_len": stats["max_prompt_len"],
                "max_new_tokens": stats["max_new_tokens"],
                "max_batch": stats["max_batch"],
                "block_size": stats["block_size"],
                "num_blocks": stats["num_blocks"],
                "active_sequences": stats["active_sequences"],
                "queue_depth": stats["queue_depth"],
                "queue_limit": stats["queue_limit"]}


def _is_decode_model(model):
    """A decode adapter exposes the prefill/decode closure pair."""
    return (hasattr(model, "decode_fn") and hasattr(model, "prefill_fn")
            and hasattr(model, "make_pools"))


class ModelRegistry:
    """Thread-safe name → :class:`ServedModel` /
    :class:`DecodeServedModel` map."""

    def __init__(self, **scheduler_defaults):
        self._models = {}
        self._order = []
        self._default = None
        self._lock = threading.Lock()
        self._scheduler_defaults = scheduler_defaults

    def add(self, name, model, transform=None, default=False,
            metrics=None, version=None, **scheduler_kwargs):
        """Register a model (workflow / package path / PackageLoader /
        callable) under ``name``; compiles its bucket ladder now so the
        first request is already warm.  A decode adapter (anything with
        the ``prefill_fn``/``decode_fn``/``make_pools`` trio) routes to
        :meth:`add_decode` instead.

        Re-adding an existing ``name`` is the HOT-LOAD path: the new
        entry (optionally tagged ``version``) warms fully before the
        swap, the swap itself is one dict write under the lock, and the
        replaced scheduler drains — in-flight requests against the old
        version complete normally, so a rolling fleet update never
        drops a response."""
        if _is_decode_model(model):
            return self.add_decode(name, model, default=default,
                                   metrics=metrics, version=version,
                                   **scheduler_kwargs)
        source = model if isinstance(model, str) else type(model).__name__
        kwargs = dict(self._scheduler_defaults)
        kwargs.update(scheduler_kwargs)
        scheduler = BucketScheduler(
            model, name=name,
            metrics=metrics or ServingMetrics(name), **kwargs)
        entry = ServedModel(name, scheduler, transform=transform,
                            source=source, version=version)
        return self._install(name, entry, default)

    def add_decode(self, name, model, default=False, metrics=None,
                   version=None, **decode_kwargs):
        """Register a decode adapter under ``name`` — warms its decode
        executable and prefill ladder now, serves
        ``POST /api/<name>/generate``."""
        # registry-wide defaults may mix bucket- and decode-scheduler
        # knobs (one server can host both kinds); forward only what
        # DecodeScheduler actually takes
        kwargs = {k: v for k, v in self._scheduler_defaults.items()
                  if k in ("max_batch", "block_size", "max_prompt_len",
                           "max_new_tokens", "num_blocks",
                           "queue_limit", "cache", "manifest",
                           "warmup", "prefix_caching",
                           "prefill_chunk_tokens", "spec_depth",
                           "kvtier", "kv_dtype")}
        # a model may carry its own geometry (the toydecode spec path):
        # registry-wide defaults < model defaults < explicit kwargs
        kwargs.update(getattr(model, "decode_defaults", None) or {})
        kwargs.update(decode_kwargs)
        scheduler = DecodeScheduler(
            model, name=name,
            metrics=metrics or DecodeMetrics(name), **kwargs)
        entry = DecodeServedModel(name, scheduler,
                                  source=type(model).__name__,
                                  version=version)
        return self._install(name, entry, default)

    def _install(self, name, entry, default):
        with self._lock:
            prior = self._models.get(name)
            self._models[name] = entry
            if name not in self._order:
                self._order.append(name)
            if default or self._default is None:
                self._default = name
        if prior is not None:     # hot swap: drain the replaced scheduler
            prior.scheduler.close(drain=True)
        return entry

    def load_package(self, name, path, **kwargs):
        """Hot-load an exported package zip under ``name``."""
        return self.add(name, str(path), **kwargs)

    def remove(self, name, drain=True):
        with self._lock:
            entry = self._models.pop(name, None)
            if name in self._order:
                self._order.remove(name)
            if self._default == name:
                self._default = self._order[0] if self._order else None
        if entry is not None:
            entry.scheduler.close(drain=drain)
        return entry is not None

    def get(self, name):
        with self._lock:
            return self._models.get(name)

    def resolve(self, name=None):
        """``None``/empty → the default entry; unknown → None."""
        with self._lock:
            if not name:
                name = self._default
            return self._models.get(name) if name else None

    def names(self):
        with self._lock:
            return list(self._order)

    @property
    def default_name(self):
        return self._default

    def ready(self):
        """True when at least one model is registered and EVERY
        registered scheduler finished its warmup ladder — what
        ``GET /readyz`` (and through it the fleet router) gates on."""
        with self._lock:
            entries = list(self._models.values())
        return bool(entries) and all(e.scheduler.ready for e in entries)

    def load_snapshot(self):
        """Per-model backpressure signals (cheap — no latency sorts),
        the router's least-loaded dispatch input."""
        with self._lock:
            entries = list(self._models.items())
        return {name: entry.scheduler.load() for name, entry in entries}

    def describe(self):
        with self._lock:
            entries = list(self._models.items())
        return {name: entry.describe() for name, entry in entries}

    def metrics_snapshot(self):
        with self._lock:
            entries = list(self._models.items())
        return {name: {**entry.scheduler.metrics.snapshot(),
                       **entry.scheduler.stats()}
                for name, entry in entries}

    def close(self, drain=True):
        with self._lock:
            entries = list(self._models.values())
        for entry in entries:
            entry.scheduler.close(drain=drain)
