"""veles_tpu.serving — dynamic-batching inference service.

The TPU-native counterpart of the reference's standalone inference
runtime (libVeles beside the trainer): turn a trained workflow or an
exported package into a production HTTP service.

- :mod:`.scheduler` — request-granularity micro-batching onto warm,
  shape-bucketed XLA executables (power-of-two padding, AOT warmup,
  zero steady-state recompilation, bounded-queue backpressure);
- :mod:`.decode` — token-level continuous batching for autoregressive
  decode: per-step admit/retire against ONE warm executable, prompt
  prefill through a length-bucket ladder;
- :mod:`.kvcache` — the paged KV cache's host-side block allocator
  (free list + page tables over the preallocated device pools);
- :mod:`.registry` — several named, hot-loadable models per server;
- :mod:`.server` — the HTTP front end (429 load shedding, graceful
  drain, ``/metrics`` + ``/healthz``);
- :mod:`.metrics` — latency histograms, batch-fill, req/s and decode
  tok/s + TTFT, wired into the Chrome-trace event log.

Quickstart::

    from veles_tpu.serving import InferenceServer
    server = InferenceServer({"mnist": "mnist_pkg.zip"}, port=8080)
    # POST http://127.0.0.1:8080/api/mnist {"input": [[...784...]]}
    server.stop()

or from the CLI: ``python -m veles_tpu --serve mnist_pkg.zip``.  For
decode serving, register a decode adapter (e.g.
``znicz.samples.flagship.FlagshipDecodeModel()``) and POST
``{"prompt": [...], "max_new_tokens": n}`` to ``/api/<name>/generate``.
"""

from .decode import DecodeScheduler
from .kvcache import KVBlockPool
from .metrics import DecodeMetrics, LatencyWindow, ServingMetrics
from .registry import DecodeServedModel, ModelRegistry, ServedModel
from .scheduler import (BucketScheduler, DeadlineExpired,
                        SchedulerClosed, SchedulerOverflow,
                        bucket_sizes, deadline_expired)
from .server import InferenceServer
from .sessions import pack_state, pack_states, unpack_state, unpack_states
from .toydecode import ToyDecodeModel

__all__ = ["BucketScheduler", "DeadlineExpired", "DecodeMetrics",
           "DecodeScheduler", "DecodeServedModel", "InferenceServer",
           "KVBlockPool", "LatencyWindow", "ModelRegistry",
           "ServedModel", "SchedulerClosed", "SchedulerOverflow",
           "ServingMetrics", "ToyDecodeModel", "bucket_sizes",
           "deadline_expired", "pack_state", "pack_states",
           "unpack_state", "unpack_states"]
