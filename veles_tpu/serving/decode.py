"""Token-level continuous batching over a paged KV cache.

:class:`BucketScheduler` batches at REQUEST granularity — right for
fixed-shape classifiers, wrong for autoregressive decode, where
sequences finish at different times and a static batch leaves rows idle
from the first early finish to the last straggler.  This scheduler is
the Orca-style alternative (PAPERS.md; "Ragged Paged Attention", arXiv
2604.15464): scheduling decisions happen **every token step**, not
every request —

- one warm decode executable with STATIC shapes (``max_batch`` rows ×
  the ``[max_batch, max_blocks]`` page-table operand) runs the whole
  lifetime of the server: admitting a sequence writes integers into
  the page table, retiring one returns its blocks to the free list,
  and the executable never recompiles (``stats()["compiles"]`` is flat
  after warmup, across restarts via the compile cache + warmup
  manifest);
- prompt prefill goes through a power-of-two length ladder (the same
  bucket discipline — and the same persistent-executable plumbing — as
  the request path), one sequence per prefill; with
  ``prefill_chunk_tokens`` set, prefill instead runs through ONE warm
  fixed-size chunk executable, one chunk per worker iteration,
  interleaved with decode steps — a long prompt no longer stalls the
  batch for a monolithic ladder call (the TTFT-vs-throughput tension
  ragged paged attention exists to resolve);
- with ``prefix_caching`` on, the block pool is content-addressed over
  token-prefix hashes (:func:`.kvcache.key_chain`): admission attaches
  to already-resident blocks via refcounts and prefills only the
  non-resident suffix, chunk by chunk — shared system prompts and
  multi-turn re-submissions skip most of their prefill.  Both knobs
  default OFF, which is bit-for-bit the historical behavior;
- with ``spec_depth`` set, decode runs **speculatively**: a cheap
  drafter (``model.draft_fn``) proposes k tokens per row per
  iteration, and the target verifies all k+1 positions in ONE batched
  pass (``model.verify_fn`` → the multi-token
  :func:`~veles_tpu.znicz.paged_attention.paged_verify_attention`
  entry of the same ragged kernel).  Greedy rejection sampling —
  accept the longest draft prefix the target agrees with, plus the
  target's own correction token — makes the emitted stream
  token-for-token identical to plain decode; K/V written for rejected
  positions is rolled back by NOT advancing the length over it (the
  kernel's length masking hides it until overwritten), so rejected
  content is never published, shared, or exported.  The knob defaults
  OFF, which is bit-for-bit the plain per-token step;
- K/V lives in fixed-size blocks of a preallocated device pool
  (:mod:`.kvcache` owns placement; znicz/paged_attention.py gathers
  through the page table), so memory is allocated per sequence LENGTH,
  not per ``max_batch x max_context`` rectangle;
- backpressure is a bounded queue: beyond ``queue_limit`` outstanding
  requests :meth:`submit` raises :class:`SchedulerOverflow` and the
  server answers 429 + Retry-After.

The single worker thread owns every mutable: the block pool, the page
table, the session map, and the device pool handles (the decode
executable donates and returns them).  ``submit`` only validates and
enqueues — the cross-thread surface is one Queue and one Future per
request.
"""

import collections
import os
import queue
import threading
import time
import uuid
from concurrent.futures import Future

import numpy

from ..compilecache import WarmupManifest, default_cache
from ..logger import events
from ..observability import trace as _trace
from ..observability.flight import RECORDER as _flight
from .kvcache import KVBlockPool, key_chain, required_blocks
from .metrics import DecodeMetrics
from .scheduler import (DeadlineExpired, SchedulerClosed,
                        SchedulerOverflow, bucket_sizes,
                        deadline_expired)

_STOP = object()

#: completed results kept for session re-attach (router failover /
#: migration races land the client's follow-up after completion)
_FINISHED_KEEP = 256

#: hand-picked prefill chunk size (tokens per chunk executable call) —
#: the ``serving.prefill_chunk`` autotune site's baseline candidate
DEFAULT_PREFILL_CHUNK = 32

#: hand-picked speculation depth (draft tokens per iteration) — the
#: ``serving.spec_depth`` autotune site's baseline candidate
DEFAULT_SPEC_DEPTH = 2


def _tid(req):
    """The request's flight-timeline key (its trace id), or None for
    trace-less direct submits — the recorder ignores None keys."""
    return req.trace.trace_id if req.trace is not None else None


class _Request:
    __slots__ = ("prompt", "max_new_tokens", "future", "enqueued",
                 "trace", "sid", "deadline")

    def __init__(self, prompt, max_new_tokens, session_id=None,
                 deadline=None):
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.future = Future()
        self.enqueued = time.perf_counter()
        self.trace = _trace.current()
        # every sequence is addressable: an explicit X-Session-Id or a
        # fresh one — migration and re-attach key on it
        self.sid = str(session_id) if session_id else uuid.uuid4().hex[:16]
        self.deadline = deadline    # absolute time.monotonic() or None


class _Job:
    """A callable the WORKER runs between token steps — the only safe
    place to touch pools/table/sessions (checkpoint_kv/restore_kv)."""

    __slots__ = ("fn", "future")

    def __init__(self, fn):
        self.fn = fn
        self.future = Future()


class _Session:
    """One admitted sequence: its row, blocks, and token state."""

    __slots__ = ("req", "row", "blocks", "length", "next_input",
                 "generated", "first_token_s", "shared", "prefilled",
                 "tier")

    def __init__(self, req, row, blocks):
        self.req = req
        self.row = row
        self.blocks = blocks
        self.length = 0          # tokens in the KV cache
        self.next_input = 0      # last emitted token (next step's input)
        self.generated = []
        self.first_token_s = None
        self.shared = 0          # leading blocks attached already-resident
        self.prefilled = 0       # prompt tokens prefilled so far (chunked)
        self.tier = None         # deepest tier serving the prefix hit

    @property
    def done(self):
        return len(self.generated) >= self.req.max_new_tokens


class DecodeScheduler:
    """Admit/retire sequences every step against one warm executable.

    ``model`` is a decode adapter (e.g.
    :class:`veles_tpu.znicz.samples.flagship.FlagshipDecodeModel`):
    ``make_pools(num_blocks, block_size)``, ``prefill_fn(block_size)``,
    ``decode_fn(block_size)``, ``vocab``.

    Geometry: ``max_batch`` concurrent sequences, each at most
    ``max_prompt_len`` prompt + ``max_new_tokens`` generated tokens,
    stored in ``block_size``-token blocks.  ``num_blocks`` defaults to
    full occupancy (every row at max context) + the reserved trash
    block; size it smaller to oversubscribe memory, in which case
    admission also waits for free blocks.
    """

    def __init__(self, model, *, max_batch=None, block_size=None,
                 max_prompt_len=32, max_new_tokens=32, num_blocks=None,
                 queue_limit=64, name="decode", metrics=None,
                 cache=None, manifest=None, warmup=True,
                 prefix_caching=False, prefill_chunk_tokens=None,
                 spec_depth=None, kvtier=None, kv_dtype=None):
        self.name = name
        self.model = model
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.queue_limit = int(queue_limit)
        self.max_context = self.max_prompt_len + self.max_new_tokens
        # prefill chunking is a TUNABLE SITE too (serving.prefill_chunk):
        # an int pins the chunk size, "auto" consults the tuning store,
        # None (default) keeps the monolithic bucket-ladder path exactly
        self.prefix_caching = bool(prefix_caching)
        self._chunk_source = None
        chunk = prefill_chunk_tokens
        if chunk == "auto":
            from ..autotune import dispatch as _autotune
            from ..autotune.space import pow2_bucket
            cfg_c, self._chunk_source = _autotune.resolve(
                "serving.prefill_chunk",
                "mp%d" % pow2_bucket(self.max_prompt_len),
                default={"chunk_tokens": DEFAULT_PREFILL_CHUNK})
            chunk = cfg_c["chunk_tokens"]
        elif chunk is not None:
            self._chunk_source = "explicit"
        self.chunk_tokens = int(chunk) if chunk else None
        if self.chunk_tokens is not None and self.chunk_tokens < 1:
            raise ValueError("prefill_chunk_tokens must be >= 1")
        if self.prefix_caching and not self.chunk_tokens:
            raise ValueError(
                "prefix_caching=True requires prefill_chunk_tokens — "
                "the chunked path is what admits partially-resident "
                "prompts (suffix-only prefill)")
        if self.chunk_tokens and not hasattr(model,
                                             "prefill_chunk_fn"):
            raise ValueError(
                "model %r has no prefill_chunk_fn; chunked prefill "
                "is unavailable for it" % getattr(model, "name", model))
        # speculation depth is a TUNABLE SITE too (serving.spec_depth):
        # an int pins k, "auto" consults the tuning store (measured
        # acceptance rate vs verify cost), None (default) keeps the
        # plain per-token step exactly
        self._spec_source = None
        spec = spec_depth
        if spec == "auto":
            from ..autotune import dispatch as _autotune
            from ..autotune.space import pow2_bucket
            cfg_s, self._spec_source = _autotune.resolve(
                "serving.spec_depth",
                "mn%d" % pow2_bucket(self.max_new_tokens),
                default={"spec_depth": DEFAULT_SPEC_DEPTH})
            spec = cfg_s["spec_depth"]
        elif spec is not None:
            self._spec_source = "explicit"
        self.spec_depth = None if spec is None else int(spec)
        if self.spec_depth is not None and self.spec_depth < 1:
            raise ValueError("spec_depth must be >= 1 (or None to "
                             "disable speculative decoding)")
        if self.spec_depth and not (hasattr(model, "draft_fn")
                                    and hasattr(model, "verify_fn")):
            raise ValueError(
                "model %r has no draft_fn/verify_fn; speculative "
                "decoding is unavailable for it"
                % getattr(model, "name", model))
        # KV-pool precision is a TUNABLE SITE too (serving.kv_dtype):
        # a string pins it, "auto" consults the tuning store (whose
        # probe is error-bounded, not bitwise), None (default) keeps
        # the f32 pools and every model call byte-identical
        self._kv_dtype_source = None
        kvd = kv_dtype
        if kvd == "auto":
            from ..autotune import dispatch as _autotune
            cfg_q, self._kv_dtype_source = _autotune.resolve(
                "serving.kv_dtype", "ctx%d" % self.max_context,
                default={"kv_dtype": "f32"})
            kvd = cfg_q["kv_dtype"]
        elif kvd is not None:
            self._kv_dtype_source = "explicit"
        self.kv_dtype = str(kvd) if kvd else "f32"
        if self.kv_dtype != "f32":
            supported = tuple(getattr(model, "kv_dtypes", ("f32",)))
            if self.kv_dtype not in supported:
                raise ValueError(
                    "model %r does not serve kv_dtype=%r "
                    "(supported: %s)"
                    % (getattr(model, "name", model), self.kv_dtype,
                       ", ".join(supported)))
        # quantized pools widen the model-hook signatures ONLY when
        # on: the f32 default calls every factory exactly as before
        self._model_kw = ({} if self.kv_dtype == "f32"
                          else {"kv_dtype": self.kv_dtype})
        self._tag_sfx = ("" if self.kv_dtype == "f32"
                         else "-" + self.kv_dtype)
        # the decode geometry is a TUNABLE SITE (serving.decode):
        # explicit kwargs pin it; otherwise a tuning record for this
        # context-length class picks the measured (max_batch,
        # block_size), and tuner off = the historical (8, 8) defaults
        # exactly
        if max_batch is not None and block_size is not None:
            self.config_source = "explicit"
            cfg = {"max_batch": int(max_batch),
                   "block_size": int(block_size)}
        else:
            from ..autotune import dispatch as _autotune
            from ..znicz.paged_attention import DEFAULT_BLOCK_SIZE
            cfg, self.config_source = _autotune.resolve(
                "serving.decode", "ctx%d" % self.max_context,
                default={"max_batch": 8,
                         "block_size": DEFAULT_BLOCK_SIZE})
            if max_batch is not None:
                cfg["max_batch"] = int(max_batch)
            if block_size is not None:
                cfg["block_size"] = int(block_size)
        self.max_batch = int(cfg["max_batch"])
        self.block_size = int(cfg["block_size"])
        self.max_blocks = required_blocks(self.max_context,
                                          self.block_size)
        if num_blocks is None:
            num_blocks = self.max_batch * self.max_blocks + 1
        self.metrics = metrics or DecodeMetrics(name)
        self.prefill_buckets = bucket_sizes(self.max_prompt_len)
        self._pool = KVBlockPool(num_blocks, self.block_size,
                                 prefix_caching=self.prefix_caching)
        if not self._pool.fits(self.max_context):
            raise ValueError(
                "num_blocks=%d cannot hold even one max-context "
                "sequence (%d tokens need %d blocks of %d)"
                % (num_blocks, self.max_context, self.max_blocks,
                   self.block_size))
        # tiered KV cache (veles_tpu/kvtier): None (default) keeps the
        # evict-means-die pool exactly; a config dict — {"host_bytes",
        # "disk_dir", "disk_bytes"} — or a ready TieredKVStore hooks
        # the pool's eviction path so refcount-0 chains demote to host
        # RAM / disk and admits readmit them with zero re-prefill
        self._kvtier = self._resolve_kvtier(kvtier)
        self._advert = None          # {"hbm": [...], "host": [...], ...}
        self._advert_sig = None
        self._readmit_bytes = 0      # wire bytes of the last tier readmit
        if self._kvtier is not None:
            self._pool.on_evict = self._demote_block
            self._refresh_advert()   # disk chains advertise pre-traffic
        self._k_pools, self._v_pools = model.make_pools(
            num_blocks, self.block_size, **self._model_kw)
        # numpy mirrors of the step operands; the worker edits them on
        # admit/retire and ships them whole every step
        self._np_table = numpy.zeros((self.max_batch, self.max_blocks),
                                     numpy.int32)
        self._np_lengths = numpy.zeros(self.max_batch, numpy.int32)
        self._np_tokens = numpy.zeros(self.max_batch, numpy.int32)
        self._sessions = {}          # row -> _Session (decoding)
        self._chunking = collections.deque()   # _Session mid-prefill
        self._by_sid = {}            # session id -> live _Session
        self._migrating = {}         # session id -> parked Future
        self._finished = collections.OrderedDict()  # sid -> result (LRU)
        self._pending = collections.deque()
        self._queue = queue.Queue()
        self._depth = 0              # queued + pending + active
        self._depth_lock = threading.Lock()
        self._closed = False
        self._abort = False
        # compile plumbing — same cache/manifest resolution and stats
        # split (fresh compiles vs cache hits) as BucketScheduler
        import jax
        self._jax = jax
        # static per-block byte footprint across every pool leaf (int8
        # pools carry their f32 scale planes — both leaves index blocks
        # on axis 0, so shape[1:] is exactly the per-block payload)
        self._block_bytes = sum(
            int(numpy.prod(leaf.shape[1:])) * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(
                (self._k_pools, self._v_pools)))
        self.metrics.set_kv_dtype(self.kv_dtype)
        self.metrics.set_kv_bytes(0)
        self._decode_jit = jax.jit(
            model.decode_fn(self.block_size, **self._model_kw),
            donate_argnums=(0, 1))
        self._prefill_jit = jax.jit(
            model.prefill_fn(self.block_size, **self._model_kw),
            donate_argnums=(2, 3))
        self._chunk_jit = None
        if self.chunk_tokens:
            self._chunk_jit = jax.jit(
                model.prefill_chunk_fn(self.block_size,
                                       **self._model_kw),
                donate_argnums=(3, 4))
        self._draft_jit = self._verify_jit = None
        if self.spec_depth:
            # the drafter only READS the pools (no donation — the
            # verify pass reuses them); verify donates like decode
            self._draft_jit = jax.jit(
                model.draft_fn(self.block_size, self.spec_depth,
                               **self._model_kw))
            self._verify_jit = jax.jit(
                model.verify_fn(self.block_size, self.spec_depth,
                                **self._model_kw),
                donate_argnums=(0, 1))
        self._decode_exe = None
        self._chunk_exe = None
        self._draft_exe = None
        self._verify_exe = None
        self._prefill_exes = {}
        # lifetime speculation counters (stats()/kv_dump alongside the
        # registry-backed metrics series)
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_rejected = 0
        self._compiles = 0
        self._cache_hits = 0
        self._compile_seconds = 0.0
        self._warmup_compiles = 0
        self._compile_lock = threading.Lock()
        if cache is None:
            cache = default_cache()
        self._cache = cache or None
        if manifest is None:
            self._manifest = (self._cache.manifest
                              if self._cache is not None else None)
        elif isinstance(manifest, str):
            self._manifest = WarmupManifest(manifest)
        else:
            self._manifest = manifest or None
        if self._manifest is not None and self.config_source == "tuned":
            # winners ride the warmup manifest: a warm restart decodes
            # with the SAME tuned geometry, so the cached executable
            # matches and nothing recompiles
            self._manifest.record_config(
                self.name, "serving.decode",
                {"max_batch": self.max_batch,
                 "block_size": self.block_size})
        if self._manifest is not None and self._chunk_source == "tuned":
            self._manifest.record_config(
                self.name, "serving.prefill_chunk",
                {"chunk_tokens": self.chunk_tokens})
        if self._manifest is not None and self._spec_source == "tuned":
            self._manifest.record_config(
                self.name, "serving.spec_depth",
                {"spec_depth": self.spec_depth})
        if self._manifest is not None \
                and self._kv_dtype_source == "tuned":
            self._manifest.record_config(
                self.name, "serving.kv_dtype",
                {"kv_dtype": self.kv_dtype})
        self._warmed = False
        if warmup:
            self.warmup()
        self._worker = threading.Thread(
            target=self._worker_loop, daemon=True,
            name="veles-decode-%s" % name)
        self._worker.start()

    # -- compilation ---------------------------------------------------------
    def _pool_structs(self):
        return self._jax.tree_util.tree_map(
            lambda a: self._jax.ShapeDtypeStruct(a.shape, a.dtype),
            (self._k_pools, self._v_pools))

    def _aot(self, jitted, *structs, tag):
        """AOT compile (through the persistent cache when active) with
        the scheduler's compile accounting."""
        t0 = time.perf_counter()
        if self._cache is not None:
            compiled, hit = self._cache.get_or_compile(
                jitted, *structs, name="serving.%s.%s"
                % (self.name, tag))
        else:
            compiled, hit = jitted.lower(*structs).compile(), None
        dt = time.perf_counter() - t0
        if hit:
            self._cache_hits += 1
        else:
            self._compiles += 1
        self._compile_seconds += dt
        events.span("serving.compile", dt, model=self.name, bucket=tag,
                    cache_hit=bool(hit) if hit is not None else None)
        return compiled

    def _get_decode_exe(self):
        if self._decode_exe is None:
            with self._compile_lock:
                if self._decode_exe is None:
                    jax = self._jax
                    kps, vps = self._pool_structs()
                    self._decode_exe = self._aot(
                        self._decode_jit, kps, vps,
                        jax.ShapeDtypeStruct(self._np_table.shape,
                                             numpy.int32),
                        jax.ShapeDtypeStruct((self.max_batch,),
                                             numpy.int32),
                        jax.ShapeDtypeStruct((self.max_batch,),
                                             numpy.int32),
                        tag="decode%d%s" % (self.max_batch,
                                            self._tag_sfx))
                    if self._manifest is not None:
                        self._manifest.record(self.name + "@decode",
                                              self.max_batch)
        return self._decode_exe

    def _get_prefill_exe(self, bucket):
        exe = self._prefill_exes.get(bucket)
        if exe is None:
            with self._compile_lock:
                exe = self._prefill_exes.get(bucket)
                if exe is None:
                    jax = self._jax
                    kps, vps = self._pool_structs()
                    exe = self._aot(
                        self._prefill_jit,
                        jax.ShapeDtypeStruct((int(bucket),),
                                             numpy.int32),
                        jax.ShapeDtypeStruct((), numpy.int32),
                        kps, vps,
                        jax.ShapeDtypeStruct((self.max_blocks,),
                                             numpy.int32),
                        tag="prefill%d%s" % (int(bucket),
                                             self._tag_sfx))
                    self._prefill_exes[bucket] = exe
                    if self._manifest is not None:
                        self._manifest.record(self.name + "@prefill",
                                              bucket)
        return exe

    def _get_draft_exe(self):
        if self._draft_exe is None:
            with self._compile_lock:
                if self._draft_exe is None:
                    jax = self._jax
                    kps, vps = self._pool_structs()
                    self._draft_exe = self._aot(
                        self._draft_jit, kps, vps,
                        jax.ShapeDtypeStruct(self._np_table.shape,
                                             numpy.int32),
                        jax.ShapeDtypeStruct((self.max_batch,),
                                             numpy.int32),
                        jax.ShapeDtypeStruct((self.max_batch,),
                                             numpy.int32),
                        tag="draft%d%s" % (self.spec_depth,
                                           self._tag_sfx))
                    if self._manifest is not None:
                        self._manifest.record(self.name + "@draft",
                                              self.spec_depth)
        return self._draft_exe

    def _get_verify_exe(self):
        if self._verify_exe is None:
            with self._compile_lock:
                if self._verify_exe is None:
                    jax = self._jax
                    kps, vps = self._pool_structs()
                    self._verify_exe = self._aot(
                        self._verify_jit, kps, vps,
                        jax.ShapeDtypeStruct(self._np_table.shape,
                                             numpy.int32),
                        jax.ShapeDtypeStruct((self.max_batch,),
                                             numpy.int32),
                        jax.ShapeDtypeStruct(
                            (self.max_batch, self.spec_depth + 1),
                            numpy.int32),
                        tag="verify%d%s" % (self.spec_depth,
                                            self._tag_sfx))
                    if self._manifest is not None:
                        self._manifest.record(self.name + "@verify",
                                              self.spec_depth)
        return self._verify_exe

    def _get_chunk_exe(self):
        if self._chunk_exe is None:
            with self._compile_lock:
                if self._chunk_exe is None:
                    jax = self._jax
                    kps, vps = self._pool_structs()
                    self._chunk_exe = self._aot(
                        self._chunk_jit,
                        jax.ShapeDtypeStruct((self.chunk_tokens,),
                                             numpy.int32),
                        jax.ShapeDtypeStruct((), numpy.int32),
                        jax.ShapeDtypeStruct((), numpy.int32),
                        kps, vps,
                        jax.ShapeDtypeStruct((self.max_blocks,),
                                             numpy.int32),
                        tag="chunk%d%s" % (self.chunk_tokens,
                                           self._tag_sfx))
                    if self._manifest is not None:
                        self._manifest.record(self.name + "@chunk",
                                              self.chunk_tokens)
        return self._chunk_exe

    def _warmup_order(self):
        order = list(self.prefill_buckets)
        if self._manifest is None:
            return order
        first = [b for b in
                 self._manifest.buckets(self.name + "@prefill")
                 if b in order]
        return first + [b for b in order if b not in first]

    def warmup(self):
        """Compile the decode step and the whole prefill path up front
        so steady state never compiles.  Chunked mode replaces the
        whole prefill ladder with ONE chunk executable (every chunk of
        every prompt runs through it) — one more AOT entry in the
        warmup manifest, one less reason for a restart to compile."""
        self._get_decode_exe()
        if self.spec_depth:
            self._get_draft_exe()
            self._get_verify_exe()
        if self.chunk_tokens:
            self._get_chunk_exe()
        else:
            for b in self._warmup_order():
                self._get_prefill_exe(b)
        self._warmup_compiles = self._compiles
        self._warmed = True

    # -- request side --------------------------------------------------------
    def validate(self, prompt, max_new_tokens):
        prompt = numpy.asarray(prompt)
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError("prompt must be a non-empty 1-D token "
                             "sequence")
        if prompt.shape[0] > self.max_prompt_len:
            raise ValueError(
                "prompt of %d tokens exceeds max_prompt_len=%d"
                % (prompt.shape[0], self.max_prompt_len))
        if not numpy.issubdtype(prompt.dtype, numpy.integer):
            if not numpy.all(prompt == prompt.astype(numpy.int64)):
                raise ValueError("prompt tokens must be integers")
        prompt = prompt.astype(numpy.int32)
        vocab = getattr(self.model, "vocab", None)
        if vocab and (prompt.min() < 0 or prompt.max() >= vocab):
            raise ValueError("prompt tokens outside [0, %d)" % vocab)
        if not 1 <= int(max_new_tokens) <= self.max_new_tokens:
            raise ValueError(
                "max_new_tokens must be in [1, %d], got %r"
                % (self.max_new_tokens, max_new_tokens))
        return prompt

    def submit(self, prompt, max_new_tokens=None, session_id=None,
               deadline=None):
        """Enqueue one generate request → Future of
        ``{"tokens": [...], "ttft_s": float, "prompt_tokens": n,
        "session_id": sid}``.  Raises SchedulerOverflow /
        SchedulerClosed / DeadlineExpired / ValueError."""
        if max_new_tokens is None:
            max_new_tokens = self.max_new_tokens
        prompt = self.validate(prompt, max_new_tokens)
        if self._closed:
            raise SchedulerClosed("decode scheduler %r is draining"
                                  % self.name)
        if deadline_expired(deadline):
            self.metrics.record_expired()
            raise DeadlineExpired(
                "deadline passed before admission to %r" % self.name)
        with self._depth_lock:
            if self._depth >= self.queue_limit:
                self.metrics.record_reject()
                raise SchedulerOverflow(
                    "decode queue full (%d outstanding, limit %d)"
                    % (self._depth, self.queue_limit))
            self._depth += 1
        req = _Request(prompt, max_new_tokens, session_id=session_id,
                       deadline=deadline)
        _flight.record(_tid(req), "queue.enter", model=self.name,
                       session=req.sid,
                       prompt_tokens=int(prompt.shape[0]),
                       kv_dtype=self.kv_dtype)
        # meta too (events don't feed aggregate()'s group keys): the
        # attribution report can slice tail latency by pool precision
        _flight.annotate(_tid(req), kv_dtype=self.kv_dtype)
        self._queue.put(req)
        return req.future

    def generate(self, prompt, max_new_tokens=None, timeout=None,
                 session_id=None, deadline=None):
        """Blocking :meth:`submit`."""
        return self.submit(prompt, max_new_tokens,
                           session_id=session_id,
                           deadline=deadline).result(timeout)

    # -- worker --------------------------------------------------------------
    def _worker_loop(self):
        stop = False
        while True:
            block = (not self._sessions and not self._chunking
                     and not self._pending and not stop)
            while True:
                try:
                    item = self._queue.get(block=block, timeout=None) \
                        if block else self._queue.get_nowait()
                except queue.Empty:
                    break
                block = False
                if item is _STOP:
                    stop = True
                    break
                if isinstance(item, _Job):
                    # step boundary: no executable in flight, worker
                    # owns every mutable — run the job inline
                    try:
                        item.future.set_result(item.fn())
                    except Exception as exc:  # noqa: BLE001 — to caller
                        item.future.set_exception(exc)
                    continue
                self._pending.append(item)
            if self._abort:
                self._cancel_all()
                return
            self._admit()
            # THE interleave: one prefill chunk, then one decode step —
            # a long prompt advances without ever stalling live rows
            # for more than one chunk's worth of device time
            if self._chunking:
                self._chunk_step()
            if self._sessions:
                if self.spec_depth:
                    self._spec_step()
                else:
                    self._step()
            elif stop and not self._pending and not self._chunking:
                return

    def _fail(self, req, exc):
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(exc)
        self._release()

    def _release(self):
        with self._depth_lock:
            self._depth -= 1

    def _cancel_all(self):
        exc = SchedulerClosed("scheduler shut down")
        while self._pending:
            self._fail(self._pending.popleft(), exc)
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                self._fail(item, exc)
        while self._chunking:
            session = self._chunking.popleft()
            self._by_sid.pop(session.req.sid, None)
            self._release_session_blocks(session, publish=False)
            self._fail(session.req, exc)
        for row in list(self._sessions):
            session = self._sessions[row]
            self._retire(session, error=exc)
        for sid in list(self._migrating):
            future = self._migrating.pop(sid)
            if future.set_running_or_notify_cancel():
                future.set_exception(exc)

    # -- admission / prefill -------------------------------------------------
    def _set_occupancy(self):
        self.metrics.set_occupancy(
            len(self._sessions), self._pool.live_blocks /
            max(self._pool.capacity, 1))
        # cached (refcount-0, content retained for prefix reuse) blocks
        # still hold device bytes — resident means "not free"
        self.metrics.set_kv_bytes(
            (self._pool.live_blocks + self._pool.cached_blocks)
            * self._block_bytes)

    def _free_rows(self):
        busy = set(self._sessions)
        busy.update(s.row for s in self._chunking)
        return [r for r in range(self.max_batch) if r not in busy]

    def _admit(self):
        # shed queue-expired work FIRST: a request whose deadline passed
        # while it waited must not block the head of the line or spend
        # a prefill on an answer nobody is waiting for
        if self._pending:
            now = time.monotonic()
            live = collections.deque()
            while self._pending:
                req = self._pending.popleft()
                if deadline_expired(req.deadline, now):
                    self.metrics.record_expired()
                    self._fail(req, DeadlineExpired(
                        "deadline passed before prefill"))
                else:
                    live.append(req)
            self._pending = live
        rows = self._free_rows()
        while self._pending and rows:
            req = self._pending[0]
            need = required_blocks(
                len(req.prompt) + req.max_new_tokens, self.block_size)
            if self.chunk_tokens:
                if not self._admit_chunked(req, need, rows):
                    break           # head-of-line waits for retirements
                continue
            blocks = self._pool.alloc(need)
            if blocks is None:
                break               # head-of-line waits for retirements
            self._pending.popleft()
            row = rows.pop(0)
            session = _Session(req, row, blocks)
            _flight.record(_tid(req), "queue.admit", row=row,
                           chunked=False)
            try:
                self._prefill(session)
            except Exception as exc:  # noqa: BLE001 — fail THIS request
                self._pool.free(blocks)
                self._np_table[row] = 0
                self._fail(req, exc)
                rows.insert(0, row)
                continue
            self._sessions[row] = session
            self._by_sid[req.sid] = session
            self.metrics.record_admit(len(req.prompt))
            if session.done:        # max_new_tokens == 1: prefill was all
                self._retire(session)
                rows.insert(0, row)
        self.metrics.set_chunk_queue(len(self._chunking))
        self._set_occupancy()
        if self._kvtier is not None:
            self._refresh_advert()

    def _admit_chunked(self, req, need, rows):
        """Admit the head-of-line request onto the chunked path: attach
        the resident prefix (refcounted, suffix-only prefill), allocate
        the rest as private blocks, queue the session for chunk steps.
        Returns False when the pool cannot serve it yet."""
        length = len(req.prompt)
        matched, tier_hit, tier_s = [], None, 0.0
        if self.prefix_caching:
            # never match the whole prompt: the first output token
            # needs the hidden state at position length-1, which only
            # a prefill of >= 1 suffix token computes
            keys = key_chain(req.prompt, self.block_size,
                             kv_dtype=self.kv_dtype)[:(length - 1) //
                                                     self.block_size]
            hbm_matched = self._pool.acquire_prefix(keys)
            matched = list(hbm_matched)
            if self._kvtier is not None and len(matched) < len(keys):
                self._readmit_bytes = 0
                t_tier = time.perf_counter()
                matched, tier_hit = self._extend_from_tiers(keys,
                                                            matched)
                tier_s = time.perf_counter() - t_tier
            if tier_hit is None and matched:
                tier_hit = "hbm"
        private = self._pool.alloc(need - len(matched))
        if private is None:
            if matched:
                self._pool.release(matched)
            return False
        self._pending.popleft()
        row = rows.pop(0)
        session = _Session(req, row, list(matched) + private)
        session.shared = len(matched)
        session.tier = tier_hit
        session.prefilled = len(matched) * self.block_size
        tid = _tid(req)
        _flight.record(tid, "queue.admit", row=row, chunked=True,
                       prefix_blocks=len(matched))
        if matched:
            _flight.record(tid, "tier.hit", tier=tier_hit,
                           blocks=len(matched),
                           readmit_bytes=(self._readmit_bytes
                                          if tier_s else 0),
                           seconds=round(tier_s, 6))
        # the page-table row stays zeroed (trash) until the final chunk
        # lands: decode steps must treat this row as padding, and a
        # stray write must never touch a shared block
        self._chunking.append(session)
        self._by_sid[req.sid] = session
        self.metrics.record_admit(length,
                                  prefilled=length - session.prefilled)
        self.metrics.record_prefix(len(matched))
        return True

    def _chunk_step(self):
        """Advance the oldest prefilling session by ONE chunk through
        the warm chunk executable; on the final chunk the session
        becomes a decode row."""
        session = self._chunking.popleft()
        req = session.req
        length = len(req.prompt)
        start = session.prefilled
        end = min(start + self.chunk_tokens, length)
        tokens = numpy.zeros(self.chunk_tokens, numpy.int32)
        tokens[:end - start] = req.prompt[start:end]
        block_row = numpy.zeros(self.max_blocks, numpy.int32)
        block_row[:len(session.blocks)] = session.blocks
        run = self._get_chunk_exe()
        t0 = time.perf_counter()
        try:
            first, self._k_pools, self._v_pools = run(
                tokens, numpy.int32(start), numpy.int32(length),
                self._k_pools, self._v_pools, block_row)
            if end >= length:
                first = int(first)   # D2H sync only on the final chunk
        except Exception as exc:  # noqa: BLE001 — fail THIS request
            self._by_sid.pop(req.sid, None)
            self._release_session_blocks(session, publish=False)
            self._fail(req, exc)
            return
        # per-token stand-in cost: a chunk blocks the loop only for its
        # OWN tokens (and resident prefix tokens cost nothing at all)
        delay = getattr(self.model, "prefill_host_delay", 0)
        if delay:
            time.sleep(delay * (end - start))
        dt = time.perf_counter() - t0
        session.prefilled = end
        self.metrics.record_chunk()
        events.span("serving.prefill_chunk", dt, model=self.name,
                    start=int(start), prompt_tokens=int(length))
        _flight.record(_tid(req), "prefill.chunk",
                       seconds=round(dt, 6), start=int(start),
                       end=int(end), prompt_tokens=int(length))
        if end < length:
            self._chunking.append(session)
            return
        session.length = length
        session.next_input = first
        session.generated.append(first)
        session.first_token_s = time.perf_counter() - req.enqueued
        self._np_table[session.row, :] = 0
        self._np_table[session.row, :len(session.blocks)] = \
            session.blocks
        self._np_lengths[session.row] = length
        self._np_tokens[session.row] = first
        self._sessions[session.row] = session
        self.metrics.record_first_token(
            session.first_token_s,
            resident=session.shared * self.block_size / length,
            tier=session.tier)
        _flight.record(_tid(req), "first_token",
                       ttft_s=round(session.first_token_s, 6),
                       resident_blocks=session.shared,
                       tier=session.tier)
        self._publish_prompt(session)
        if session.done:            # max_new_tokens == 1
            self._retire(session)
        self.metrics.set_chunk_queue(len(self._chunking))

    # -- prefix publication / block release ----------------------------------
    def _publish_prompt(self, session):
        """Make the session's full PROMPT blocks addressable the moment
        its prefill completes — sequences arriving while it decodes
        already match them."""
        if not self.prefix_caching:
            return
        keys = key_chain(session.req.prompt, self.block_size,
                         kv_dtype=self.kv_dtype)
        for i, key in enumerate(keys):
            block = session.blocks[i]
            if not self._pool.is_shared(block):
                # first writer wins; on a key collision ours stays a
                # private copy and dies with the session
                self._pool.publish(block, key)

    def _publish_history(self, session):
        """At successful retire, publish the full blocks of the entire
        history (prompt + generated) — a multi-turn follow-up that
        re-submits this conversation attaches to them."""
        history = list(session.req.prompt) + session.generated[:-1]
        keys = key_chain(history, self.block_size,
                         kv_dtype=self.kv_dtype)
        for i, key in enumerate(keys):
            if i >= len(session.blocks):
                break
            block = session.blocks[i]
            if not self._pool.is_shared(block):
                self._pool.publish(block, key)

    def _release_session_blocks(self, session, publish):
        """Give a leaving session's blocks back: shared ones drop a
        reference (content stays resident), private ones return to the
        free list — optionally publishing the history first so the
        content remains addressable."""
        if not self.prefix_caching:
            self._pool.free(session.blocks)
            return
        if publish:
            self._publish_history(session)
        shared = [b for b in session.blocks if self._pool.is_shared(b)]
        private = [b for b in session.blocks
                   if not self._pool.is_shared(b)]
        if shared:
            self._pool.release(shared)
        if private:
            self._pool.free(private)

    # -- tiered KV cache (veles_tpu/kvtier) ----------------------------------
    def _resolve_kvtier(self, kvtier):
        if not kvtier:
            return None
        if not self.prefix_caching:
            raise ValueError(
                "kvtier requires prefix_caching=True — only "
                "content-addressed chains can demote and readmit")
        from ..kvtier import DIR_ENV, TieredKVStore
        if isinstance(kvtier, TieredKVStore):
            if kvtier.observer is None:
                kvtier.observer = self.metrics
            return kvtier
        cfg = dict(kvtier)
        disk_dir = cfg.get("disk_dir")
        if disk_dir is True:
            disk_dir = os.environ.get(DIR_ENV)
            if not disk_dir:
                raise ValueError(
                    "kvtier disk tier requested but %s is not set "
                    "(the fleet supervisor exports it per replica)"
                    % DIR_ENV)
        return TieredKVStore(host_bytes=int(cfg.get("host_bytes") or 0),
                             disk_dir=disk_dir,
                             disk_bytes=int(cfg.get("disk_bytes") or 0),
                             observer=self.metrics)

    def _demote_block(self, block, key):
        """Pool eviction hook: capture the block's device contents
        (still intact — eviction only reclaims the slot) and park them
        in the tier stack.  Runs on the worker at a step boundary."""
        tree = self._jax.tree_util
        b = numpy.int64(int(block))
        gather = lambda pool: numpy.asarray(pool[b])  # noqa: E731
        payload = {
            "kv_k": tree.tree_leaves(tree.tree_map(gather,
                                                   self._k_pools)),
            "kv_v": tree.tree_leaves(tree.tree_map(gather,
                                                   self._v_pools)),
        }
        from .sessions import pack_block
        self._kvtier.demote(key, pack_block(payload))

    def _extend_from_tiers(self, keys, matched):
        """Continue an :meth:`KVBlockPool.acquire_prefix` match down
        the tier stack: each further chain key found in host RAM or on
        disk is scattered back into a fresh HBM block, published under
        its key, and attached to the session — the readmit that makes
        'evicted from every HBM pool' cost zero re-prefill.  Returns
        (matched_blocks, deepest_tier_hit)."""
        from .sessions import unpack_block
        tree = self._jax.tree_util
        jnp = self._jax.numpy
        deepest = None
        for key in keys[len(matched):]:
            found = self._kvtier.lookup(key)
            if found is None:
                break
            tier, data = found
            self._readmit_bytes += len(data)
            alloc = self._pool.alloc(1)
            if alloc is None:
                break                # pool full: prefill the rest
            block = alloc[0]
            payload = unpack_block(data)
            structure = tree.tree_structure(self._k_pools)
            scatter = lambda pool, host: pool.at[block].set(  # noqa: E731
                jnp.asarray(host))
            self._k_pools = tree.tree_map(
                scatter, self._k_pools,
                tree.tree_unflatten(structure, payload["kv_k"]))
            self._v_pools = tree.tree_map(
                scatter, self._v_pools,
                tree.tree_unflatten(structure, payload["kv_v"]))
            if not self._pool.publish(block, key):
                # key got resident between the miss and now (cannot
                # happen on the single worker, but stay safe): drop our
                # copy and attach to the resident one
                self._pool.free([block])
                revived = self._pool.acquire_prefix([key])
                if not revived:
                    break
                block = revived[0]
            matched.append(block)
            if deepest != "disk":
                deepest = tier
        return matched, deepest

    def _refresh_advert(self):
        """Rebuild the resident-chain advertisement (the ``kv_tiers``
        payload :meth:`load` piggybacks on the router's /readyz poll)
        when residency actually changed.  Keys travel truncated-hex,
        capped per tier — the router only needs enough to rank
        replicas, not the full index."""
        from ..kvtier import advert_key
        cap = 256
        sig = (self._pool.published_blocks, self._pool.evicted_blocks,
               self._kvtier.version)
        if sig == self._advert_sig:
            return
        self._advert_sig = sig
        advert = {"hbm": sorted(advert_key(k) for k in
                                self._pool.resident_keys())[:cap]}
        for tier, keys in self._kvtier.resident_keys().items():
            advert[tier] = sorted(advert_key(k) for k in keys)[:cap]
        self._advert = advert
        used = self._kvtier.used_bytes()
        self.metrics.set_tier_bytes(**used)

    def _prefill(self, session):
        req = session.req
        length = len(req.prompt)
        bucket = next(b for b in self.prefill_buckets if b >= length)
        run = self._get_prefill_exe(bucket)
        tokens = numpy.zeros(bucket, numpy.int32)
        tokens[:length] = req.prompt
        block_row = numpy.zeros(self.max_blocks, numpy.int32)
        block_row[:len(session.blocks)] = session.blocks
        t0 = time.perf_counter()
        first, self._k_pools, self._v_pools = run(
            tokens, numpy.int32(length), self._k_pools, self._v_pools,
            block_row)
        first = int(first)
        # stand-in hook (the ``sleep:`` philosophy): pin prefill wall
        # time per PROMPT TOKEN so monolithic-vs-chunked head-of-line
        # blocking is measurable without XLA cost
        delay = getattr(self.model, "prefill_host_delay", 0)
        if delay:
            time.sleep(delay * length)
        dt = time.perf_counter() - t0
        session.length = length
        session.next_input = first
        session.generated.append(first)
        session.first_token_s = time.perf_counter() - req.enqueued
        self._np_table[session.row, :] = 0
        self._np_table[session.row, :len(session.blocks)] = \
            session.blocks
        self._np_lengths[session.row] = length
        self._np_tokens[session.row] = first
        self.metrics.record_first_token(session.first_token_s)
        events.span("serving.prefill", dt, model=self.name,
                    bucket=int(bucket), prompt_tokens=int(length))
        tid = _tid(req)
        _flight.record(tid, "prefill.chunk", seconds=round(dt, 6),
                       start=0, end=int(length),
                       prompt_tokens=int(length))
        _flight.record(tid, "first_token",
                       ttft_s=round(session.first_token_s, 6))

    # -- the per-token step --------------------------------------------------
    def _step(self):
        run = self._get_decode_exe()
        t0 = time.perf_counter()
        next_tokens, self._k_pools, self._v_pools = run(
            self._k_pools, self._v_pools, self._np_table,
            self._np_lengths, self._np_tokens)
        next_tokens = numpy.asarray(next_tokens)     # D2H sync point
        # stand-in hook (mirrors the fleet's ``sleep:`` philosophy): a
        # test model can pin per-step wall time so migration drills get
        # a real mid-generation window without XLA cost
        delay = getattr(self.model, "step_host_delay", 0)
        if delay:
            time.sleep(delay)
        dt = time.perf_counter() - t0
        active = list(self._sessions.values())
        step_rows = []
        for session in active:
            token = int(next_tokens[session.row])
            session.length += 1              # the fed token is now cached
            session.generated.append(token)
            session.next_input = token
            self._np_lengths[session.row] = session.length
            self._np_tokens[session.row] = token
            step_rows.append((_tid(session.req),
                              len(session.generated)))
            if session.done:
                self._retire(session)
        # one lock acquisition for the whole batch; the fair per-row
        # share (dt / active rows) is computed inside
        _flight.record_step_rows(step_rows, dt)
        self.metrics.record_step(len(active), self.max_batch, dt)

    def _spec_step(self):
        """One speculative iteration: draft k tokens per row, verify
        all k+1 fed positions in one batched pass, accept greedily.

        The verify output at position ``i`` is the target's next token
        given the history plus the fed tokens ``0 .. i`` — so the
        longest prefix where ``draft[i] == out[i - 1]`` consists of
        tokens plain decode would have emitted, and ``out[m]`` (the
        correction) is the target's own token after them.  Every
        emitted token is therefore exactly the plain-decode stream;
        speculation only changes how many arrive per iteration.

        Rollback: the verify pass wrote K/V at ``length .. length+k``,
        but ``length`` only advances over the emitted tokens — the
        positions past it stay masked by the kernel (and the toy
        model's gather) until the next iteration overwrites them.
        Because length never covers rejected content, history
        publication (:meth:`_publish_history`), export and
        ``checkpoint_kv`` can never leak it.
        """
        k = self.spec_depth
        draft_run = self._get_draft_exe()
        verify_run = self._get_verify_exe()
        t0 = time.perf_counter()
        drafts = numpy.asarray(draft_run(
            self._k_pools, self._v_pools, self._np_table,
            self._np_lengths, self._np_tokens))          # [B, k]
        ddelay = getattr(self.model, "draft_host_delay", 0)
        if ddelay:
            time.sleep(ddelay)
        ddt = time.perf_counter() - t0
        fed = numpy.concatenate(
            [self._np_tokens[:, None], drafts],
            axis=1).astype(numpy.int32)                  # [B, k+1]
        t1 = time.perf_counter()
        out, self._k_pools, self._v_pools = verify_run(
            self._k_pools, self._v_pools, self._np_table,
            self._np_lengths, fed)
        out = numpy.asarray(out)                         # D2H sync
        delay = getattr(self.model, "step_host_delay", 0)
        if delay:
            time.sleep(delay)
        vdt = time.perf_counter() - t1
        active = list(self._sessions.values())
        accepted_total = emitted_total = 0
        draft_share = ddt / max(len(active), 1)
        verify_share = vdt / max(len(active), 1)
        for session in active:
            row = session.row
            accepted = 0
            while (accepted < k and
                   int(drafts[row, accepted]) == int(out[row, accepted])):
                accepted += 1
            remaining = (session.req.max_new_tokens
                         - len(session.generated))
            emit = [int(t) for t in out[row, :accepted + 1][:remaining]]
            # roll back every written-but-unemitted position (rejected
            # drafts + accepted tail past the token budget)
            self._pool.note_draft_rollback(k + 1 - len(emit))
            for token in emit:
                session.length += 1      # the fed token is now cached
                session.generated.append(token)
            session.next_input = emit[-1]
            self._np_lengths[row] = session.length
            self._np_tokens[row] = session.next_input
            accepted_total += accepted
            emitted_total += len(emit)
            _flight.record(_tid(session.req), "spec.step",
                           step=len(session.generated), drafted=k,
                           accepted=accepted, emitted=len(emit),
                           draft_share_s=round(draft_share, 6),
                           verify_share_s=round(verify_share, 6))
            if session.done:
                self._retire(session)
        rejected_total = len(active) * k - accepted_total
        self._spec_drafted += len(active) * k
        self._spec_accepted += accepted_total
        self._spec_rejected += rejected_total
        self.metrics.record_draft(len(active), k, ddt)
        self.metrics.record_verify(len(active), k + 1, accepted_total,
                                   rejected_total, vdt)
        # record_step's token accounting counts EMITTED tokens: one per
        # active row like plain decode, plus the extra accepted ones
        self.metrics.record_step(len(active), self.max_batch, vdt)
        extra = emitted_total - len(active)
        if extra > 0:
            self.metrics.record_extra_tokens(extra)

    def _retire(self, session, error=None):
        self._sessions.pop(session.row, None)
        self._by_sid.pop(session.req.sid, None)
        self._release_session_blocks(session, publish=error is None)
        self._np_table[session.row, :] = 0
        self._np_lengths[session.row] = 0
        self._np_tokens[session.row] = 0
        future = session.req.future
        tid = _tid(session.req)
        if error is not None:
            self.metrics.record_complete(len(session.generated),
                                         ok=False)
            _flight.record(tid, "retire",
                           tokens=len(session.generated),
                           error=type(error).__name__)
            _flight.anomaly(tid, "error",
                            error=type(error).__name__)
            _flight.finish(tid, status="error")
            if future.set_running_or_notify_cancel():
                future.set_exception(error)
        else:
            self.metrics.record_complete(len(session.generated))
            tokens = len(session.generated)
            per_token = None
            if session.first_token_s is not None and tokens > 1:
                total_s = time.perf_counter() - session.req.enqueued
                per_token = max(0.0, total_s - session.first_token_s) \
                    / (tokens - 1)
            _flight.record(tid, "retire", tokens=tokens,
                           session=session.req.sid)
            _flight.finish(tid, status="ok",
                           ttft_s=session.first_token_s,
                           per_token_s=per_token)
            result = {
                "tokens": [int(t) for t in session.generated],
                "prompt_tokens": len(session.req.prompt),
                "ttft_s": round(session.first_token_s, 6),
                "session_id": session.req.sid,
            }
            # keep the result for re-attach: a migrated session's
            # follow-up (or a router retry) may arrive AFTER completion
            self._finished[session.req.sid] = result
            while len(self._finished) > _FINISHED_KEEP:
                self._finished.popitem(last=False)
            if future.set_running_or_notify_cancel():
                future.set_result(result)
        self._release()

    # -- KV checkpoint / restore ---------------------------------------------
    def _run_job(self, fn, timeout=120.0):
        """Ship ``fn`` to the worker thread and wait for its result
        (the worker runs jobs only at step boundaries)."""
        if self._closed:
            raise SchedulerClosed("decode scheduler %r is draining"
                                  % self.name)
        job = _Job(fn)
        self._queue.put(job)
        return job.future.result(timeout)

    def checkpoint_kv(self, directory, name="kv"):
        """Checkpoint the complete decode state — device K/V pools,
        page table mirrors, block-pool accounting and every live
        session's token state — as a sharded checkpoint under
        ``directory``.  Runs on the worker at a step boundary, so the
        captured state is a consistent token-step cut.  Returns the
        checkpoint path."""
        return self._run_job(lambda: self._checkpoint_kv(directory,
                                                         name))

    def restore_kv(self, path):
        """Load a :meth:`checkpoint_kv` checkpoint into this (idle)
        scheduler and resume its sequences mid-generation.  Geometry
        must match.  Returns ``{row: Future}`` — the futures of the
        resumed sequences (their original futures died with the old
        process).  Decoding continues immediately; the restored
        sequences emit exactly the tokens the uninterrupted run would
        have."""
        return self._run_job(lambda: self._restore_kv(path))

    def _checkpoint_kv(self, directory, name):
        from ..checkpoint import save_state
        # finish in-flight chunked prefills first: a session with half
        # a prompt in the pool has no consistent cut to save
        while self._chunking:
            self._chunk_step()
        state = {
            "geometry": {
                "max_batch": self.max_batch,
                "block_size": self.block_size,
                "max_prompt_len": self.max_prompt_len,
                "max_new_tokens": self.max_new_tokens,
                "num_blocks": self._pool.num_blocks,
                "prefix_caching": self.prefix_caching,
                "kv_dtype": self.kv_dtype,
            },
            "k_pools": self._k_pools,
            "v_pools": self._v_pools,
            "table": self._np_table.copy(),
            "lengths": self._np_lengths.copy(),
            "tokens": self._np_tokens.copy(),
            "pool": self._pool.state_dict(),
            "sessions": [{
                "row": int(s.row),
                "blocks": [int(b) for b in s.blocks],
                "length": int(s.length),
                "next_input": int(s.next_input),
                "generated": [int(t) for t in s.generated],
                "first_token_s": float(s.first_token_s or 0.0),
                "prompt": numpy.array(s.req.prompt),
                "max_new_tokens": int(s.req.max_new_tokens),
                "shared": int(s.shared),
            } for s in self._sessions.values()],
        }
        return save_state(directory, name, state,
                          meta={"kind": "decode_kv",
                                "scheduler": self.name})

    def _restore_kv(self, path):
        from ..checkpoint import load_state
        if self._sessions or self._pending:
            raise RuntimeError(
                "restore_kv into a busy scheduler (restore before "
                "serving traffic)")
        state = load_state(path)
        geo = dict(state["geometry"])
        # dtype first, and by name: restoring int8 blocks into f32
        # pools (or vice versa) would silently reinterpret quantized
        # bytes — refuse with the reason, not a generic geometry diff
        ck_dtype = str(geo.pop("kv_dtype", "f32"))
        if ck_dtype != self.kv_dtype:
            raise ValueError(
                "kv_dtype mismatch: checkpoint holds %s pools but "
                "this scheduler serves %s" % (ck_dtype, self.kv_dtype))
        mine = {"max_batch": self.max_batch,
                "block_size": self.block_size,
                "max_prompt_len": self.max_prompt_len,
                "max_new_tokens": self.max_new_tokens,
                "num_blocks": self._pool.num_blocks,
                "prefix_caching": self.prefix_caching}
        if geo != mine:
            raise ValueError("geometry mismatch: checkpoint %s vs "
                             "scheduler %s" % (geo, mine))
        jnp = self._jax.numpy
        self._k_pools = self._jax.tree_util.tree_map(
            jnp.asarray, state["k_pools"])
        self._v_pools = self._jax.tree_util.tree_map(
            jnp.asarray, state["v_pools"])
        self._np_table[:] = state["table"]
        self._np_lengths[:] = state["lengths"]
        self._np_tokens[:] = state["tokens"]
        self._pool.load_state(state["pool"])
        futures = {}
        for saved in state["sessions"]:
            req = _Request(numpy.asarray(saved["prompt"], numpy.int32),
                           saved["max_new_tokens"])
            session = _Session(req, int(saved["row"]),
                               [int(b) for b in saved["blocks"]])
            session.length = int(saved["length"])
            session.next_input = int(saved["next_input"])
            session.generated = [int(t) for t in saved["generated"]]
            session.first_token_s = saved["first_token_s"]
            session.shared = int(saved.get("shared", 0))
            self._sessions[session.row] = session
            with self._depth_lock:
                self._depth += 1
            futures[session.row] = req.future
        self._set_occupancy()
        return futures

    # -- live session migration ----------------------------------------------
    # Per-SEQUENCE checkpointing on the checkpoint_kv pytree path: a
    # session's state at a step boundary is its token bookkeeping plus
    # the K/V contents of ITS blocks (gathered host-side), which makes
    # a mid-generation sequence portable to any peer scheduler with the
    # same block size — there it is just another row in the running
    # batch (the ragged paged layout's whole point).  Export PARKS the
    # original request future instead of completing it: the source only
    # answers (with a "migrated" redirect marker) after release_migrated
    # confirms the target imported, so the client's follow-up can never
    # race an import that failed.

    def export_sessions(self, session_ids=None):
        """Export live sessions (all, or the given ids) as portable
        state dicts at a step boundary.  Exported sessions leave this
        scheduler (rows and blocks freed, futures parked) — follow with
        :meth:`import_sessions` on a peer and :meth:`release_migrated`
        here, or re-import locally to abort."""
        return self._run_job(lambda: self._export_sessions(session_ids))

    def import_sessions(self, states):
        """Adopt exported sessions mid-generation.  Imports each state
        independently; returns ``(imported_ids, errors)`` where errors
        is ``[(sid, reason), ...]`` — the caller (supervisor) releases
        the imported ones and restores the failed ones to the source."""
        def job():
            done, errors = [], []
            for state in states:
                try:
                    done.append(self._import_session(state))
                except Exception as exc:  # noqa: BLE001 — per-session
                    errors.append((str(state.get("session_id")),
                                   str(exc)))
            return done, errors
        return self._run_job(job)

    def release_migrated(self, session_ids, target=None):
        """Complete the parked futures of exported sessions with a
        ``{"migrated": True, "target": ...}`` marker — the source-side
        commit, answered only after the target imported."""
        return self._run_job(
            lambda: self._release_migrated(session_ids, target))

    def attach(self, session_id):
        """Re-attach to a session by id: ``("live", future)`` while it
        decodes (or is parked mid-migration), ``("finished", result)``
        after completion, None when unknown."""
        return self._run_job(lambda: self._attach(session_id))

    def session_ids(self):
        """Session-id snapshot: active / migrating / finished."""
        return self._run_job(lambda: {
            "active": sorted(self._by_sid)
            + sorted(r.sid for r in self._pending),
            "migrating": sorted(self._migrating),
            "finished": list(self._finished)})

    def kv_dump(self):
        """Live-pool introspection for tools/kv_inspect.py: resident
        prefixes, refcounts, dedupe ratio and an integrity verdict —
        captured on the worker at a step boundary, so the snapshot is
        self-consistent."""
        return self._run_job(self._kv_dump)

    def _kv_dump(self):
        dump = self._pool.dump()
        sessions = []
        for session in (list(self._sessions.values())
                        + list(self._chunking)):
            sessions.append({
                "session_id": session.req.sid,
                "row": int(session.row),
                "blocks": [int(b) for b in session.blocks],
                "shared_blocks": int(session.shared),
                "length": int(session.length),
                "prefilled": int(session.prefilled),
            })
        problems = list(dump["integrity"])
        allocated = self._pool._live | set(self._pool._refs)
        for entry in sessions:
            missing = [b for b in entry["blocks"] if b not in allocated]
            if missing:
                problems.append("session %s references unallocated "
                                "block(s) %s"
                                % (entry["session_id"], missing))
        if self._kvtier is not None:
            problems.extend(self._kvtier.check_integrity())
            tiers = self._kvtier.stats()
            resident = {"hbm": sorted(k.hex()[:12] for k in
                                      self._pool.resident_keys())}
            for tier, keys in self._kvtier.resident_keys().items():
                resident[tier] = sorted(str(k)[:12] for k in keys)
            tiers["resident"] = resident
            dump["kvtier"] = tiers
        dump["kv_dtype"] = self.kv_dtype
        if self.kv_dtype != "f32":
            dump["quant"] = self._quant_stats()
        dump.update(model=self.name,
                    prefill_chunk_tokens=self.chunk_tokens,
                    active_sequences=len(self._sessions),
                    chunking_sessions=len(self._chunking),
                    sessions=sessions,
                    integrity=problems)
        if self.spec_depth:
            drafted = self._spec_drafted
            dump["speculation"] = {
                "spec_depth": self.spec_depth,
                "draft_tokens": drafted,
                "accepted_tokens": self._spec_accepted,
                "rejected_tokens": self._spec_rejected,
                "acceptance_rate":
                    round(self._spec_accepted / drafted, 4)
                    if drafted else None,
                "draft_rollbacks": self._pool.draft_rollbacks,
                "rolled_back_tokens": self._pool.rolled_back_tokens,
            }
        return dump

    def _quant_stats(self):
        """The ``quant`` block of :meth:`kv_dump`: per-block byte
        footprint plus scale statistics over the pools' f32 scale
        planes — how hot the quantization grid runs.  A zero scale
        marks a never-written (or wiped) block slice, so the stats
        cover the nonzero entries and report the zero fraction."""
        scales = []
        def visit(leaf):
            if isinstance(leaf, dict) and "s" in leaf:
                scales.append(numpy.asarray(leaf["s"]))
            return leaf
        self._jax.tree_util.tree_map(
            visit, (self._k_pools, self._v_pools),
            is_leaf=lambda x: isinstance(x, dict))
        out = {"kv_dtype": self.kv_dtype,
               "bytes_per_block": int(self._block_bytes)}
        if scales:
            flat = numpy.concatenate([s.reshape(-1) for s in scales])
            nz = flat[flat > 0]
            if nz.size:
                out["scales"] = {
                    "min": float(nz.min()),
                    "max": float(nz.max()),
                    "mean": float(nz.mean()),
                    "zero_fraction": round(
                        1.0 - nz.size / flat.size, 4),
                }
        return out

    def spill_session(self, session_id, directory):
        """Spill one (idle) session to a host-side sharded checkpoint
        and free its row/blocks; any waiter gets a ``{"spilled": True}``
        marker.  Re-admit later with :meth:`readmit_session`."""
        return self._run_job(
            lambda: self._spill_session(session_id, directory))

    def readmit_session(self, path, delete=True):
        """Re-admit a spilled session into the running batch; returns
        its id (collect the result via :meth:`attach`)."""
        return self._run_job(lambda: self._readmit_session(path, delete))

    def _export_sessions(self, session_ids=None):
        want = None if session_ids is None else set(session_ids)
        states = []
        for session in list(self._sessions.values()):
            if want is not None and session.req.sid not in want:
                continue
            states.append(self._export_one(session))
        # mid-prefill (chunking) sessions abandon their partial KV and
        # travel as prompt-only states — the peer prefills them from
        # scratch (or from ITS resident prefixes)
        keep_chunking = collections.deque()
        while self._chunking:
            session = self._chunking.popleft()
            if want is not None and session.req.sid not in want:
                keep_chunking.append(session)
                continue
            self._by_sid.pop(session.req.sid, None)
            self._release_session_blocks(session, publish=False)
            states.append(self._fresh_state(session.req))
            self._migrating[session.req.sid] = session.req.future
            self._release()
        self._chunking = keep_chunking
        # queued-but-unprefilled requests ride along as prompt-only
        # states (no KV yet — the peer prefills them from scratch)
        keep = collections.deque()
        while self._pending:
            req = self._pending.popleft()
            if want is not None and req.sid not in want:
                keep.append(req)
                continue
            states.append(self._fresh_state(req))
            self._migrating[req.sid] = req.future
            self._release()
        self._pending = keep
        if states:
            self.metrics.record_migrate(len(states), "out")
            self.metrics.set_occupancy(
                len(self._sessions), self._pool.live_blocks /
                max(self._pool.capacity, 1))
        return states

    def _fresh_state(self, req):
        # the timeline travels WITH the migrated session (migration is
        # an anomaly trigger and a hop the destination must attribute),
        # stitched by the trace id the destination re-adopts
        tid = _tid(req)
        state = {"session_id": req.sid,
                 "prompt": numpy.array(req.prompt),
                 "max_new_tokens": int(req.max_new_tokens),
                 "block_size": self.block_size,
                 "kv_dtype": self.kv_dtype,
                 "deadline_left_s": None if req.deadline is None
                 else max(req.deadline - time.monotonic(), 0.0)}
        if tid:
            _flight.record(tid, "migrate.export", session=req.sid,
                           model=self.name)
            _flight.anomaly(tid, "migration")
            state["trace_id"] = tid
            timeline = _flight.export(tid)
            if timeline is not None:
                state["flight"] = timeline
        return state

    def _export_one(self, session):
        req = session.req
        # with the tier stack on, the leading run of published blocks
        # travels BY HASH: the chain keys are content addresses any
        # peer can resolve against its own pool / tiers (or this
        # replica's disk tier after a respawn), so the wire carries
        # device bytes only for the private tail
        hash_keys = []
        if self._kvtier is not None:
            for b in session.blocks:
                key = self._pool.key_of(b)
                if key is None:
                    break
                hash_keys.append(key)
        tail = session.blocks[len(hash_keys):]
        blocks = numpy.asarray(tail, numpy.int64)
        tree = self._jax.tree_util
        gather = lambda pool: numpy.asarray(pool[blocks])  # noqa: E731
        state = self._fresh_state(req)
        state.update({
            "length": int(session.length),
            "next_input": int(session.next_input),
            "generated": [int(t) for t in session.generated],
            "first_token_s": float(session.first_token_s or 0.0),
            "kv_k": tree.tree_leaves(tree.tree_map(gather,
                                                   self._k_pools)),
            "kv_v": tree.tree_leaves(tree.tree_map(gather,
                                                   self._v_pools)),
        })
        if hash_keys:
            state["kv_hash"] = [k.hex() for k in hash_keys]
        self._sessions.pop(session.row, None)
        self._by_sid.pop(req.sid, None)
        self._release_session_blocks(session, publish=False)
        self._np_table[session.row, :] = 0
        self._np_lengths[session.row] = 0
        self._np_tokens[session.row] = 0
        self._migrating[req.sid] = req.future
        self._release()
        return state

    def _import_session(self, state):
        sid = str(state["session_id"])
        if sid in self._by_sid or any(r.sid == sid
                                      for r in self._pending):
            raise ValueError("session %r is already live here" % sid)
        if int(state["block_size"]) != self.block_size:
            raise ValueError(
                "block_size mismatch: session %s vs scheduler %s"
                % (state["block_size"], self.block_size))
        prompt = self.validate(numpy.asarray(state["prompt"]),
                               state["max_new_tokens"])
        deadline = None
        if state.get("deadline_left_s") is not None:
            deadline = time.monotonic() + float(state["deadline_left_s"])
        req = _Request(prompt, state["max_new_tokens"],
                       session_id=sid, deadline=deadline)
        # continue the ORIGINAL trace: the imported session's decode
        # steps must land in the same flight timeline the source
        # exported (one trace id end-to-end across the migration hop)
        tid = state.get("trace_id")
        if tid:
            req.trace = _trace.SpanContext(str(tid), _trace.new_id(),
                                           None)
            _flight.absorb(state.get("flight"))
            _flight.record(str(tid), "migrate.import", session=sid,
                           model=self.name)
        # the parked future, when this is a source-side abort/restore —
        # the original waiter stays attached through the round trip
        parked = self._migrating.pop(sid, None)
        if parked is not None:
            req.future = parked
        # prompt-only states carry no KV bytes, so they import under
        # ANY pool dtype; states with device bytes must match — int8
        # payloads scattered into f32 pools would be garbage
        if state.get("kv_k") is not None \
                and str(state.get("kv_dtype", "f32")) != self.kv_dtype:
            if parked is not None:
                self._migrating[sid] = parked
            raise ValueError(
                "kv_dtype mismatch: session %s travels %s KV blocks "
                "but this scheduler serves %s"
                % (sid, state.get("kv_dtype", "f32"), self.kv_dtype))
        if state.get("kv_k") is None:       # prompt-only: just enqueue
            self._pending.append(req)
            with self._depth_lock:
                self._depth += 1
            return sid
        rows = self._free_rows()
        # hash-referenced lead blocks resolve against local content —
        # HBM chains first, then the tier stack (which is how a session
        # migrated toward its prefix's home replica readmits for free)
        hash_hexes = [str(h) for h in state.get("kv_hash") or []]
        lead = []
        if hash_hexes:
            if not self.prefix_caching:
                if parked is not None:
                    self._migrating[sid] = parked
                raise ValueError(
                    "session %r carries hashed prefix blocks but this "
                    "scheduler has prefix_caching off" % sid)
            keys = [bytes.fromhex(h) for h in hash_hexes]
            lead = self._pool.acquire_prefix(keys)
            if self._kvtier is not None and len(lead) < len(keys):
                lead, _ = self._extend_from_tiers(keys, lead)
            if len(lead) < len(keys):
                if lead:
                    self._pool.release(lead)
                if parked is not None:
                    self._migrating[sid] = parked
                raise ValueError(
                    "cannot resolve hashed prefix of session %r "
                    "(%d/%d chain keys resident)"
                    % (sid, len(lead), len(hash_hexes)))
        n_blocks = int(numpy.shape(state["kv_k"][0])[0])
        blocks = self._pool.alloc(n_blocks) if rows else None
        if blocks is None:
            if lead:
                self._pool.release(lead)
            if parked is not None:          # re-park: caller may retry
                self._migrating[sid] = parked
            raise RuntimeError(
                "no capacity to import session %r (%d blocks, %d free; "
                "%d rows free)" % (sid, n_blocks,
                                   self._pool.free_blocks, len(rows)))
        tree = self._jax.tree_util
        jnp = self._jax.numpy
        blocks_arr = numpy.asarray(blocks, numpy.int64)
        structure = tree.tree_structure(self._k_pools)
        scatter = lambda pool, host: pool.at[blocks_arr].set(  # noqa: E731
            jnp.asarray(host))
        self._k_pools = tree.tree_map(
            scatter, self._k_pools,
            tree.tree_unflatten(structure, state["kv_k"]))
        self._v_pools = tree.tree_map(
            scatter, self._v_pools,
            tree.tree_unflatten(structure, state["kv_v"]))
        row = rows.pop(0)
        session = _Session(req, row, list(lead) + blocks)
        session.shared = len(lead)
        session.length = int(state["length"])
        session.next_input = int(state["next_input"])
        session.generated = [int(t) for t in state["generated"]]
        session.first_token_s = float(state["first_token_s"])
        self._np_table[row, :] = 0
        self._np_table[row, :len(session.blocks)] = session.blocks
        self._np_lengths[row] = session.length
        self._np_tokens[row] = session.next_input
        self._sessions[row] = session
        self._by_sid[sid] = session
        with self._depth_lock:
            self._depth += 1
        self.metrics.record_migrate(1, "in")
        self._set_occupancy()
        return sid

    def _release_migrated(self, session_ids, target):
        released = []
        for sid in session_ids:
            future = self._migrating.pop(sid, None)
            if future is None:
                continue
            if future.set_running_or_notify_cancel():
                future.set_result({"migrated": True, "session_id": sid,
                                   "target": target})
            released.append(sid)
        return released

    def _attach(self, session_id):
        sid = str(session_id)
        session = self._by_sid.get(sid)
        if session is not None:
            return "live", session.req.future
        for req in self._pending:
            if req.sid == sid:
                return "live", req.future
        if sid in self._migrating:
            return "live", self._migrating[sid]
        if sid in self._finished:
            return "finished", self._finished[sid]
        return None

    def _spill_session(self, session_id, directory):
        from ..checkpoint import save_state
        sid = str(session_id)
        session = self._by_sid.get(sid)
        if session is None:
            raise KeyError("no live session %r to spill" % sid)
        state = self._export_one(session)
        path = save_state(directory, "session-" + sid, state,
                          meta={"kind": "decode_session",
                                "scheduler": self.name,
                                "session_id": sid})
        future = self._migrating.pop(sid, None)
        if future is not None and future.set_running_or_notify_cancel():
            future.set_result({"spilled": True, "session_id": sid,
                               "path": str(path)})
        events.event("serving.session_spill", model=self.name,
                     session=sid)
        return str(path)

    def _readmit_session(self, path, delete):
        from ..checkpoint import delete_checkpoint, load_state
        state = load_state(path)
        sid = self._import_session(state)
        if delete:
            delete_checkpoint(path)
        events.event("serving.session_readmit", model=self.name,
                     session=sid)
        return sid

    # -- lifecycle / introspection -------------------------------------------
    def close(self, drain=True, timeout=30.0):
        """Stop accepting; with ``drain`` every already-submitted
        request finishes (admitted sequences run out, queued ones still
        get admitted as rows free), else cancel everything."""
        if self._closed:
            return
        self._closed = True
        if not drain:
            self._abort = True
        self._queue.put(_STOP)
        self._worker.join(timeout)
        # late racers that slipped past the closed flag
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                self._fail(item, SchedulerClosed("scheduler shut down"))

    @property
    def queue_depth(self):
        return self._depth

    @property
    def active_sequences(self):
        return len(self._sessions)

    @property
    def ready(self):
        """True once the decode step and the whole prefill ladder are
        compiled — the ``GET /readyz`` / fleet-admission signal."""
        return self._warmed and not self._closed

    def load(self):
        """Cheap backpressure snapshot for routers (int/float reads
        only — poll-safe)."""
        depth = self._depth
        out = {"kind": "decode",
               "queue_depth": depth,
               "queue_limit": self.queue_limit,
               "utilization": round(depth / self.queue_limit, 4),
               "active_rows": len(self._sessions),
               "chunking_sessions": len(self._chunking),
               "kv_occupancy": round(
                   self._pool.live_blocks /
                   max(self._pool.capacity, 1), 4)}
        advert = self._advert
        if advert is not None:
            # resident-chain advertisement: rides the router's /readyz
            # load poll into its fleet-wide prefix directory (the
            # cache-aware routing input) — a plain attribute read of a
            # snapshot the worker swaps in whole, so still poll-safe
            out["kv_tiers"] = advert
        return out

    def retry_after_s(self, cap=30):
        """Computed ``Retry-After`` for shed generate requests: gangs
        of queued sequences ahead x the tokens each must stream x the
        recent per-step wall time."""
        step_p50 = self.metrics.step_latency.summary().get("p50_ms")
        if not step_p50:
            return 1
        gangs_ahead = -(-self._depth // self.max_batch)  # ceil
        est = gangs_ahead * self.max_new_tokens * (step_p50 / 1e3)
        return max(1, min(int(cap), int(est + 0.999)))

    def stats(self):
        """Zero-recompile evidence + occupancy, BucketScheduler-shaped
        (``compiles`` = fresh XLA only; warm restarts show 0)."""
        pool = self._pool.stats()
        out = {
            "buckets": list(self.prefill_buckets),
            "executables": (1 if self._decode_exe is not None else 0)
            + (1 if self._chunk_exe is not None else 0)
            + (1 if self._draft_exe is not None else 0)
            + (1 if self._verify_exe is not None else 0)
            + len(self._prefill_exes),
            "compiles": self._compiles,
            "cache_hits": self._cache_hits,
            "compile_seconds": round(self._compile_seconds, 4),
            "warmup_compiles": self._warmup_compiles,
            "post_warmup_compiles": self._compiles -
            self._warmup_compiles,
            "queue_depth": self._depth,
            "queue_limit": self.queue_limit,
            "max_batch": self.max_batch,
            "config_source": self.config_source,
            "active_sequences": len(self._sessions),
            "migrating_sessions": len(self._migrating),
            "block_size": self.block_size,
            "num_blocks": pool["num_blocks"],
            "free_blocks": pool["free_blocks"],
            "kv_utilization": pool["utilization"],
            "kv_dtype": self.kv_dtype,
            "block_bytes": int(self._block_bytes),
            "kv_bytes_resident": int(
                (self._pool.live_blocks + self._pool.cached_blocks)
                * self._block_bytes),
            "max_prompt_len": self.max_prompt_len,
            "max_new_tokens": self.max_new_tokens,
            "prefix_caching": self.prefix_caching,
            "prefill_chunk_tokens": self.chunk_tokens,
            "chunking_sessions": len(self._chunking),
            "ready": self.ready,
            "closed": self._closed,
        }
        if self._chunk_source is not None:
            out["chunk_source"] = self._chunk_source
        if self._kv_dtype_source is not None:
            out["kv_dtype_source"] = self._kv_dtype_source
        if self.spec_depth:
            drafted = self._spec_drafted
            out.update(
                spec_depth=self.spec_depth,
                spec_source=self._spec_source,
                draft_tokens=drafted,
                accepted_tokens=self._spec_accepted,
                rejected_tokens=self._spec_rejected,
                acceptance_rate=round(self._spec_accepted / drafted, 4)
                if drafted else None,
                rolled_back_tokens=self._pool.rolled_back_tokens)
        if self.prefix_caching:
            out.update(prefix_hits=pool["prefix_hits"],
                       dedup_blocks=pool["dedup_blocks"],
                       published_blocks=pool["published_blocks"],
                       evicted_blocks=pool["evicted_blocks"],
                       shared_blocks=pool["shared_blocks"],
                       cached_blocks=pool["cached_blocks"])
        if self._kvtier is not None:
            out["kvtier"] = self._kvtier.stats()
        return out
