"""Trace-context propagation: one ``trace_id`` across processes.

The reference platform's event stream was causally flat — every node
logged to the shared Mongo collection and correlation was by timestamp.
Here a *trace context* (``trace_id`` + span ids) travels with the work:

- in-process via a thread-local span stack (plus a process-wide ambient
  context adopted from the environment), automatically stamped onto
  every :class:`~veles_tpu.logger.EventLog` record;
- master → worker via the jobserver protocol (``"trace"`` field on job
  messages, :mod:`veles_tpu.jobserver`);
- parent → CLI-trial subprocess via the ``VELES_TRACE_CONTEXT`` env var
  (:func:`inject_env` / :func:`adopt_env`, used by ``subproc.run_trial``
  and ``distributed.ElasticRunner``);
- HTTP request → batch → executable in serving (``X-Trace-Id`` header,
  serving/server.py → scheduler batch spans).

Each process still writes its own ``events-<pid>.jsonl``; because the
records share one ``trace_id``, ``tools/merge_traces.py`` folds them
into a single chrome://tracing / Perfetto timeline of the whole
distributed run.  Setting ``VELES_TRACE_DIR`` enables tracing in any
veles_tpu process (workers inherit it with zero plumbing).

Stdlib-only; importable from anywhere without cycles.
"""

import contextlib
import os
import threading
import uuid

__all__ = ["new_id", "current", "span_context", "adopt", "payload",
           "http_headers", "inject_env", "adopt_env", "set_ambient",
           "TRACE_ENV"]

#: env var carrying "trace_id:parent_span" across process boundaries
TRACE_ENV = "VELES_TRACE_CONTEXT"

_local = threading.local()
_ambient = None      # process-wide fallback (set once from the env)


class SpanContext:
    """One active span: ids only — timing stays with the EventLog."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id, span_id, parent_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def __repr__(self):
        return "<span %s/%s parent=%s>" % (self.trace_id, self.span_id,
                                           self.parent_id)


def new_id():
    """A fresh 64-bit hex id (trace or span)."""
    return uuid.uuid4().hex[:16]


def _stack():
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def current():
    """The innermost active :class:`SpanContext` (thread-local first,
    then the process ambient context), or None."""
    stack = _stack()
    if stack:
        return stack[-1]
    return _ambient


def set_ambient(trace_id, parent_span=None):
    """Install a process-wide fallback context (e.g. adopted from the
    spawning master via the environment)."""
    global _ambient
    _ambient = SpanContext(trace_id, parent_span or new_id(), None) \
        if trace_id else None
    return _ambient


@contextlib.contextmanager
def span_context(trace_id=None, parent=None):
    """Push a new span: child of the current context unless overridden."""
    cur = current()
    tid = trace_id or (cur.trace_id if cur else new_id())
    pid = parent if parent is not None else \
        (cur.span_id if cur and tid == cur.trace_id else None)
    ctx = SpanContext(tid, new_id(), pid)
    stack = _stack()
    stack.append(ctx)
    try:
        yield ctx
    finally:
        stack.pop()


def payload(ctx=None):
    """Wire form of ``ctx`` (default: current) for protocol messages;
    the receiver's spans become CHILDREN of this span.  None when no
    context is active."""
    ctx = ctx or current()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "parent_span": ctx.span_id}


@contextlib.contextmanager
def adopt(wire):
    """Enter the remote context described by a :func:`payload` dict
    (no-op passthrough for None/garbage — a traceless peer must not
    break the receiver)."""
    if not isinstance(wire, dict) or not wire.get("trace_id"):
        yield None
        return
    with span_context(trace_id=str(wire["trace_id"]),
                      parent=wire.get("parent_span")) as ctx:
        yield ctx


def http_headers(ctx=None):
    """HTTP form of ``ctx`` (default: current) — the headers an
    in-process hop (fleet router → replica) forwards so the receiving
    server's request span joins the same trace.  Empty when no context
    is active."""
    ctx = ctx or current()
    if ctx is None:
        return {}
    return {"X-Trace-Id": ctx.trace_id}


def inject_env(env=None):
    """Return ``env`` (default: a copy of os.environ) with the current
    context encoded for a child process; unchanged when no context."""
    ctx = current()
    if ctx is None:
        return env
    env = dict(os.environ if env is None else env)
    env[TRACE_ENV] = "%s:%s" % (ctx.trace_id, ctx.span_id)
    return env


def adopt_env(environ=None):
    """Adopt :data:`TRACE_ENV` from the environment as the process
    ambient context (call once at process startup).  Returns the
    context or None."""
    raw = (environ if environ is not None else os.environ).get(TRACE_ENV)
    if not raw:
        return None
    trace_id, _, parent = raw.partition(":")
    ctx = SpanContext(trace_id, new_id(), parent or None)
    global _ambient
    _ambient = ctx
    return ctx
