"""Process-global metrics registry: counters, gauges, histograms.

The reference platform aggregated every unit's numbers into one shared
event stream (Mongo) that the web status server served back out; our
equivalent backbone is this registry — one process-global, thread-safe
store of labelled counters/gauges/histograms that BOTH the training side
(:mod:`veles_tpu.observability.profiler`) and the serving side
(:mod:`veles_tpu.serving.metrics`) record into, exposed two ways by
``StatusServer`` (web_status.py):

- ``GET /metrics`` — Prometheus text exposition (format 0.0.4), so a
  stock Prometheus/Grafana stack scrapes training and serving from the
  same endpoint;
- merged into ``GET /status`` JSON under the ``"metrics"`` key for the
  dashboard and humans.

Dependency-free (stdlib only) and safe to import from anywhere — no
veles_tpu module is imported here, which is what lets ``logger.py``,
``units.py`` and the serving stack all use it without cycles.
"""

import math
import threading
import weakref

__all__ = ["MetricsRegistry", "REGISTRY", "counter", "gauge", "histogram",
           "render_prometheus"]

#: default histogram ladder (seconds): micro-benchmark to human scale
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _escape_label(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_value(v):
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v != v:
        return "NaN"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class _Child:
    """One (metric, label-values) time series."""

    __slots__ = ("_lock", "labels")

    def __init__(self, labels):
        self._lock = threading.Lock()
        self.labels = labels            # tuple of label values


class CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, labels):
        super().__init__(labels)
        self._value = 0.0

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError("counters only go up (got %r)" % (amount,))
        with self._lock:
            self._value += amount

    @property
    def value(self):
        return self._value


class GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, labels):
        super().__init__(labels)
        self._value = 0.0

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        self.inc(-amount)

    def set_max(self, value):
        """Watermark semantics: keep the maximum ever seen."""
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self):
        return self._value


class HistogramChild(_Child):
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, labels, buckets):
        super().__init__(labels)
        self.buckets = buckets
        self.counts = [0] * len(buckets)    # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        value = float(value)
        with self._lock:
            self.sum += value
            self.count += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[i] += 1
                    break

    def snapshot(self):
        with self._lock:
            return {"count": self.count, "sum": round(self.sum, 6),
                    "avg": round(self.sum / self.count, 6)
                    if self.count else None}


class Metric:
    """A named metric family; ``labels(**kv)`` returns the child series."""

    def __init__(self, name, help, kind, label_names, buckets=None):
        self.name = name
        self.help = help
        self.kind = kind                    # counter | gauge | histogram
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets) if buckets else None
        self._children = {}
        self._default_child = None
        self._lock = threading.Lock()

    def labels(self, **kv):
        if set(kv) != set(self.label_names):
            raise ValueError(
                "%s expects labels %r, got %r"
                % (self.name, self.label_names, tuple(kv)))
        key = tuple(str(kv[n]) for n in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if self.kind == "counter":
                        child = CounterChild(key)
                    elif self.kind == "gauge":
                        child = GaugeChild(key)
                    else:
                        child = HistogramChild(key, self.buckets)
                    self._children[key] = child
        return child

    # label-less convenience: the metric itself acts as its only child
    # (child cached: inc() sits on serving hot paths, and labels()
    # rebuilds the key tuple + set-compares on every call)
    def _default(self):
        child = self._default_child
        if child is None:
            if self.label_names:
                raise ValueError("%s has labels %r; use .labels(...)"
                                 % (self.name, self.label_names))
            child = self.labels()
            self._default_child = child
        return child

    def inc(self, amount=1):
        self._default().inc(amount)

    def set(self, value):
        self._default().set(value)

    def observe(self, value):
        self._default().observe(value)

    @property
    def value(self):
        return self._default().value

    def children(self):
        with self._lock:
            return dict(self._children)


class MetricsRegistry:
    """Thread-safe name → :class:`Metric` map with Prometheus export.

    Metric constructors are idempotent: asking for an existing name with
    the same kind/labels returns the existing family (so modules can
    declare their metrics independently); a conflicting redeclaration
    raises — silent type drift would corrupt the exposition.
    """

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()
        # scrape-time collectors (prometheus-client custom-collector
        # style): objects whose collect_metrics() refreshes derived
        # gauges (e.g. latency quantiles over a sample window) right
        # before export.  Weak references: a dead scheduler's metrics
        # object must not be kept alive (or keep collecting) forever.
        self._collectors = weakref.WeakSet()

    def _declare(self, name, help, kind, label_names, buckets=None):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is not None:
                if metric.kind != kind or \
                        metric.label_names != tuple(label_names):
                    raise ValueError(
                        "metric %r already declared as %s%r, cannot "
                        "redeclare as %s%r" %
                        (name, metric.kind, metric.label_names, kind,
                         tuple(label_names)))
                return metric
            metric = Metric(name, help, kind, label_names, buckets)
            self._metrics[name] = metric
            return metric

    def counter(self, name, help="", labels=()):
        return self._declare(name, help, "counter", labels)

    def gauge(self, name, help="", labels=()):
        return self._declare(name, help, "gauge", labels)

    def histogram(self, name, help="", labels=(), buckets=None):
        return self._declare(name, help, "histogram", labels,
                             buckets or DEFAULT_BUCKETS)

    def register_collector(self, obj):
        """Register ``obj`` (held weakly); its ``collect_metrics()``
        runs before every export."""
        self._collectors.add(obj)
        return obj

    def _run_collectors(self):
        for obj in list(self._collectors):
            try:
                obj.collect_metrics()
            except Exception:  # noqa: BLE001 — a broken collector must
                pass           # never take down the scrape endpoint

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def metrics(self):
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    # -- export --------------------------------------------------------------
    def render_prometheus(self):
        """The full registry as Prometheus text exposition 0.0.4."""
        self._run_collectors()
        lines = []
        for metric in self.metrics():
            lines.append("# HELP %s %s" %
                         (metric.name,
                          metric.help.replace("\\", "\\\\")
                          .replace("\n", "\\n")))
            lines.append("# TYPE %s %s" % (metric.name, metric.kind))
            children = metric.children()
            for key in sorted(children):
                child = children[key]
                pairs = list(zip(metric.label_names, key))
                if metric.kind == "histogram":
                    cum = 0
                    for b, c in zip(child.buckets, child.counts):
                        cum += c
                        lines.append("%s_bucket{%s} %d" % (
                            metric.name,
                            _label_str(pairs + [("le", _format_value(
                                float(b)))]),
                            cum))
                    lines.append("%s_bucket{%s} %d" % (
                        metric.name,
                        _label_str(pairs + [("le", "+Inf")]),
                        child.count))
                    suffix = _label_str(pairs)
                    suffix = "{%s}" % suffix if suffix else ""
                    lines.append("%s_sum%s %s" % (
                        metric.name, suffix, _format_value(child.sum)))
                    lines.append("%s_count%s %d" % (
                        metric.name, suffix, child.count))
                else:
                    suffix = _label_str(pairs)
                    suffix = "{%s}" % suffix if suffix else ""
                    lines.append("%s%s %s" % (
                        metric.name, suffix, _format_value(child.value)))
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self):
        """JSON-able view for the /status merge and dashboards."""
        self._run_collectors()
        out = {}
        for metric in self.metrics():
            series = []
            children = metric.children()
            for key in sorted(children):
                child = children[key]
                entry = {"labels": dict(zip(metric.label_names, key))}
                if metric.kind == "histogram":
                    entry.update(child.snapshot())
                else:
                    entry["value"] = child.value
                series.append(entry)
            out[metric.name] = {"type": metric.kind, "help": metric.help,
                                "series": series}
        return out

    def reset(self):
        """Drop every metric (tests / forked workers)."""
        with self._lock:
            self._metrics.clear()


def _label_str(pairs):
    return ",".join('%s="%s"' % (n, _escape_label(v)) for n, v in pairs)


#: the process-global registry every subsystem records into
REGISTRY = MetricsRegistry()


def counter(name, help="", labels=()):
    return REGISTRY.counter(name, help, labels)


def gauge(name, help="", labels=()):
    return REGISTRY.gauge(name, help, labels)


def histogram(name, help="", labels=(), buckets=None):
    return REGISTRY.histogram(name, help, labels, buckets)


def render_prometheus():
    return REGISTRY.render_prometheus()
