"""StepProfiler: where does a training step actually spend its time?

The TPU-compilation literature (TVM; "Automatic Full Compilation ... to
Cloud TPUs", PAPERS.md) is unambiguous about where training-loop wins
hide: recompiles and host/device transfer stalls.  This profiler makes
both visible for any veles_tpu workflow by wrapping the two hot units —
the loader and the fused train step — and splitting every step into:

- **data-wait**: host-side minibatch preparation (the loader's run).
  With a :class:`~veles_tpu.loader.prefetch.MinibatchPrefetcher`
  attached (attach it BEFORE the profiler), the loader's run() merely
  pops the prefetch queue, so this phase measures time the step loop is
  *actually blocked* on input — the number the prefetcher exists to
  drive to zero;
- **host**: python + dispatch time of the step's ``run()`` (with XLA's
  async dispatch this is the enqueue cost, not the math);
- **device**: the remaining device-compute tail, measured by fencing on
  the step's outputs (``block_until_ready``) after dispatch returns.

Per step it also counts JAX recompiles (jit cache-size deltas across
every jitted function the step owns — an AOT-warm loop shows zero),
examples/sec over a sliding window, and per-device HBM peak watermarks.
Everything is emitted twice: into the process-global
:class:`~veles_tpu.observability.registry.MetricsRegistry` (scraped at
``/metrics``) and as ``train.step`` spans into the Chrome-trace
:class:`~veles_tpu.logger.EventLog`.

Fencing serializes the dispatch pipeline, which is precisely what makes
the breakdown honest — and is why the profiler is opt-in
(``Workflow.attach_profiler()``, ``root.common.observability.profile``)
and why ``bench.py --stage observability`` records its measured
overhead on the MNIST step loop.
"""

import collections
import time

from ..logger import events
from .registry import REGISTRY

#: examples/sec sliding window (steps)
RATE_WINDOW = 256
#: device-memory watermark poll period (steps) — memory_stats() is a
#: host call; every step would be pure overhead for a slow-moving number
MEM_POLL_STEPS = 16


def _find_step(workflow):
    step = getattr(workflow, "fused_step", None)
    if step is not None:
        return step
    controller = getattr(workflow, "graph_controller_", None)
    if controller is not None and controller.traced_unit_count:
        # whole-workflow compilation: the traced-region flush IS the
        # step — wrap it so recompiles and host/device phase slices
        # report exactly like the fused path
        return controller
    for unit in workflow:
        if getattr(unit, "view_group", None) == "TRAINER":
            return unit
    raise ValueError("no training step found in %r (pass step=...)"
                     % workflow)


def _find_loader(workflow):
    loader = getattr(workflow, "loader", None)
    if loader is not None and hasattr(loader, "run"):
        return loader
    return None


def _transient(method):
    """Wrap a bound method in a plain function marked ``transient_`` so
    ``Pickleable.__getstate__`` (and the snapshotter's deepcopy-based
    capture) drops the instrumentation instead of dragging the profiler
    — registry children, locks and all — into a snapshot."""
    def call():
        return method()
    call.transient_ = True
    return call


class StepProfiler:
    """Wraps ``loader.run``/``step.run`` of one workflow with timing,
    recompile and memory accounting.  ``detach()`` restores both."""

    def __init__(self, workflow=None, step=None, loader=None,
                 registry=None, fence=True, name=None):
        if step is None:
            step = _find_step(workflow)
        if loader is None and workflow is not None:
            loader = _find_loader(workflow)
        self.workflow = workflow
        self.step = step
        self.loader = loader
        self.fence = fence
        self.name = name or (workflow.name if workflow is not None
                             else type(step).__name__)
        reg = registry or REGISTRY
        lbl = {"workflow": self.name}
        self._c_steps = reg.counter(
            "veles_training_steps_total",
            "Training/eval steps executed", ("workflow",)).labels(**lbl)
        self._c_examples = reg.counter(
            "veles_training_examples_total",
            "Examples consumed by training steps",
            ("workflow",)).labels(**lbl)
        self._c_recompiles = reg.counter(
            "veles_training_recompiles_total",
            "JAX jit cache misses observed on the step's functions",
            ("workflow",)).labels(**lbl)
        self._h_phase = reg.histogram(
            "veles_training_step_phase_seconds",
            "Per-step time split: data_wait | host | device",
            ("workflow", "phase"))
        self._h_data = self._h_phase.labels(phase="data_wait", **lbl)
        self._h_host = self._h_phase.labels(phase="host", **lbl)
        self._h_device = self._h_phase.labels(phase="device", **lbl)
        self._h_snapshot = self._h_phase.labels(phase="snapshot", **lbl)
        self._g_rate = reg.gauge(
            "veles_training_examples_per_sec",
            "Sliding-window training throughput",
            ("workflow",)).labels(**lbl)
        self._g_mem = reg.gauge(
            "veles_device_peak_memory_bytes",
            "Per-device HBM peak watermark",
            ("workflow", "device"))
        # totals for summary() (per-instance; the registry children are
        # process-global and shared across same-named workflows)
        self.steps = 0
        self.examples = 0
        self.recompiles = 0
        self.data_wait_s = 0.0
        self.host_s = 0.0
        self.device_s = 0.0
        self.peak_memory = {}
        self._rate = collections.deque(maxlen=RATE_WINDOW)
        self._pending_data_wait = 0.0
        # examples come from the loader's samples_served delta when
        # available — correct on BOTH the per-minibatch path and the
        # epoch-scan path (where one run() consumes a whole class)
        self._last_served = int(getattr(loader, "samples_served", 0)
                                or 0)
        self._jits = self._discover_jits()
        self._jit_cache = self._jit_cache_size()
        self._orig_step_run = step.run
        self._orig_loader_run = loader.run if loader is not None else None
        # keep STABLE wrapper objects: detach()'s identity check must
        # compare against the exact object installed here.  Transient
        # plain-function closures, not bound methods — a snapshot taken
        # with the profiler attached must drop the wrappers, not pickle
        # the profiler (see _transient)
        self._step_wrapper = _transient(self._step_run)
        self._loader_wrapper = _transient(self._loader_run_wrapped)
        step.run = self._step_wrapper
        if loader is not None:
            loader.run = self._loader_wrapper
        # snapshot capture stall as a distinct slice: wrap the
        # snapshotter's run and attribute its measured export stall
        self.snapshotter = getattr(workflow, "snapshotter", None) \
            if workflow is not None else None
        self.snapshot_s = 0.0
        self._orig_snap_run = None
        self._snap_wrapper = None
        if self.snapshotter is not None:
            self._orig_snap_run = self.snapshotter.run
            self._snap_wrapper = _transient(self._snap_run)
            self.snapshotter.run = self._snap_wrapper

    # -- instrumentation -----------------------------------------------------
    def _discover_jits(self):
        """Every jitted callable the step owns (``_train_step_``,
        ``_eval_step_g_``, ...) — anything exposing ``_cache_size``.  A
        graph-compiler step publishes its own accounting via
        ``profiled_jits`` (one aggregate counting variant builds plus any
        inner-jit retraces)."""
        hook = getattr(self.step, "profiled_jits", None)
        if callable(hook):
            return list(hook())
        jits = []
        for value in vars(self.step).values():
            if callable(getattr(value, "_cache_size", None)):
                jits.append(value)
        return jits

    def _jit_cache_size(self):
        total = 0
        for fn in self._jits:
            try:
                total += int(fn._cache_size())
            except Exception:  # noqa: BLE001 — diagnostics never raise
                pass
        return total

    def _loader_run_wrapped(self):
        t0 = time.perf_counter()
        try:
            return self._orig_loader_run()
        finally:
            # attributed to the NEXT step: the loader prepares the
            # minibatch the step consumes
            self._pending_data_wait += time.perf_counter() - t0

    def _consumed_examples(self):
        ld = self.loader
        if ld is not None and hasattr(ld, "samples_served"):
            served = int(ld.samples_served)
            n, self._last_served = max(0, served - self._last_served), \
                served
            return n
        size = getattr(self.step, "minibatch_size", None)
        return int(size) if size is not None else 0

    def _fence_outputs(self):
        """Block until the step's device work is done.  Prefers the loss
        scalar (always produced last), falls back to the param tree."""
        for probe in (getattr(self.step, "loss", None),
                      getattr(self.step, "_params_", None)):
            if probe is None:
                continue
            try:
                import jax
                jax.block_until_ready(probe)
                return
            except Exception:  # noqa: BLE001
                continue

    def _step_run(self):
        data_wait = self._pending_data_wait
        self._pending_data_wait = 0.0
        t0 = time.perf_counter()
        try:
            result = self._orig_step_run()
        except Exception:
            # a crashed step still counts its host time; re-raise
            self.host_s += time.perf_counter() - t0
            raise
        t1 = time.perf_counter()
        if self.fence:
            self._fence_outputs()
        t2 = time.perf_counter()
        host, device = t1 - t0, t2 - t1
        n = self._consumed_examples()
        cache = self._jit_cache_size()
        recompiled = max(0, cache - self._jit_cache)
        self._jit_cache = cache
        # per-instance totals
        self.steps += 1
        self.examples += n
        self.recompiles += recompiled
        self.data_wait_s += data_wait
        self.host_s += host
        self.device_s += device
        # registry
        self._c_steps.inc()
        if n:
            self._c_examples.inc(n)
        if recompiled:
            self._c_recompiles.inc(recompiled)
        self._h_data.observe(data_wait)
        self._h_host.observe(host)
        self._h_device.observe(device)
        self._rate.append((t2, n))
        if len(self._rate) >= 2:
            span = self._rate[-1][0] - self._rate[0][0]
            if span > 0:
                self._g_rate.set(
                    sum(c for _, c in self._rate) / span)
        if self.steps % MEM_POLL_STEPS == 1:
            self._poll_memory()
        events.span("train.step", data_wait + host + device,
                    workflow=self.name,
                    data_wait_ms=round(data_wait * 1e3, 3),
                    host_ms=round(host * 1e3, 3),
                    device_ms=round(device * 1e3, 3),
                    examples=n, recompiles=recompiled)
        return result

    def _snap_run(self):
        """The snapshotter accounts its own training-thread stall
        (``stall_s``, zero for throttled-away calls) — read the delta so
        a gating-only run never floods the phase histogram."""
        snap = self.snapshotter
        before = float(getattr(snap, "stall_s", 0.0) or 0.0)
        result = self._orig_snap_run()
        stalled = float(getattr(snap, "stall_s", 0.0) or 0.0) - before
        if stalled > 0:
            self.snapshot_s += stalled
            self._h_snapshot.observe(stalled)
        return result

    def _poll_memory(self):
        device = getattr(self.step, "device", None)
        for dev in getattr(device, "jax_devices", None) or []:
            try:
                stats = dev.memory_stats() or {}
                peak = stats.get("peak_bytes_in_use")
            except Exception:  # noqa: BLE001 — cpu backends may not have it
                continue
            if peak:
                key = str(dev)
                self.peak_memory[key] = max(
                    self.peak_memory.get(key, 0), int(peak))
                self._g_mem.labels(workflow=self.name,
                                   device=key).set_max(peak)

    # -- lifecycle / reading -------------------------------------------------
    def detach(self):
        """Restore the wrapped run() methods (idempotent; tolerant of
        being attached on top of an earlier profiler — the original
        callable is restored rather than the class default)."""
        for obj, wrapper, orig in (
                (self.step, self._step_wrapper, self._orig_step_run),
                (self.loader, self._loader_wrapper,
                 self._orig_loader_run),
                (self.snapshotter, self._snap_wrapper,
                 self._orig_snap_run)):
            if obj is None:
                continue
            if obj.__dict__.get("run") is wrapper:
                del obj.__dict__["run"]
                # a pre-existing instance-level run (an OUTER profiler's
                # wrapper, or a MinibatchPrefetcher's plain-function
                # consume wrapper — no __func__) must come back
                if orig is not None and \
                        getattr(orig, "__func__", None) is not \
                        type(obj).run:
                    obj.__dict__["run"] = orig

    def summary(self):
        """Aggregate breakdown for results JSON / humans."""
        self._poll_memory()
        total = self.data_wait_s + self.host_s + self.device_s
        out = {"steps": self.steps, "examples": self.examples,
               "recompiles": self.recompiles,
               "data_wait_s": round(self.data_wait_s, 4),
               "host_s": round(self.host_s, 4),
               "device_s": round(self.device_s, 4)}
        if self.snapshot_s:
            out["snapshot_stall_s"] = round(self.snapshot_s, 4)
        if total > 0:
            out["examples_per_sec"] = round(self.examples / total, 1)
            out["phase_pct"] = {
                "data_wait": round(100 * self.data_wait_s / total, 1),
                "host": round(100 * self.host_s / total, 1),
                "device": round(100 * self.device_s / total, 1)}
            if self.snapshot_s:
                # share of the whole loop including checkpoint stalls —
                # the slice async snapshotting exists to shrink
                loop = total + self.snapshot_s
                out["phase_pct"]["snapshot"] = round(
                    100 * self.snapshot_s / loop, 1)
        if self.peak_memory:
            out["device_peak_memory_bytes"] = dict(self.peak_memory)
        prefetcher = getattr(self.loader, "prefetcher_", None)
        if prefetcher is not None:
            out["prefetch"] = prefetcher.stats()
        return out
