"""Phase attribution: flight timelines → "which phase owns the tail".

Pure functions over the timeline dicts the
:class:`~veles_tpu.observability.flight.FlightRecorder` produces — no
locks, no registry, importable by tools and benches alike.

The decomposition mirrors how the scheduler actually spends a
request's wall clock:

- **queue** — ``queue.enter`` → ``queue.admit`` gap (admission wait);
- **prefill** — sum of ``prefill.chunk`` seconds;
- **decode** — sum of ``decode.step`` per-row shares (batch cost ÷
  active rows, so shared steps attribute fairly) plus speculative
  draft shares;
- **verify** — speculative verify shares (``spec.step``);
- **tier** — KV-tier readmit time (``tier.hit`` seconds);
- **migration** — ``migrate.export`` → ``migrate.import`` hop gap;
- **other** — the residual against measured wall clock, kept explicit
  so a report that stops covering the tail is visible instead of
  silently wrong (the bench gate asserts coverage ≥ 95%).

TTFT is decomposed over events up to the ``first_token`` mark;
per-token latency over events after it.  :func:`aggregate` groups
requests by tenant tag and replica and reports p50/p95/p99 per phase.
"""

__all__ = ["PHASES", "phase_breakdown", "aggregate", "percentile",
           "render_report"]

#: attribution phases, in report order
PHASES = ("queue", "prefill", "decode", "verify", "tier", "migration",
          "other")


def percentile(values, q):
    """Exact percentile of a small sample (same convention as
    serving.metrics.LatencyWindow)."""
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
    return ordered[idx]


def _zero_phases():
    return {p: 0.0 for p in PHASES}


def _add_event(phases, ev):
    kind = ev.get("kind")
    if kind == "prefill.chunk":
        phases["prefill"] += float(ev.get("seconds", 0.0) or 0.0)
    elif kind == "decode.step":
        phases["decode"] += float(ev.get("share_s", 0.0) or 0.0)
    elif kind == "spec.step":
        phases["decode"] += float(ev.get("draft_share_s", 0.0) or 0.0)
        phases["verify"] += float(ev.get("verify_share_s", 0.0) or 0.0)
    elif kind == "tier.hit":
        phases["tier"] += float(ev.get("seconds", 0.0) or 0.0)


def phase_breakdown(timeline):
    """Decompose ONE timeline dict into phase seconds.

    Returns ``{"ttft_s", "ttft_phases", "per_token_s", "tokens",
    "decode_phases", "coverage"}`` — any piece may be None when its
    marker events are missing (e.g. a shed request never prefilled).
    """
    events = timeline.get("events") or []
    t_enter = t_admit = t_first = None
    ttft_s = None
    tokens = 0
    exports, imports = [], []
    for ev in events:
        kind = ev.get("kind")
        if kind == "queue.enter" and t_enter is None:
            t_enter = ev["t"]
        elif kind == "queue.admit" and t_admit is None:
            t_admit = ev["t"]
        elif kind == "first_token" and t_first is None:
            t_first = ev["t"]
            if ev.get("ttft_s") is not None:
                ttft_s = float(ev["ttft_s"])
        elif kind == "retire":
            tokens = int(ev.get("tokens", 0) or 0)
        elif kind == "migrate.export":
            exports.append(ev["t"])
        elif kind == "migrate.import":
            imports.append(ev["t"])

    ttft_phases = _zero_phases()
    decode_phases = _zero_phases()
    for ev in events:
        target = ttft_phases if (t_first is not None and
                                 ev["t"] <= t_first) else decode_phases
        _add_event(target, ev)
    if t_enter is not None and t_admit is not None:
        ttft_phases["queue"] = max(0.0, t_admit - t_enter)
    # an admitted request still WAITS while the engine serves other
    # sessions (head-of-line long prefills, interleaved decode steps
    # between its own chunks) — that service wait is queueing from the
    # request's perspective.  prefill.chunk events are stamped at chunk
    # COMPLETION, so each chunk's start is t - seconds; the gap back to
    # the previous mark (admission, or the previous chunk's end) is
    # wait, not compute
    mark = t_admit
    for ev in events:
        if ev.get("kind") != "prefill.chunk" or \
                (t_first is not None and ev["t"] > t_first):
            continue
        if mark is not None:
            start = ev["t"] - float(ev.get("seconds", 0.0) or 0.0)
            ttft_phases["queue"] += max(0.0, start - mark)
        mark = ev["t"]
    # migration: each export pairs with the next import after it; the
    # gap is wall time the session spent in flight between replicas
    mig = 0.0
    for t_exp in exports:
        after = [t for t in imports if t >= t_exp]
        if after:
            mig += after[0] - t_exp
    if mig:
        target = ttft_phases if (t_first is not None and exports and
                                 exports[0] <= t_first) else decode_phases
        target["migration"] += mig

    coverage = None
    if ttft_s is None and t_first is not None and t_enter is not None:
        ttft_s = max(0.0, t_first - t_enter)
    if ttft_s:
        covered = sum(v for p, v in ttft_phases.items() if p != "other")
        ttft_phases["other"] = max(0.0, ttft_s - covered)
        coverage = min(1.0, covered / ttft_s) if ttft_s > 0 else None

    per_token_s = None
    finished = timeline.get("finished_unix")
    if t_first is not None and finished is not None and tokens > 1:
        per_token_s = max(0.0, finished - t_first) / (tokens - 1)
        covered = sum(v for p, v in decode_phases.items()
                      if p != "other")
        decode_phases["other"] = max(
            0.0, (finished - t_first) - covered)

    return {"ttft_s": ttft_s, "ttft_phases": ttft_phases,
            "per_token_s": per_token_s, "tokens": tokens,
            "decode_phases": decode_phases, "coverage": coverage}


def aggregate(timelines, group_by=("tenant", "replica")):
    """Many timelines → per-group phase-attribution report.

    Groups by the requested meta keys (missing values group under
    ``"-"``); returns ``{group: {"count", "anomalies", "ttft_ms":
    {p50,p95,p99}, "per_token_ms": {...}, "ttft_phase_ms": {phase:
    mean}, "ttft_phase_pct": {...}, "per_token_phase_ms": {...},
    "coverage"}}``."""
    groups = {}
    for tl in timelines:
        meta = tl.get("meta") or {}
        key = "/".join(str(meta.get(k) or tl.get(k) or "-")
                       for k in group_by)
        g = groups.setdefault(key, {
            "count": 0, "anomalies": 0, "ttft": [], "per_token": [],
            "ttft_phases": _zero_phases(),
            "decode_phases": _zero_phases(), "coverage": []})
        g["count"] += 1
        if tl.get("anomalies"):
            g["anomalies"] += 1
        br = phase_breakdown(tl)
        if br["ttft_s"] is not None:
            g["ttft"].append(br["ttft_s"])
            for p in PHASES:
                g["ttft_phases"][p] += br["ttft_phases"][p]
        if br["per_token_s"] is not None:
            g["per_token"].append(br["per_token_s"])
            for p in PHASES:
                g["decode_phases"][p] += br["decode_phases"][p]
        if br["coverage"] is not None:
            g["coverage"].append(br["coverage"])

    out = {}
    for key, g in groups.items():
        n_ttft = max(1, len(g["ttft"]))
        n_tok = max(1, len(g["per_token"]))
        ttft_total = sum(g["ttft_phases"].values())
        row = {
            "count": g["count"], "anomalies": g["anomalies"],
            "ttft_ms": _quantiles_ms(g["ttft"]),
            "per_token_ms": _quantiles_ms(g["per_token"]),
            "ttft_phase_ms": {
                p: round(1e3 * g["ttft_phases"][p] / n_ttft, 3)
                for p in PHASES},
            "per_token_phase_ms": {
                p: round(1e3 * g["decode_phases"][p] / n_tok, 3)
                for p in PHASES},
            "coverage": round(sum(g["coverage"]) /
                              len(g["coverage"]), 4)
            if g["coverage"] else None,
        }
        if ttft_total > 0:
            row["ttft_phase_pct"] = {
                p: round(100.0 * g["ttft_phases"][p] / ttft_total, 1)
                for p in PHASES}
        out[key] = row
    return out


def _quantiles_ms(values):
    if not values:
        return None
    return {"p50": round(1e3 * percentile(values, 0.50), 3),
            "p95": round(1e3 * percentile(values, 0.95), 3),
            "p99": round(1e3 * percentile(values, 0.99), 3)}


def render_report(agg, group_by=("tenant", "replica")):
    """Human-readable phase-attribution table."""
    lines = []
    header = "%-24s %6s %5s %10s %10s  %s" % (
        "/".join(group_by), "count", "anom", "ttft_p99", "tok_p99",
        "ttft phase shares")
    lines.append(header)
    lines.append("-" * len(header))
    for key in sorted(agg):
        row = agg[key]
        ttft = row.get("ttft_ms") or {}
        tok = row.get("per_token_ms") or {}
        pct = row.get("ttft_phase_pct") or {}
        shares = " ".join("%s=%s%%" % (p, pct[p])
                          for p in PHASES if pct.get(p))
        lines.append("%-24s %6d %5d %10s %10s  %s" % (
            key, row["count"], row["anomalies"],
            _fmt_ms(ttft.get("p99")), _fmt_ms(tok.get("p99")),
            shares or "-"))
        if row.get("coverage") is not None:
            lines.append("%-24s %s" % (
                "", "coverage=%.1f%% of wall-clock TTFT attributed"
                % (100.0 * row["coverage"])))
    return "\n".join(lines)


def _fmt_ms(v):
    return "-" if v is None else ("%.1fms" % v)
