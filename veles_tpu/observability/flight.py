"""Per-request flight recorder: one timeline per request, always on.

The observability core (PR 2) answers *aggregate* questions — p99
gauges, counters, opt-in offline trace files — but not "why was THIS
request's TTFT 2 s" or "which phase owns the decode tail".  The flight
recorder rebuilds per-request visibility the way TVM/TPU-compilation
systems must (PAPERS.md): every latency source the serving stack has
accreted (router retries/affinity, admission queueing, chunked prefill,
batched decode steps, speculative verify, KV-tier readmits, live
migration hops) reports a **typed event** into a per-request timeline,
stitched across processes by the existing ``X-Trace-Id`` wire
(:mod:`~veles_tpu.observability.trace`).

Bounded by construction:

- a per-replica fixed-size ring of timelines (``capacity``, drop-oldest
  — evicting never blocks the hot path on I/O);
- a per-timeline event cap (``max_events``; beyond it events are
  counted, not stored);
- recording is a lock + list append; the decode step hot path batches
  all rows of one step under a single lock acquisition
  (:meth:`FlightRecorder.record_step_rows`).

Timelines persist to JSONL (``flight-<pid>.jsonl`` under
``VELES_FLIGHT_DIR``) only on **anomaly triggers** — deadline miss/504,
429 shed, connection retry, migration, SIGKILL-recovery replay, or
TTFT/per-token latency above a rolling p99 threshold — so steady state
stays memory-only and cheap.  ``GET /api/<name>/requests`` (replica),
``GET /fleet/requests`` (router-merged) and ``tools/request_inspect.py``
read the ring; :mod:`~veles_tpu.observability.attribution` turns
timelines into phase-share reports.

Single-source rule: every event kind has exactly ONE producer.  The
decode step is recorded by the scheduler worker (with the per-row
share), NOT by mirroring the ``serving.decode`` span a
:class:`~veles_tpu.observability.profiler.StepProfiler` or
``DecodeMetrics`` may also emit — the optional EventLog bridge
(:meth:`FlightRecorder.install_span_bridge`) therefore skips every span
name that has a first-class producer (:data:`DIRECT_SPAN_KINDS`), and
a per-timeline step-ordinal guard drops duplicates even if two
producers ever race.  Stdlib-only; imports nothing above
``observability``.
"""

import collections
import json
import os
import threading
import time

from .registry import REGISTRY

__all__ = ["FlightRecorder", "RECORDER", "FLIGHT_DIR_ENV",
           "DIRECT_SPAN_KINDS", "configure_from_env"]

#: persistence dir env var (planted per replica by the fleet supervisor)
FLIGHT_DIR_ENV = "VELES_FLIGHT_DIR"

#: span names with a first-class flight producer — the EventLog bridge
#: must NEVER mirror these into timelines (single-source; satellite of
#: the StepProfiler double-count fix: a profiler attached while a
#: decode scheduler is live re-emits step spans, but only the scheduler
#: worker's record_step_rows() feeds the timeline)
DIRECT_SPAN_KINDS = frozenset((
    "serving.decode", "serving.draft", "serving.verify",
    "serving.prefill_chunk", "serving.prefill", "train.step",
    "serving.request", "serving.generate_request", "fleet.route",
))

#: anomaly reasons (the persist triggers)
ANOMALY_REASONS = ("deadline_504", "shed_429", "retry", "migration",
                   "recovery_replay", "ttft_p99", "per_token_p99",
                   "error")


class _Timeline:
    """One request's event list plus bookkeeping.  Events are stored as
    ``(t_wall, kind, info_dict_or_None)`` tuples — rendered to dicts
    only at read time, never on the hot path."""

    __slots__ = ("trace_id", "started", "finished", "status", "events",
                 "dropped", "anomalies", "meta", "persisted",
                 "last_step", "imported")

    def __init__(self, trace_id, t):
        self.trace_id = trace_id
        self.started = t
        self.finished = None
        self.status = None
        self.events = []
        self.dropped = 0
        self.anomalies = []
        self.meta = {}
        self.persisted = False
        self.last_step = -1        # decode-step ordinal dedup guard
        self.imported = []         # event tuples absorbed from a peer

    def to_dict(self, replica=None):
        evs = [_render(e) for e in self.imported]
        evs += [_render(e) for e in self.events]
        evs.sort(key=lambda e: e["t"])
        out = {"trace_id": self.trace_id,
               "started_unix": round(self.started, 6),
               "status": self.status,
               "anomalies": list(self.anomalies),
               "events": evs}
        if replica:
            out["replica"] = replica
        if self.finished is not None:
            out["finished_unix"] = round(self.finished, 6)
        if self.dropped:
            out["events_dropped"] = self.dropped
        if self.meta:
            out["meta"] = dict(self.meta)
        return out


def _render(ev):
    t, kind, info = ev
    rec = {"t": round(t, 6), "kind": kind}
    if type(info) is tuple:
        # compact decode.step storage: (step, share_s, rows) — the
        # per-row hot path appends a shared-shape tuple instead of
        # allocating a dict per row per step
        rec["step"], rec["share_s"], rec["rows"] = info
    elif info:
        rec.update(info)
    return rec


class FlightRecorder:
    """Fixed-size ring of per-request timelines keyed by trace id."""

    def __init__(self, capacity=256, max_events=512, window=512,
                 min_samples=32, persist_dir=None, replica=None,
                 enabled=True):
        self.capacity = int(capacity)
        self.max_events = int(max_events)
        self.min_samples = int(min_samples)
        self.persist_dir = persist_dir
        self.replica = replica
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._ring = collections.OrderedDict()   # trace_id -> _Timeline
        self._ttft_window = collections.deque(maxlen=int(window))
        self._tok_window = collections.deque(maxlen=int(window))
        # rolling p99s are recomputed lazily every _P99_REFRESH inserts
        # — sorting the window on every finish() would dominate the
        # recorder's own overhead budget
        self._p99_cache = {}
        self._p99_stale = {}
        self._file = None
        self._bridge_installed = False
        # hold the label-less CHILD series directly — the metric-family
        # indirection (labels() key build + dict lookup) is measurable
        # at one inc per recorded event
        self._c_requests = REGISTRY.counter(
            "veles_flight_requests_total",
            "Request timelines opened by the flight recorder").labels()
        self._c_events = REGISTRY.counter(
            "veles_flight_events_total",
            "Typed events recorded into flight timelines").labels()
        self._c_dropped = REGISTRY.counter(
            "veles_flight_events_dropped_total",
            "Events dropped by the per-timeline cap").labels()
        self._c_anomalies = REGISTRY.counter(
            "veles_flight_anomalies_total",
            "Anomaly triggers by reason", ("reason",))
        self._c_persisted = REGISTRY.counter(
            "veles_flight_persisted_total",
            "Anomalous timelines persisted to JSONL").labels()

    # -- configuration -------------------------------------------------------
    def configure(self, persist_dir=None, replica=None, enabled=None):
        if persist_dir is not None:
            self.persist_dir = persist_dir
            self._close_file()
        if replica is not None:
            self.replica = replica
        if enabled is not None:
            self.enabled = bool(enabled)

    def _resolve_dir(self):
        return self.persist_dir or os.environ.get(FLIGHT_DIR_ENV)

    # -- recording (hot path) ------------------------------------------------
    def _timeline(self, trace_id, t):
        """Get-or-create under the caller's lock; evicts drop-oldest."""
        tl = self._ring.get(trace_id)
        if tl is not None:
            self._ring.move_to_end(trace_id)
            return tl
        tl = _Timeline(trace_id, t)
        self._ring[trace_id] = tl
        self._c_requests.inc()
        while len(self._ring) > self.capacity:
            self._ring.popitem(last=False)
        return tl

    def record(self, trace_id, kind, **info):
        """Append one typed event to ``trace_id``'s timeline."""
        if not self.enabled or not trace_id:
            return
        t = time.time()
        with self._lock:
            tl = self._timeline(trace_id, t)
            if kind == "decode.step":
                step = info.get("step")
                if step is not None and step <= tl.last_step:
                    return          # duplicate producer — single-source
                tl.last_step = step if step is not None else tl.last_step
            if len(tl.events) >= self.max_events:
                tl.dropped += 1
                self._c_dropped.inc()
                return
            tl.events.append((t, kind, info or None))
        self._c_events.inc()

    def record_step_rows(self, rows, seconds):
        """One decode batch step: ``rows`` is ``[(trace_id, ordinal),
        ...]`` for every active row; each gets the fair per-row share
        (batch cost ÷ active rows) under a SINGLE lock acquisition."""
        if not self.enabled or not rows:
            return
        n_rows = len(rows)
        share = round(seconds / n_rows, 6)
        t = time.time()
        recorded = dropped = 0
        ring_get = self._ring.get
        max_events = self.max_events
        with self._lock:
            for trace_id, step in rows:
                if not trace_id:
                    continue
                # fast path: plain lookup, no LRU touch — this is the
                # highest-frequency producer, and every session is
                # re-touched by its own lifecycle events anyway
                tl = ring_get(trace_id)
                if tl is None:
                    tl = self._timeline(trace_id, t)
                if step is not None and step <= tl.last_step:
                    continue
                tl.last_step = step if step is not None else tl.last_step
                if len(tl.events) >= max_events:
                    tl.dropped += 1
                    dropped += 1
                    continue
                tl.events.append((t, "decode.step",
                                  (step, share, n_rows)))
                recorded += 1
        # counters batch OUTSIDE the ring lock: one registry-lock
        # acquisition per step, not per row (the overhead gate)
        if recorded:
            self._c_events.inc(recorded)
        if dropped:
            self._c_dropped.inc(dropped)

    def annotate(self, trace_id, **meta):
        """Attach request metadata (model, tenant, session, replica
        hop) without consuming an event slot."""
        if not self.enabled or not trace_id:
            return
        with self._lock:
            tl = self._timeline(trace_id, time.time())
            tl.meta.update({k: v for k, v in meta.items()
                            if v is not None})

    # -- anomalies / lifecycle ----------------------------------------------
    def anomaly(self, trace_id, reason, **info):
        """Mark a timeline anomalous (it will persist on finish — or
        now, if already finished) and record the trigger event."""
        if not self.enabled or not trace_id:
            return
        t = time.time()
        with self._lock:
            tl = self._timeline(trace_id, t)
            if reason not in tl.anomalies:
                tl.anomalies.append(reason)
            self._c_anomalies.labels(reason=reason).inc()
            if len(tl.events) < self.max_events:
                info = dict(info)
                info["reason"] = reason
                tl.events.append((t, "anomaly", info))
                self._c_events.inc()
            if tl.finished is not None:
                self._persist_locked(tl)

    def finish(self, trace_id, status="ok", ttft_s=None,
               per_token_s=None):
        """Close a timeline; feeds the rolling p99 windows and persists
        when any anomaly trigger fired.  Latency values above the
        current rolling p99 (after ``min_samples``) are themselves
        anomaly triggers — the tail self-selects for persistence."""
        if not self.enabled or not trace_id:
            return
        t = time.time()
        with self._lock:
            tl = self._timeline(trace_id, t)
            tl.finished = t
            tl.status = status
            for value, window, reason in (
                    (ttft_s, self._ttft_window, "ttft_p99"),
                    (per_token_s, self._tok_window, "per_token_p99")):
                if value is None:
                    continue
                if len(window) >= self.min_samples:
                    p99 = self._p99_cache.get(reason)
                    if p99 is None or \
                            self._p99_stale.get(reason, 0) >= \
                            _P99_REFRESH:
                        p99 = _p99(window)
                        self._p99_cache[reason] = p99
                        self._p99_stale[reason] = 0
                    if value > p99 and reason not in tl.anomalies:
                        tl.anomalies.append(reason)
                        self._c_anomalies.labels(reason=reason).inc()
                        tl.events.append(
                            (t, "anomaly",
                             {"reason": reason,
                              "value_s": round(value, 6),
                              "p99_s": round(p99, 6)}))
                window.append(value)
                self._p99_stale[reason] = \
                    self._p99_stale.get(reason, 0) + 1
            if tl.anomalies:
                self._persist_locked(tl)

    # -- migration travel ----------------------------------------------------
    def export(self, trace_id):
        """JSON-safe snapshot for the session wire (timelines travel
        with migrated sessions); None when the id is unknown."""
        if not trace_id:
            return None
        with self._lock:
            tl = self._ring.get(trace_id)
            if tl is None:
                return None
            return tl.to_dict(replica=self.replica)

    def absorb(self, data):
        """Merge a peer's exported timeline into the local ring (the
        import half of migration travel)."""
        if not self.enabled or not isinstance(data, dict):
            return
        trace_id = data.get("trace_id")
        if not trace_id:
            return
        src = data.get("replica")
        with self._lock:
            tl = self._timeline(trace_id, time.time())
            # source and destination may share one process (in-test
            # migrations): never duplicate events the local timeline
            # already holds
            seen = set((round(t, 6), kind)
                       for t, kind, _ in tl.events + tl.imported)
            for ev in data.get("events", []):
                if not isinstance(ev, dict) or "t" not in ev:
                    continue
                if (round(float(ev["t"]), 6),
                        ev.get("kind")) in seen:
                    continue
                info = {k: v for k, v in ev.items()
                        if k not in ("t", "kind")}
                if src and "replica" not in info:
                    info["replica"] = src
                tl.imported.append((float(ev["t"]),
                                    str(ev.get("kind", "event")),
                                    info or None))
            for reason in data.get("anomalies", []):
                if reason not in tl.anomalies:
                    tl.anomalies.append(reason)
            for k, v in (data.get("meta") or {}).items():
                tl.meta.setdefault(k, v)

    # -- persistence ---------------------------------------------------------
    def _persist_locked(self, tl):
        if tl.persisted:
            return
        directory = self._resolve_dir()
        if not directory:
            return
        try:
            if self._file is None:
                os.makedirs(directory, exist_ok=True)
                self._file = open(os.path.join(
                    directory, "flight-%d.jsonl" % os.getpid()),
                    "a", buffering=1)
            self._file.write(json.dumps(
                tl.to_dict(replica=self.replica)) + "\n")
            tl.persisted = True
            self._c_persisted.inc()
        except OSError:
            pass                    # diagnostics never take down serving

    def _close_file(self):
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None

    # -- reading -------------------------------------------------------------
    def get(self, trace_id):
        with self._lock:
            tl = self._ring.get(trace_id)
            return tl.to_dict(replica=self.replica) \
                if tl is not None else None

    def snapshot(self, trace_id=None, model=None, limit=64):
        """Recent timelines, newest first; optionally one id or one
        model's requests."""
        if trace_id:
            doc = self.get(trace_id)
            return [doc] if doc else []
        with self._lock:
            out = []
            for tl in reversed(self._ring.values()):
                if model and tl.meta.get("model") not in (None, model):
                    continue
                out.append(tl.to_dict(replica=self.replica))
                if len(out) >= limit:
                    break
            return out

    def stats(self):
        with self._lock:
            return {"timelines": len(self._ring),
                    "capacity": self.capacity,
                    "ttft_window": len(self._ttft_window),
                    "per_token_window": len(self._tok_window),
                    "replica": self.replica,
                    "persist_dir": self._resolve_dir()}

    # -- EventLog bridge -----------------------------------------------------
    def install_span_bridge(self, eventlog=None):
        """Mirror generic EventLog spans into EXISTING timelines.

        Only spans carrying an explicit trace id that already has a
        timeline are ingested (the bridge never creates — an ambient
        process-wide trace context must not grow an unbounded
        pseudo-request), and names in :data:`DIRECT_SPAN_KINDS` are
        skipped because their first-class producers already record them
        with richer typed info — the single-source rule that keeps a
        live StepProfiler from double-counting decode steps."""
        if eventlog is None:
            from ..logger import events as eventlog
        eventlog.span_sink = self._span_sink
        self._bridge_installed = True

    def _span_sink(self, name, kind, duration, info):
        if not self.enabled or name in DIRECT_SPAN_KINDS:
            return
        from . import trace as _trace
        ctx = _trace.current()
        trace_id = (info or {}).get("trace_id") or \
            (ctx.trace_id if ctx is not None else None)
        if not trace_id:
            return
        t = time.time()
        with self._lock:
            tl = self._ring.get(trace_id)
            if tl is None:
                return              # bridge never creates timelines
            if len(tl.events) >= self.max_events:
                tl.dropped += 1
                self._c_dropped.inc()
                return
            ev = {"span": name}
            if duration is not None:
                ev["seconds"] = round(duration, 6)
            tl.events.append((t, "span", ev))
            self._c_events.inc()

    # -- tests ---------------------------------------------------------------
    def reset(self):
        with self._lock:
            self._ring.clear()
            self._ttft_window.clear()
            self._tok_window.clear()
            self._p99_cache.clear()
            self._p99_stale.clear()
            self._close_file()


#: finishes between rolling-p99 recomputations (the sort is O(n log n)
#: over the window; amortizing it keeps finish() on the cheap path)
_P99_REFRESH = 16


def _p99(window):
    ordered = sorted(window)
    return ordered[min(len(ordered) - 1,
                       int(0.99 * (len(ordered) - 1) + 0.5))]


#: process-global recorder — per-replica because a replica IS a process
RECORDER = FlightRecorder()


def configure_from_env(replica=None):
    """Adopt ``VELES_FLIGHT_DIR`` (planted by the fleet supervisor) and
    the replica id; called at replica/router startup."""
    RECORDER.configure(persist_dir=os.environ.get(FLIGHT_DIR_ENV),
                       replica=replica)
    return RECORDER
