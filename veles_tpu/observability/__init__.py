"""Unified observability core.

Three pieces, one picture (the reference platform's shared event stream
plus web status server, rebuilt TPU-native):

- :mod:`~veles_tpu.observability.registry` — the process-global
  :class:`MetricsRegistry` (counters/gauges/histograms with labels) that
  training AND serving record into; exposed as Prometheus text at the
  status server's ``/metrics`` and merged into ``/status`` JSON.
- :mod:`~veles_tpu.observability.profiler` — :class:`StepProfiler`,
  which wraps a workflow's training step and splits each step into
  data-wait / host / device-compute time, counts jit recompiles, tracks
  examples/sec and device-memory watermarks.
- :mod:`~veles_tpu.observability.trace` — trace-context propagation so
  per-process ``events-*.jsonl`` files from a distributed run share one
  ``trace_id`` and merge into a single Perfetto timeline
  (``tools/merge_traces.py``).

``registry`` and ``trace`` are stdlib-only and import nothing from
veles_tpu (so ``logger``/``units`` can use them cycle-free); the
profiler — which needs the logger — loads lazily via attribute access.
"""

from .registry import (MetricsRegistry, REGISTRY, counter, gauge,  # noqa
                       histogram, render_prometheus)
from . import trace                                                # noqa


def __getattr__(name):
    # lazy: profiler imports logger, which imports observability.trace —
    # resolving it on demand keeps the package importable from logger.py
    if name == "StepProfiler":
        from .profiler import StepProfiler
        return StepProfiler
    if name == "profiler":
        from . import profiler
        return profiler
    if name in ("flight", "attribution"):
        import importlib
        return importlib.import_module("." + name, __name__)
    if name == "FlightRecorder":
        from .flight import FlightRecorder
        return FlightRecorder
    raise AttributeError(name)
