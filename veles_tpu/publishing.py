"""Publisher: render a training-run report.

Re-creation of /root/reference/veles/publishing/ (publisher.py:57 +
backend registry): the reference gathered workflow info and plots and
rendered to Confluence/Markdown/PDF/IPython-notebook templates.  The
kept backends are **markdown** and **json** (Confluence XML-RPC and
LaTeX toolchains are environment dependencies this build deliberately
avoids); the gathered info set matches: workflow name/checksum, config,
results, per-unit timing table, plot artifacts.
"""

import json
import os
import time

from .result_provider import IResultProvider
from .units import Unit

BACKENDS = {}


def register_backend(name):
    def deco(fn):
        BACKENDS[name] = fn
        return fn
    return deco


def gather_info(workflow):
    units = []
    for unit in workflow:
        units.append({
            "name": unit.name,
            "class": type(unit).__name__,
            "runs": unit.timers.get("runs", 0),
            "seconds": round(unit.timers.get("run", 0.0), 4),
        })
    plots = []
    for unit in workflow:
        if hasattr(unit, "plot_name") and hasattr(unit, "path"):
            plots.append({"name": unit.plot_name, "path": unit.path})
    return {
        "workflow": workflow.name,
        "checksum": workflow.checksum,
        "generated": time.strftime("%Y-%m-%d %H:%M:%S"),
        "results": workflow.gather_results(),
        "units": units,
        "plots": plots,
    }


@register_backend("json")
def render_json(info, path):
    with open(path, "w") as f:
        json.dump(info, f, indent=2, default=str)
    return path


@register_backend("markdown")
def render_markdown(info, path):
    lines = ["# %s — training report" % info["workflow"], "",
             "Generated: %s" % info["generated"],
             "Checksum: `%s`" % info["checksum"], "", "## Results", ""]
    for k, v in sorted(info["results"].items()):
        lines.append("- **%s**: %s" % (k, v))
    lines += ["", "## Units", "",
              "| unit | class | runs | seconds |",
              "|------|-------|------|---------|"]
    for u in info["units"]:
        lines.append("| %s | %s | %d | %.4f |" %
                     (u["name"], u["class"], u["runs"], u["seconds"]))
    if info["plots"]:
        lines += ["", "## Plots", ""]
        for p in info["plots"]:
            lines.append("- %s: `%s`" % (p["name"], p["path"]))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


class Publisher(Unit, IResultProvider):
    """End-of-run report unit (link it from the Decision; it fires once
    the workflow completes)."""

    MAPPING = "publisher"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.runs_after_stop = True
        self.backends = tuple(kwargs.get("backends", ("markdown",)))
        self.directory = kwargs.get("directory", ".")
        self.basename = kwargs.get("basename", "report")
        self.complete = None      # linked: decision.complete
        self.published = []

    def link_decision(self, decision):
        self.link_attrs(decision, "complete")
        self.gate_skip = ~decision.complete
        return self

    def run(self):
        os.makedirs(self.directory, exist_ok=True)
        info = gather_info(self._workflow)
        ext = {"markdown": ".md", "json": ".json"}
        self.published = []
        for backend in self.backends:
            path = os.path.join(self.directory,
                                self.basename + ext.get(backend, ".txt"))
            self.published.append(BACKENDS[backend](info, path))

    def get_metric_values(self):
        return {"reports": list(self.published)}
