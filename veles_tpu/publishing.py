"""Publisher: render a training-run report.

Re-creation of /root/reference/veles/publishing/ (publisher.py:57 +
backend registry, 1103 LoC over 4 backends): the reference gathered
workflow info and plots and rendered to Confluence/Markdown/PDF/
IPython-notebook templates.  Backends here: **markdown**, **json**,
**ipynb** (nbformat-4 JSON, dependency-free — the notebook opens in
Jupyter with the results bound to a live ``results`` variable for
follow-up analysis, plots embedded base64), **html** (one
self-contained static page, plots inlined), **confluence**
(storage-format XHTML published over the reference's XML-RPC surface
via stdlib ``xmlrpc.client``; offline it writes the artifact only), and
**pdf** (a minimal hand-assembled PDF-1.4, no LaTeX).  All FOUR of the
reference's report destinations (Confluence/Markdown/PDF/ipynb) are
covered dependency-free, plus json and html.  The
gathered info set matches the reference: workflow name/checksum,
results, per-unit timing table, plot artifacts.
"""

import base64
import json
import logging
import os
import time

from .result_provider import IResultProvider
from .units import Unit

BACKENDS = {}


def register_backend(name):
    def deco(fn):
        BACKENDS[name] = fn
        return fn
    return deco


def gather_info(workflow):
    units = []
    for unit in workflow:
        units.append({
            "name": unit.name,
            "class": type(unit).__name__,
            "runs": unit.timers.get("runs", 0),
            "seconds": round(unit.timers.get("run", 0.0), 4),
        })
    plots = []
    for unit in workflow:
        if hasattr(unit, "plot_name") and hasattr(unit, "path"):
            plots.append({"name": unit.plot_name, "path": unit.path})
    return {
        "workflow": workflow.name,
        "checksum": workflow.checksum,
        "generated": time.strftime("%Y-%m-%d %H:%M:%S"),
        "results": workflow.gather_results(),
        "units": units,
        "plots": plots,
    }


@register_backend("json")
def render_json(info, path):
    with open(path, "w") as f:
        json.dump(info, f, indent=2, default=str)
    return path


@register_backend("markdown")
def render_markdown(info, path):
    lines = _md_report_lines(info)
    if info["plots"]:
        lines += ["", "## Plots", ""]
        for p in info["plots"]:
            lines.append("- %s: `%s`" % (p["name"], p["path"]))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def _embed_plots(info):
    """(mime, b64, name) for each plot artifact that exists on disk."""
    out = []
    for p in info["plots"]:
        path = p.get("path")
        if not path or not os.path.exists(str(path)):
            continue
        ext = os.path.splitext(str(path))[1].lower().lstrip(".")
        mime = {"png": "image/png", "jpg": "image/jpeg",
                "jpeg": "image/jpeg", "svg": "image/svg+xml"}.get(ext)
        if mime is None:
            continue
        with open(str(path), "rb") as f:
            out.append((mime, base64.b64encode(f.read()).decode(),
                        p["name"]))
    return out


def _md_report_lines(info):
    """The shared markdown body (markdown + ipynb backends)."""
    lines = ["# %s — training report" % info["workflow"], "",
             "Generated: %s" % info["generated"],
             "Checksum: `%s`" % info["checksum"], "", "## Results", ""]
    for k, v in sorted(info["results"].items()):
        lines.append("- **%s**: %s" % (k, v))
    lines += ["", "## Units", "",
              "| unit | class | runs | seconds |",
              "|------|-------|------|---------|"]
    for u in info["units"]:
        lines.append("| %s | %s | %d | %.4f |" %
                     (u["name"], u["class"], u["runs"], u["seconds"]))
    return lines


@register_backend("ipynb")
def render_ipynb(info, path):
    """nbformat-4 notebook: a markdown report cell, the results bound to
    a live ``results`` dict in a code cell, and one markdown cell per
    plot with the image embedded as a cell attachment (the reference's
    IPythonNotebookBackend rendered the same report to a notebook
    template; nbformat is plain JSON, so no dependency is needed)."""
    cells = [{
        "cell_type": "markdown", "metadata": {},
        "source": "\n".join(_md_report_lines(info)),
    }, {
        "cell_type": "code", "metadata": {}, "outputs": [],
        "execution_count": None,
        # json.loads of an embedded literal, NOT a bare dict: None/
        # True/NaN would render as null/true/NaN — invalid Python
        # (python's json.loads accepts NaN/Infinity)
        "source": "# the run's results, live for follow-up analysis\n"
                  "import json\nresults = json.loads(%r)\nresults" %
                  json.dumps(info["results"], default=str,
                             sort_keys=True),
    }]
    for i, (mime, b64, name) in enumerate(_embed_plots(info)):
        att = "plot%d.%s" % (i, mime.split("/")[1].split("+")[0])
        cells.append({
            "cell_type": "markdown", "metadata": {},
            "attachments": {att: {mime: b64}},
            "source": "### %s\n\n![%s](attachment:%s)" % (name, name,
                                                          att),
        })
    nb = {"cells": cells,
          "metadata": {"language_info": {"name": "python"}},
          "nbformat": 4, "nbformat_minor": 5}
    with open(path, "w") as f:
        json.dump(nb, f, indent=1, default=str)
    return path


def _pdf_escape(text):
    return (text.replace("\\", r"\\").replace("(", r"\(")
            .replace(")", r"\)").encode("latin-1", "replace"))


@register_backend("pdf")
def render_pdf(info, path):
    """A real PDF report with NO LaTeX and no dependencies: a minimal
    hand-assembled PDF-1.4 (catalog/pages/Helvetica font, one
    uncompressed text content stream per page).  The reference's PDF
    backend shelled out to a LaTeX toolchain
    (/root/reference/veles/publishing/pdf_backend.py) — the toolchain
    is an environment dependency this build avoids; the capability
    (results as a PDF artifact) is what this preserves."""
    lines = ["%s - training report" % info["workflow"], "",
             "Generated: %s" % info["generated"],
             "Checksum: %s" % info["checksum"], "", "Results", ""]
    for k, v in sorted(info["results"].items()):
        lines.append("  %s: %s" % (k, v))
    lines += ["", "Units", "",
              "  %-28s %-24s %6s %10s" % ("unit", "class", "runs",
                                          "seconds")]
    for u in info["units"]:
        lines.append("  %-28s %-24s %6d %10.4f"
                     % (u["name"][:28], u["class"][:24], u["runs"],
                        u["seconds"]))
    if info["plots"]:
        lines += ["", "Plot artifacts", ""]
        lines += ["  %s: %s" % (p["name"], p["path"])
                  for p in info["plots"]]

    per_page = 54                       # 12pt leading inside 792pt page
    pages = [lines[i:i + per_page] for i in range(0, len(lines),
                                                  per_page)] or [[]]
    objs = []                           # 1-indexed PDF objects
    font_num = 3 + 2 * len(pages)
    kids = " ".join("%d 0 R" % (3 + 2 * i) for i in range(len(pages)))
    objs.append(b"<< /Type /Catalog /Pages 2 0 R >>")
    objs.append(("<< /Type /Pages /Count %d /Kids [%s] >>"
                 % (len(pages), kids)).encode())
    for i, page_lines in enumerate(pages):
        objs.append((
            "<< /Type /Page /Parent 2 0 R /MediaBox [0 0 612 792] "
            "/Resources << /Font << /F1 %d 0 R >> >> "
            "/Contents %d 0 R >>" % (font_num, 4 + 2 * i)).encode())
        body = [b"BT /F1 10 Tf 12 TL 50 760 Td"]
        for ln in page_lines:
            body.append(b"(" + _pdf_escape(ln) + b") Tj T*")
        body.append(b"ET")
        stream = b"\n".join(body)
        objs.append(b"<< /Length %d >>\nstream\n%s\nendstream"
                    % (len(stream), stream))
    objs.append(b"<< /Type /Font /Subtype /Type1 "
                b"/BaseFont /Helvetica /Encoding /WinAnsiEncoding >>")

    out = [b"%PDF-1.4"]
    offsets = []
    pos = len(out[0]) + 1
    for n, obj in enumerate(objs, start=1):
        offsets.append(pos)
        piece = b"%d 0 obj\n%s\nendobj" % (n, obj)
        out.append(piece)
        pos += len(piece) + 1
    xref_pos = pos
    xref = [b"xref", b"0 %d" % (len(objs) + 1),
            b"0000000000 65535 f "]
    xref += [b"%010d 00000 n " % off for off in offsets]
    out += xref
    out += [b"trailer", b"<< /Size %d /Root 1 0 R >>" % (len(objs) + 1),
            b"startxref", b"%d" % xref_pos, b"%%EOF"]
    with open(path, "wb") as f:
        f.write(b"\n".join(out) + b"\n")
    return path


def _xhtml_fragments(info):
    """(results_ul, units_table, plots_html) — the XHTML body pieces
    shared by the html and confluence backends."""
    from html import escape

    def esc(v):
        return escape(str(v), quote=True)

    rows = "\n".join(
        "<tr><td>%s</td><td>%s</td><td>%d</td><td>%.4f</td></tr>"
        % (esc(u["name"]), esc(u["class"]), u["runs"], u["seconds"])
        for u in info["units"])
    units = ("<table><tr><th>unit</th><th>class</th><th>runs</th>"
             "<th>seconds</th></tr>\n%s</table>" % rows)
    results = "<ul>%s</ul>" % "\n".join(
        "<li><b>%s</b>: %s</li>" % (esc(k), esc(v))
        for k, v in sorted(info["results"].items()))
    plots = "\n".join(
        '<h3>%s</h3><img alt="%s" src="data:%s;base64,%s"/>'
        % (esc(name), esc(name), mime, b64)
        for mime, b64, name in _embed_plots(info))
    return results, units, plots


@register_backend("confluence")
def render_confluence(info, path, url=None, username=None, password=None,
                      space=None, parent=None, page_title=None,
                      timeout=120):
    """The reference's Confluence backend, dependency-free: renders the
    report as storage-format XHTML to ``path`` and, when ``url`` is
    configured, publishes it over the same XML-RPC surface the
    reference spoke (``confluence2.login/getPage/storePage``,
    /root/reference/veles/publishing/confluence.py:66-110) via stdlib
    ``xmlrpc.client``.  Without ``url`` the file artifact alone is the
    result (offline mode).  The PUBLISHED body excludes plots — storage
    format takes images as page attachments, not data: URIs — while
    the local artifact keeps them inline."""
    from html import escape
    results, units, plots = _xhtml_fragments(info)
    header = ("<p>Generated: %s<br/>Checksum: <code>%s</code></p>"
              "<h2>Results</h2>%s<h2>Units</h2>%s"
              % (escape(str(info["generated"])),
                 escape(str(info["checksum"])), results, units))
    with open(path, "w") as f:
        f.write(header + plots)
    if not url:
        return path
    import xmlrpc.client
    if not url.lower().startswith("https://"):
        # credentials ride the XML-RPC body in the clear; make a plain
        # http wiki an explicit, logged decision (ADVICE r4)
        logging.getLogger("publishing").warning(
            "confluence url %r is not https: credentials will be sent "
            "unencrypted", url)

    class _TimeoutTransport(xmlrpc.client.Transport):
        # no timeout would let a black-holed wiki wedge the workflow
        # right after training (the reference set a socket default
        # timeout for the same reason, confluence.py:60-64)
        def make_connection(self, host):
            conn = super().make_connection(host)
            conn.timeout = timeout
            return conn

    proxy = xmlrpc.client.ServerProxy(url.rstrip("/") + "/rpc/xmlrpc",
                                      allow_none=True,
                                      transport=_TimeoutTransport())
    api = proxy.confluence2
    token = api.login(username, password)
    try:
        title = page_title or "%s training report" % info["workflow"]
        try:
            page = api.getPage(token, space, title)
        except xmlrpc.client.Fault as fault:
            # The server signals "page missing" with a Fault (the
            # reference treats getPageSummary faults the same way) —
            # but an auth/permission Fault must NOT be converted into
            # a confusing create-path failure (ADVICE r4): re-raise
            # anything that names a credentials problem.  The missing-
            # page Fault usually echoes the requested title — strip it
            # first so a workflow named e.g. "TokenLM" can't false-
            # positive the keyword scan.
            msg = str(fault.faultString or "").lower().replace(
                title.lower(), "")
            if any(w in msg for w in ("auth", "permission", "token",
                                      "session", "denied", "credential")):
                raise
            page = {"space": space, "title": title}
            if parent is not None:
                page["parentId"] = str(parent)
        page["content"] = header
        stored = api.storePage(token, page)
    finally:
        api.logout(token)
    return stored.get("url", path) if isinstance(stored, dict) else path


@register_backend("html")
def render_html(info, path):
    """One self-contained static HTML page, plots inlined base64."""
    from html import escape

    def esc(v):
        return escape(str(v), quote=True)

    results, units_table, plots = _xhtml_fragments(info)
    doc = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>%s — training report</title>
<style>
body{font-family:sans-serif;margin:2em;max-width:60em}
table{border-collapse:collapse}td,th{border:1px solid #999;padding:.3em}
img{max-width:100%%;border:1px solid #ccc}
</style></head><body>
<h1>%s — training report</h1>
<p>Generated: %s<br>Checksum: <code>%s</code></p>
<h2>Results</h2>%s
<h2>Units</h2>
%s
%s
</body></html>
""" % (esc(info["workflow"]), esc(info["workflow"]),
       esc(info["generated"]), esc(info["checksum"]), results,
       units_table, plots)
    with open(path, "w") as f:
        f.write(doc)
    return path


class Publisher(Unit, IResultProvider):
    """End-of-run report unit (link it from the Decision; it fires once
    the workflow completes)."""

    MAPPING = "publisher"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.runs_after_stop = True
        self.backends = tuple(kwargs.get("backends", ("markdown",)))
        self.directory = kwargs.get("directory", ".")
        self.basename = kwargs.get("basename", "report")
        # per-backend options, e.g. {"confluence": {"url": ...,
        # "username": ..., "password": ..., "space": ...}}
        self.backend_options = dict(kwargs.get("backend_options", {}))
        self.complete = None      # linked: decision.complete
        self.published = []

    def link_decision(self, decision):
        self.link_attrs(decision, "complete")
        self.gate_skip = ~decision.complete
        return self

    def run(self):
        os.makedirs(self.directory, exist_ok=True)
        info = gather_info(self._workflow)
        ext = {"markdown": ".md", "json": ".json", "ipynb": ".ipynb",
               "html": ".html", "confluence": ".xhtml", "pdf": ".pdf"}
        self.published = []
        for backend in self.backends:
            path = os.path.join(self.directory,
                                self.basename + ext.get(backend, ".txt"))
            self.published.append(BACKENDS[backend](
                info, path, **self.backend_options.get(backend, {})))

    def get_metric_values(self):
        return {"reports": list(self.published)}
