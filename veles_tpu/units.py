"""Unit: one node of the dataflow graph.

TPU-native re-design of the reference unit engine
(/root/reference/veles/units.py:107-927).  Semantics kept:

- control-flow links (``link_from``) with AND-gates: a unit runs when *all*
  of its input links have fired since its last run (reference ``open_gate``,
  units.py:524);
- ``gate_block`` (do not run, do not propagate) and ``gate_skip`` (do not
  run, still propagate) mutable-Bool gates;
- data links (``link_attrs``) — live attribute pointers between units;
- the IDistributable 5-method protocol (reference distributable.py:222-281);
- per-unit wall-time accumulators (reference units.py:184-187,805-817);
- run-after-stop detection as a graph-linking sanitizer (units.py:823-839).

Changed for TPU: execution is an iterative worklist walk driven by the owning
Workflow instead of a thread-pool fan-out — on TPU the overlap the reference's
thread pool provided comes for free from XLA's async dispatch, and the hot
tensor path is collapsed into jitted step functions by the accelerated layer
(see accelerated_units.py), leaving this graph as the build-time structure and
the host-side control plane.
"""

import time

from .config import root
from .mutable import Bool, link_attribute
from .pickling import Lockable
from .registry import UnitRegistry


class IDistributable:
    """The 5-method master/slave data protocol every unit implements.

    Reference: veles/distributable.py:222-281.  In the TPU build the inner
    training step exchanges gradients via in-program ICI collectives; this
    protocol survives for the elastic/meta-level scheduler (ensembles, GA,
    eval) and for loader index distribution.
    """

    negotiates_on_connect = False

    def generate_data_for_master(self):
        return None

    def generate_data_for_slave(self, slave=None):
        return None

    def apply_data_from_master(self, data):
        pass

    def apply_data_from_slave(self, data, slave=None):
        pass

    def drop_slave(self, slave=None):
        pass

    @property
    def has_data_for_slave(self):
        return True


class Unit(Lockable, IDistributable, metaclass=UnitRegistry):
    """Dataflow node with control links, gates, and linked attributes."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__()
        self.name = kwargs.get("name", self.__class__.__name__)
        self.view_group = kwargs.get("view_group", getattr(
            self.__class__, "view_group", "PLUMBING"))
        self._workflow = None
        self.links_from = {}   # src unit -> fired flag (the AND-gate state)
        self.links_to = {}     # dst unit -> True
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        self.ignores_gate = False   # Repeater-style: any input opens the gate
        # service side-branches (plotters, status reporters) set this so
        # the final iteration still reaches them after EndPoint fires
        self.runs_after_stop = False
        self.stopped = False   # set by the unit itself to stop propagating;
        #                        reset by FireStarter (reference units.py:823)
        self.exports = []      # attr names included in package_export
        self.demanded = list(kwargs.get("demand", ()))
        self._initialized = False
        self.timers = {"run": 0.0, "runs": 0}
        if workflow is not None:
            workflow.add_ref(self)

    # -- identity ------------------------------------------------------------
    @property
    def workflow(self):
        return self._workflow

    @workflow.setter
    def workflow(self, value):
        if self._workflow is not None and value is not self._workflow:
            self._workflow.del_ref(self)
        self._workflow = value

    @property
    def is_initialized(self):
        return self._initialized

    def __repr__(self):
        return '<%s "%s">' % (self.__class__.__name__, self.name)

    # -- linked attributes ---------------------------------------------------
    def __getattribute__(self, name):
        if name.startswith("_") or name in ("links_from", "links_to"):
            return object.__getattribute__(self, name)
        links = object.__getattribute__(self, "__dict__").get("_linked_attrs")
        if links and name in links:
            src, sname, _ = links[name]
            return getattr(src, sname)
        return object.__getattribute__(self, name)

    def __setattr__(self, name, value):
        if not name.startswith("_"):
            links = self.__dict__.get("_linked_attrs")
            if links and name in links:
                src, sname, two_way = links[name]
                if two_way:
                    setattr(src, sname, value)
                    return
                del links[name]  # one-way write takes local ownership
        object.__setattr__(self, name, value)

    def link_attrs(self, other, *mappings, two_way=False):
        """Point attributes of self at attributes of ``other``.

        Each mapping is either a name (same on both sides) or a
        ``(my_name, other_name)`` pair — reference units.py:638.
        """
        for m in mappings:
            if isinstance(m, str):
                mine = theirs = m
            else:
                mine, theirs = m
            if not hasattr(other, theirs):
                raise AttributeError(
                    "%s has no attribute %r to link into %s" %
                    (other, theirs, self))
            link_attribute(self, mine, other, theirs, two_way=two_way)
        return self

    def unlink_attrs(self, *names):
        from .mutable import unlink_attribute
        for n in names:
            unlink_attribute(self, n)

    # -- control links -------------------------------------------------------
    def link_from(self, *units):
        """Add control edges ``unit -> self`` (reference units.py:554)."""
        for u in units:
            self.links_from[u] = False
            u.links_to[self] = True
        return self

    def unlink_from(self, *units):
        for u in units:
            self.links_from.pop(u, None)
            u.links_to.pop(self, None)
        return self

    def unlink_all(self):
        for u in list(self.links_from):
            self.unlink_from(u)
        for d in list(self.links_to):
            d.unlink_from(self)
        return self

    # -- lifecycle -----------------------------------------------------------
    def initialize(self, **kwargs):
        """Prepare for running.  Subclasses override; called in dependency
        order by Workflow.initialize.  Returning True means "not ready yet,
        retry after the rest" (reference deferred-init protocol)."""
        self._initialized = True

    def run(self):
        """The unit's work.  Subclasses override."""

    def stop(self):
        """Called when the workflow is stopping; release resources."""

    # -- gate machinery ------------------------------------------------------
    def open_gate(self, src):
        """Mark ``src`` fired; True when all input links have fired.

        Reference semantics (units.py:524): the AND-gate latches each input;
        when the last one arrives all latches reset and the gate opens.
        Units with ``ignores_gate`` (Repeater) open on any input.
        """
        if src is not None and src in self.links_from:
            self.links_from[src] = True
        if self.ignores_gate:
            for k in self.links_from:
                self.links_from[k] = False
            return True
        if all(self.links_from.values()):
            for k in self.links_from:
                self.links_from[k] = False
            return True
        return False

    def reset_gates(self):
        for k in self.links_from:
            self.links_from[k] = False

    def signal(self, src, schedule):
        """An input link fired.  ``schedule(unit)`` enqueues a ready unit.

        ``gate_block`` suppresses the gate entirely — a blocked unit does not
        latch input firings (reference run_dependent checks gate_block before
        open_gate), so no partial gate state leaks past an unblock.
        """
        if bool(self.gate_block):
            return
        if not self.open_gate(src):
            return
        schedule(self)

    def execute(self, schedule):
        """Run (unless gate_skip) and propagate to dependents."""
        wf = self._workflow
        if wf is not None and wf.is_finished and \
                not (self.ignores_gate or self.runs_after_stop):
            # run-after-stop: a linking bug in the graph (units.py:823-839)
            wf.warning_run_after_stop(self)
            return
        if not bool(self.gate_skip):
            t0 = time.monotonic()
            self.run()
            dt = time.monotonic() - t0
            self.timers["run"] += dt
            self.timers["runs"] += 1
            name = self.__class__.__name__
            if name in root.common.get("timings", set()):
                print("%s: run %.3f ms" % (self.name, dt * 1e3))
            if root.common.observability.get("unit_metrics", False):
                # opt-in: every unit run lands in the process-global
                # registry (one histogram series per unit name) — the
                # /metrics twin of print_stats' end-of-run table
                from .observability.registry import REGISTRY
                REGISTRY.histogram(
                    "veles_unit_run_seconds",
                    "Per-unit run() wall time",
                    ("unit", "cls")).labels(
                    unit=self.name, cls=name).observe(dt)
            from .logger import events
            if events.enabled:
                # per-run span into the JSONL event stream (the Mongo
                # event replacement — reference logger.py:264-289 wrapped
                # run the same way); events.enabled also honors the
                # VELES_TRACE_DIR env switch, not just the config flag
                events.span(self.name, dt, cls=name)
        if self.stopped and not isinstance(self, Container):
            return  # unit declared itself done; FireStarter can revive it
        self.run_dependent(schedule)

    def run_dependent(self, schedule):
        """Fire all outgoing links (reference units.py:485)."""
        for dst in self.links_to:
            dst.signal(self, schedule)

    # -- introspection -------------------------------------------------------
    def resolve_linked(self, name):
        """Terminal ``(owner, attr)`` of a possibly-chained linked
        attribute: follows ``link_attrs`` pointers (gd.err_output →
        next_gd.err_input → ...) to the unit that actually owns the
        storage — the graph compiler's data-edge resolution, matching
        what ``__getattribute__`` does dynamically."""
        unit, attr, seen = self, name, set()
        while True:
            links = unit.__dict__.get("_linked_attrs") or {}
            if attr in links and (id(unit), attr) not in seen:
                seen.add((id(unit), attr))
                src, sname, _ = links[attr]
                unit, attr = src, sname
            else:
                return unit, attr

    def data_links(self):
        """{my_attr: (owner_unit, owner_attr)} for every linked attribute
        (resolved to its terminal owner)."""
        links = self.__dict__.get("_linked_attrs") or {}
        return {name: self.resolve_linked(name) for name in links}

    def make_trace(self):
        """The unit's pure per-step face for whole-workflow compilation
        (:mod:`veles_tpu.graphcomp`): return a
        :class:`~veles_tpu.graphcomp.faces.TraceFace` to participate in
        traced regions, a ``NoFace(reason)`` to document why not, or
        None (default) for host-side units — the tracer then keeps this
        unit interpreted and reports a family-derived reason."""
        return None

    def describe(self):
        return {
            "name": self.name,
            "class": self.__class__.__name__,
            "uuid": getattr(self.__class__, "UUID", None),
            "links_to": [u.name for u in self.links_to],
            "view_group": self.view_group,
        }

    def verify_demands(self):
        missing = [d for d in self.demanded
                   if getattr(self, d, None) is None]
        if missing:
            raise ValueError("%s: demanded attributes not supplied: %s" %
                             (self, ", ".join(missing)))


class TrivialUnit(Unit):
    """A unit that does nothing (reference units.py:916)."""

    def initialize(self, **kwargs):
        super().initialize(**kwargs)

    def run(self):
        pass


class Container(Unit):
    """Marker base for units that contain other units (units.py:925)."""

    hide_from_registry = True
