"""Pickling protocol for framework objects.

Mirrors the reference ``Pickleable`` semantics
(/root/reference/veles/distributable.py:48-133): every attribute whose name
ends with ``_`` is *transient* — excluded from pickles — and must be restored
by ``init_unpickled()``, which runs both at construction and after unpickling.
"""

import threading


class Pickleable:
    """Base for objects that survive pickling with transient state.

    Subclasses override :meth:`init_unpickled` to (re)create every
    ``*_``-suffixed attribute and must call ``super().init_unpickled()``.
    """

    def __init__(self):
        self.init_unpickled()

    def init_unpickled(self):
        """(Re)create transient state.  Called on init and on unpickle."""
        self.stream_ = None

    def __getstate__(self):
        state = {}
        for key, value in self.__dict__.items():
            if key.endswith("_"):
                continue
            if callable(value) and getattr(value, "__self__", None) is self:
                continue  # bound methods of self are rebuilt on restore
            if callable(value) and getattr(value, "transient_", False):
                # instrumentation wrappers installed over methods (e.g.
                # a MinibatchPrefetcher's run()) mark themselves
                # transient: they hold threads/queues and are re-attached
                # after restore, never pickled
                continue
            state[key] = value
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.init_unpickled()


class Lockable(Pickleable):
    """Pickleable with a transient reentrant lock (``_lock_``)."""

    def init_unpickled(self):
        super().init_unpickled()
        self._lock_ = threading.RLock()

    def __enter__(self):
        self._lock_.acquire()
        return self

    def __exit__(self, *unused):
        self._lock_.release()
