"""Command line interface: ``python -m veles_tpu <workflow.py> [config.py]``.

TPU-native re-creation of /root/reference/veles/__main__.py:136-726.  The
capability surface kept from the reference CLI:

- workflow module loading by file path or dotted module name
  (reference import_file.py:50,66), config file application, then
  ``root.x.y=value`` command-line overrides (reference __main__.py:432-478);
- the ``run(load, main)`` module convention (reference
  manualrst_veles_workflow_creation.rst:30-39, __main__.py:591-726);
- ``--snapshot`` resume (reference __main__.py:539-589 — file source; odbc/
  http sources intentionally dropped in the zero-egress build);
- deterministic seeding via ``--random-seed`` (reference :483-539);
- ``--dry-run`` levels load/init/exec (reference cmdline.py);
- ``--result-file``, ``--dump-config``, ``--visualize`` (dot graph);
- backend selection ``--backend`` (reference ``-a/--accelerator``).

TPU-native additions (replacing the master/slave flags): ``--mesh
data=8,model=2`` + ``--model-axis`` request an SPMD run over a device
mesh; ``--mode fused|graph|scan`` picks the execution strategy
(SURVEY.md §7 design stance).
"""

import argparse
import ast
import importlib
import importlib.util
import json
import os
import sys

from .config import root, fix_config, set_config_by_path
from .launcher import Launcher


def _parse_value(text):
    """Parse an override value: python literal if possible, else string."""
    try:
        return ast.literal_eval(text)
    except (ValueError, SyntaxError):
        return text


def import_workflow_module(spec):
    """Import a workflow module from a file path or dotted module name
    (reference import_file.py:50-66 package-or-module logic).  A file that
    lives inside a package tree (``__init__.py`` chain) is imported by its
    dotted name so its relative imports resolve."""
    if not os.path.exists(spec):
        if "." not in spec:
            # bare name: prefer the bundled sample of that name
            # ("veles-tpu mnist" just works from an installed package)
            sample = "veles_tpu.znicz.samples." + spec
            try:
                return importlib.import_module(sample)
            except ModuleNotFoundError as e:
                if e.name != sample:
                    raise  # a BROKEN sample must not be masked as absent
        return importlib.import_module(spec)
    path = os.path.abspath(spec)
    name = os.path.splitext(os.path.basename(path))[0]
    # climb the package chain
    parts, d = [name], os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    if len(parts) > 1:
        if d not in sys.path:
            sys.path.insert(0, d)
        return importlib.import_module(".".join(parts))
    module_spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(module_spec)
    sys.modules[name] = module
    module_spec.loader.exec_module(module)
    return module


def apply_config_file(path):
    """Execute a config file with ``root`` (and ``Range``, for GA
    tuneables) in scope (the reference runpy convention, __main__.py:432;
    reference configs imported veles.genetics.Range the same way)."""
    from .config import Range
    with open(path) as f:
        source = f.read()
    exec(compile(source, path, "exec"), {"root": root, "Range": Range})


def parse_seed(spec):
    """--random-seed value → int: decimal, ``0x``/bare hex, or
    ``file:N`` (N bytes read from the file, e.g. ``/dev/urandom:16``) —
    the reference's seeding spec surface (__main__.py:483-539)."""
    spec = str(spec)
    if ":" in spec and not spec.lower().startswith("0x"):
        fname, _, count = spec.rpartition(":")
        try:
            n = int(count)
            with open(fname, "rb") as f:
                data = f.read(n)
        except (ValueError, OSError) as e:
            raise SystemExit("bad --random-seed %r (%s)" % (spec, e))
        if len(data) < n:
            raise SystemExit("--random-seed %r: %s has only %d bytes"
                             % (spec, fname, len(data)))
        return int.from_bytes(data, "little") % (1 << 63)
    try:
        return int(spec, 0)     # decimal or 0x-prefixed hex
    except ValueError:
        try:
            return int(spec, 16)  # bare hex digest (reference unhexlify)
        except ValueError:
            raise SystemExit(
                "bad --random-seed %r (want an int, hex, or file:N)"
                % spec)


def parse_mesh(text):
    """``data=8,model=2`` → {"data": 8, "model": 2}."""
    axes = {}
    for part in text.split(","):
        name, _, size = part.partition("=")
        if not size:
            raise argparse.ArgumentTypeError(
                "mesh axis %r needs =SIZE" % part)
        axes[name.strip()] = int(size)
    return axes


def make_parser():
    p = argparse.ArgumentParser(
        prog="veles_tpu",
        description="TPU-native VELES: run a workflow module.")
    p.add_argument("workflow", nargs="?",
                   help="workflow module (.py path or dotted name)")
    p.add_argument("config", nargs="?",
                   help="config file applied before overrides")
    p.add_argument("overrides", nargs="*", metavar="root.x.y=value",
                   help="config overrides")
    p.add_argument("-s", "--snapshot", default=None,
                   help="resume from a snapshot file")
    p.add_argument("--random-seed", type=str, default=None,
                   metavar="N|0xHEX|PATH:NBYTES",
                   help="seed for the deterministic PRNG tree "
                        "(decimal, hex, or NBYTES read from PATH, "
                        "e.g. /dev/urandom:16 — see parse_seed)")
    p.add_argument("-a", "--backend", default=None,
                   choices=("auto", "tpu", "cpu", "numpy"),
                   help="compute backend (default: config)")
    p.add_argument("--mode", default=None,
                   choices=("fused", "graph", "scan"),
                   help="execution strategy (default: workflow's)")
    p.add_argument("--mesh", type=parse_mesh, default=None,
                   metavar="data=8[,model=2]",
                   help="SPMD device mesh axes")
    p.add_argument("--model-axis", default=None,
                   help="mesh axis for tensor parallelism")
    p.add_argument("--tp-mode", default=None,
                   choices=("column", "megatron"),
                   help="tensor-parallel layout: column-split every "
                        "layer, or megatron col/row alternation (one "
                        "psum per FC pair instead of a gather per layer)")
    p.add_argument("--set", action="append", default=[], dest="sets",
                   metavar="attr.path=value",
                   help="set a workflow attribute after build/restore "
                        "(e.g. --set decision.max_epochs=50); the way to "
                        "extend a resumed run past its pickled limits")
    p.add_argument("--dry-run", default="exec",
                   choices=("load", "init", "exec"),
                   help="stop after load/init (default: full run)")
    p.add_argument("--result-file", default=None,
                   help="write gathered results JSON here ('-' = stdout)")
    p.add_argument("--dump-config", action="store_true",
                   help="print the effective config tree and exit")
    p.add_argument("--visualize", default=None, metavar="FILE.dot",
                   help="write the unit graph in dot format")
    p.add_argument("--stats", action="store_true",
                   help="print per-unit timing stats after the run")
    p.add_argument("--profile", action="store_true",
                   help="attach the StepProfiler (data-wait/host/device "
                        "step split, recompile count, examples/sec into "
                        "/metrics and the result JSON; equivalent to "
                        "root.common.observability.profile=True)")
    p.add_argument("--no-fix-config", action="store_true",
                   help="keep Range placeholders (genetic optimizer use)")
    from .cmdline import contribute_arguments
    p._veles_arg_paths = contribute_arguments(p)
    p.add_argument("--death-probability", type=float, default=0.0,
                   help="fault injection: crash with this probability at "
                        "each epoch end (reference "
                        "--slave-death-probability)")
    p.add_argument("--die-at-epoch", type=int, default=None,
                   help="fault injection: crash deterministically at this "
                        "epoch end (elastic-recovery drills)")
    p.add_argument("--optimize", default=None, metavar="SIZE[:GENERATIONS]",
                   help="GA-optimize the config's Range values by running "
                        "trials as subprocesses (reference --optimize)")
    p.add_argument("--fitness-key", default="best_validation_error_pt",
                   help="result JSON key minimized by --optimize")
    p.add_argument("--ensemble-train", default=None, metavar="SIZE[:RATIO]",
                   help="train SIZE instances on random train subsets "
                        "(reference --ensemble-train size:ratio)")
    p.add_argument("--ensemble-test", default=None, metavar="FILE.json",
                   help="averaged-probability inference over the "
                        "ensemble train output JSON")
    p.add_argument("--serve", action="append", default=[],
                   metavar="PKG.zip[:NAME]", dest="serve",
                   help="serve exported package(s) over HTTP with "
                        "dynamic batching instead of training "
                        "(repeatable; NAME defaults to the file stem); "
                        "see veles_tpu.serving")
    p.add_argument("--serve-port", type=int, default=8080,
                   help="inference server port (default 8080)")
    p.add_argument("--serve-hostname", default="127.0.0.1",
                   help="inference server bind address (loopback "
                        "default keeps the models private)")
    p.add_argument("--serve-max-batch", type=int, default=64,
                   help="largest request batch bucket (power-of-two "
                        "ladder compiled at startup)")
    p.add_argument("--serve-queue-limit", type=int, default=256,
                   help="outstanding-request bound; beyond it requests "
                        "are shed with HTTP 429")
    p.add_argument("--serve-workers", type=int, default=1,
                   help="dispatch worker threads per model")
    p.add_argument("--serve-seconds", type=float, default=None,
                   help="serve for N seconds then drain and exit "
                        "(default: until SIGINT; smoke tests/CI)")
    p.add_argument("--frontend", action="store_true",
                   help="interactive wizard: answer prompts, get the "
                        "generated command line, run it (reference "
                        "--frontend web wizard, terminal edition)")
    p.add_argument("--listen", default=None, metavar="HOST:PORT",
                   help="farm --optimize/--ensemble-train trials through "
                        "a TCP job master bound here; start workers on "
                        "any host with `python -m veles_tpu.jobserver "
                        "HOST PORT` (reference master -l role)")
    p.add_argument("--workers", type=int, default=0, metavar="N",
                   help="also spawn N local trial worker processes "
                        "(elastic: dead workers respawn with backoff)")
    return p


class Main:
    """CLI driver implementing the reference ``run(load, main)`` contract
    (reference __main__.py:136,591-726)."""

    def __init__(self, argv=None):
        parser = make_parser()
        self.args = parser.parse_args(argv)
        self._arg_paths = parser._veles_arg_paths
        self.launcher = None
        self.workflow = None
        self.snapshot_loaded = False

    # -- the two callbacks handed to the workflow module ---------------------
    def _load(self, factory, **kwargs):
        """Build the workflow (or restore it from ``--snapshot``); returns
        (workflow, was_restored)."""
        args = self.args
        if args.snapshot:
            if args.mesh or args.model_axis or args.mode or args.tp_mode:
                raise SystemExit(
                    "--mesh/--model-axis/--tp-mode/--mode cannot be "
                    "applied to a restored snapshot (the pickled "
                    "workflow keeps its build-time execution strategy); "
                    "rebuild without --snapshot, or restore and resume "
                    "as-is")
            from .snapshotter import restore
            self.workflow = restore(args.snapshot)
            self.snapshot_loaded = True
        else:
            if args.mode == "graph":
                kwargs.setdefault("fused", False)
            elif args.mode == "scan":
                kwargs.setdefault("epoch_scan", True)
            elif args.mode == "fused":
                kwargs.setdefault("fused", True)
            if args.mesh:
                from .parallel.mesh import make_mesh
                kwargs.setdefault("mesh", make_mesh(args.mesh))
                if args.model_axis:
                    kwargs.setdefault("model_axis", args.model_axis)
                if args.tp_mode:
                    kwargs.setdefault("tp_mode", args.tp_mode)
            self.workflow = factory(**kwargs)
        for assignment in args.sets:
            path, _, value = assignment.partition("=")
            if not value:
                raise SystemExit("--set %r needs =value" % assignment)
            obj = self.workflow
            parts = path.split(".")
            for p in parts[:-1]:
                obj = getattr(obj, p)
            setattr(obj, parts[-1], _parse_value(value))
        if args.death_probability or args.die_at_epoch is not None:
            from .distributed import Reaper
            wf = self.workflow
            reaper = next((u for u in wf if isinstance(u, Reaper)), None)
            if reaper is None and hasattr(wf, "decision") and \
                    hasattr(wf, "loader"):
                from .prng import RandomGenerator
                # own seeded stream: drills replay under --random-seed
                # without consuming the loaders' stream
                seed = (parse_seed(args.random_seed)
                        if args.random_seed is not None else 1234) + 313
                reaper = Reaper(wf, prng=RandomGenerator().seed(seed))
                reaper.link_from(wf.decision)
                reaper.link_loader(wf.loader)
            if reaper is not None:
                reaper.death_probability = args.death_probability
                reaper.die_at_epoch = args.die_at_epoch
        self.launcher.add_workflow(self.workflow)
        return self.workflow, self.snapshot_loaded

    def _main(self, **kwargs):
        args = self.args
        if args.dry_run == "load":
            return self.workflow
        if args.profile:
            root.common.observability.profile = True
        self.launcher.initialize(**kwargs)
        if args.visualize:
            self.workflow.generate_graph(args.visualize)
        if args.dry_run == "init":
            return self.workflow
        self.launcher.run()
        if args.stats:
            self.launcher.print_stats()
        return self.workflow

    # -- entry ---------------------------------------------------------------
    def run(self):
        args = self.args
        if args.serve:
            return self._run_serve()
        if args.frontend:
            return self._run_frontend()
        if args.config is not None and "=" in args.config \
                and not os.path.exists(args.config):
            # `workflow.py root.x=1` without a config file
            args.overrides.insert(0, args.config)
            args.config = None
        if args.ensemble_test:
            # pure aggregation over an existing ensemble JSON — no
            # workflow module involved
            from . import ensemble
            self._write_result(ensemble.test(args.ensemble_test))
            return 0
        if not args.workflow:
            if args.dump_config:
                root.print_()
                return 0
            make_parser().print_help()
            return 2
        # the module import registers the workflow's config DEFAULTS; the
        # config file, then the CLI overrides, are applied on top of them
        # (reference order: _load_model :401 before _apply_config :432)
        module = import_workflow_module(args.workflow)
        # machine-local site_config lands AFTER the module's defaults
        # (so a site file can actually override them) and BEFORE the
        # config file / CLI overrides (which stay the most specific).
        # The reference applied site files at config-import time, which
        # let module defaults clobber them (config.py:294-308) — this
        # order is the deliberate improvement.
        from .config import apply_site_config
        apply_site_config()
        if args.config:
            apply_config_file(args.config)
        for override in args.overrides:
            path, _, value = override.partition("=")
            if not value:
                raise SystemExit("override %r needs =value" % override)
            set_config_by_path(root, path, _parse_value(value))
        # class-contributed options (reference cmdline.py distributed
        # argparse) — applied LAST so an explicit flag beats config files
        from .cmdline import apply_arguments
        apply_arguments(args, self._arg_paths, set_config_by_path, root)
        if args.optimize or args.ensemble_train:
            return self._run_meta(module)
        if args.listen or args.workers:
            raise SystemExit(
                "--listen/--workers distribute --optimize/--ensemble-train "
                "trials; pass one of those meta flags (a plain training "
                "run is a single process — use --mesh for multi-chip)")
        if not args.no_fix_config:
            fix_config(root)
        if args.dump_config:
            root.print_()
            return 0
        seed = args.random_seed
        if seed is None:
            seed = root.common.get("random_seed", 1234)
        from . import prng
        prng.get(0).seed(parse_seed(seed))
        self.launcher = Launcher(backend=args.backend,
                                 result_file=args.result_file)
        if not hasattr(module, "run"):
            raise SystemExit(
                "workflow module %r does not define run(load, main)"
                % args.workflow)
        module.run(self._load, self._main)
        wf = self.workflow
        if wf is not None and args.dry_run == "exec" and not wf.is_finished:
            return 1  # unit queue drained without reaching the end point
        return 0


    def _run_serve(self, output=print):
        """``--serve pkg.zip`` mode: stand up the dynamic-batching
        inference server on the exported package(s) and block until
        SIGINT (or ``--serve-seconds``), then drain gracefully.  The
        train-side flags don't apply; ``--backend`` still picks the
        JAX platform the executables compile for."""
        args = self.args
        # with --serve there is no workflow module, so positional args
        # shift: `root.x=v` strings (and a config file) slide from the
        # workflow/config slots into the override list
        for slot in ("config", "workflow"):
            value = getattr(args, slot)
            if value is not None and "=" in value \
                    and not os.path.exists(value):
                args.overrides.insert(0, value)
                setattr(args, slot, None)
        if args.workflow:
            raise SystemExit("--serve serves exported packages; drop "
                             "the workflow argument (train first, "
                             "export with veles_tpu.export, then serve "
                             "the package zip)")
        if args.backend and args.backend not in ("auto", "numpy"):
            import jax
            jax.config.update("jax_platforms", args.backend)
        # config overrides apply in serve mode too — that's how the
        # compile cache is pointed at its directory from the CLI
        # (`root.common.compile_cache={'dir': ...}`); a config file in
        # the shifted positional slot applies first, overrides on top
        if args.config:
            apply_config_file(args.config)
        for override in args.overrides:
            path, _, value = override.partition("=")
            if not value:
                raise SystemExit("override %r needs =value" % override)
            set_config_by_path(root, path, _parse_value(value))
        from .serving import InferenceServer
        models = []
        for spec in args.serve:
            path, _, name = spec.partition(":")
            if not name:
                name = os.path.splitext(os.path.basename(path))[0]
            models.append((name, path))
        # models register (and warmup-compile their bucket ladders)
        # BEFORE the socket opens: the first request ever seen is
        # already warm, and /healthz never advertises an empty server
        server = InferenceServer(
            models, port=args.serve_port, host=args.serve_hostname,
            max_batch=args.serve_max_batch,
            queue_limit=args.serve_queue_limit,
            workers=args.serve_workers)
        try:
            for name, path in models:
                entry = server.registry.get(name)
                output("serving %r from %s  (buckets %s)  POST %s/api/%s"
                       % (name, path, entry.scheduler.buckets,
                          server.url, name))
            output("endpoints: POST %s/api  ·  GET %s/healthz  ·  "
                   "GET %s/metrics" % (server.url, server.url, server.url))
            try:
                import threading
                threading.Event().wait(args.serve_seconds)
            except KeyboardInterrupt:
                output("draining...")
        finally:
            server.stop(drain=True)
        return 0

    def _run_frontend(self, input_fn=input, output=print):
        """Terminal wizard: prompt for the run's pieces, print the
        generated command line, execute it (the reference's --frontend
        opened a web wizard that produced a command line the same way,
        __main__.py:258-285)."""
        def ask(prompt, default=""):
            try:
                answer = input_fn("%s%s: " % (
                    prompt, " [%s]" % default if default else ""))
            except EOFError:
                return default
            return answer.strip() or default

        argv = []
        workflow = ask("Workflow module/file", self.args.workflow or "")
        if not workflow:
            raise SystemExit("--frontend needs a workflow to run")
        argv.append(workflow)
        config = ask("Config file (blank = none)")
        if config:
            argv.append(config)
        while True:
            override = ask("Override root.x.y=value (blank = done)")
            if not override:
                break
            if "=" not in override:
                output("  ignored (need path=value): %s" % override)
                continue
            argv.append(override)
        backend = ask("Backend (auto/tpu/cpu/numpy)", "auto")
        if backend and backend != "auto":
            argv += ["--backend", backend]
        mode = ask("Execution mode (fused/scan/graph)", "fused")
        if mode and mode != "fused":
            argv += ["--mode", mode]
        seed = ask("Random seed", "1234")
        if seed:
            argv += ["--random-seed", seed]
        result_file = ask("Result JSON file (blank = none)")
        if result_file:
            argv += ["--result-file", result_file]
        import shlex
        output("Running with the following command line: "
               "python -m veles_tpu %s" % shlex.join(argv))
        if ask("Proceed? (y/n)", "y").lower() not in ("y", "yes"):
            return 2
        return Main(argv).run()

    # -- meta modes: GA optimization and ensembles ---------------------------
    def _trial_argv(self):
        """CLI arguments each subprocess trial inherits (config file,
        overrides, backend/mode — NOT the meta flags themselves)."""
        args = self.args
        argv = []
        if args.config:
            # trials run with cwd=repo root (subproc.run_trial); a
            # relative config path from the user's cwd must survive that
            argv.append(os.path.abspath(args.config))
        argv += args.overrides
        if args.backend:
            argv += ["--backend", args.backend]
        if args.mode:
            argv += ["--mode", args.mode]
        if args.mesh:
            argv += ["--mesh", ",".join("%s=%d" % kv
                                        for kv in args.mesh.items())]
        if args.model_axis:
            argv += ["--model-axis", args.model_axis]
        if args.tp_mode:
            argv += ["--tp-mode", args.tp_mode]
        if args.snapshot:
            argv += ["--snapshot", args.snapshot]
        for assignment in args.sets:
            argv += ["--set", assignment]
        if args.random_seed is not None:
            # forward the RESOLVED int, not the spec: a PATH:NBYTES
            # spec (e.g. /dev/urandom:16) re-read per trial would give
            # every trial a different seed, breaking the determinism
            # guarantee trials rely on
            argv += ["--random-seed",
                     str(parse_seed(args.random_seed))]
        # class-contributed flags travel as config overrides so trials
        # see them too (the flags themselves are parsed per process)
        for dest, path in self._arg_paths.items():
            value = getattr(args, dest, None)
            if value is not None:
                argv.append("%s=%r" % (path, value))
        return argv

    def _write_result(self, payload):
        args = self.args
        text = json.dumps(payload, indent=2)
        if args.result_file and args.result_file != "-":
            with open(args.result_file, "w") as f:
                f.write(text)
        else:
            print(text)

    def _run_meta(self, module):
        """Dispatch --optimize / --ensemble-train (--ensemble-test is
        handled earlier in run(): it needs no workflow module).  The
        reference ran these same meta-workflows by re-invoking its own
        CLI per trial (optimization_workflow.py:286-296,
        ensemble/base_workflow.py:134-141).  With --listen/--workers the
        trials go through the cross-host job queue (jobserver.py)."""
        args = self.args
        scheduler = pool = None
        if args.listen or args.workers:
            from .jobserver import JobMaster, WorkerPool, parse_address
            host, port = parse_address(args.listen) if args.listen \
                else ("127.0.0.1", 0)
            scheduler = JobMaster(host, port, silent=False)
            if args.workers:
                pool = WorkerPool(scheduler.address, args.workers)
        try:
            if args.ensemble_train:
                from . import ensemble
                size, _, ratio = args.ensemble_train.partition(":")
                trial_argv = self._trial_argv()
                if ratio:
                    # an explicit N:ratio is the most specific setting —
                    # strip any --train-ratio-derived override so it wins
                    trial_argv = [
                        a for a in trial_argv if not str(a).startswith(
                            "root.common.ensemble.train_ratio=")]
                out = ensemble.train(
                    args.workflow, int(size),
                    train_ratio=float(ratio) if ratio
                    else (args.train_ratio or 1.0),
                    argv=trial_argv, scheduler=scheduler,
                    out_file=(args.result_file
                              if args.result_file not in (None, "-")
                              else None))
                if args.result_file in (None, "-"):
                    self._write_result(out["summary"])
                return 0
            from .genetics import GeneticsOptimizer
            size, _, gens = args.optimize.partition(":")
            trial_argv = self._trial_argv()
            if args.random_seed is None:
                # trials must still be deterministic relative to each other
                trial_argv += ["--random-seed", "1234"]
            opt = GeneticsOptimizer(
                model=args.workflow, config=root, size=int(size),
                generations=int(gens) if gens else 2,
                fitness_key=args.fitness_key, argv=trial_argv,
                scheduler=scheduler)
            best = opt.run()
            self._write_result(best)
            return 0
        finally:
            # master first: its EOF is what makes idle workers exit 0,
            # so the pool close below reaps them instead of killing them
            if scheduler is not None:
                scheduler.close()
            if pool is not None:
                pool.close()


def main(argv=None):
    return Main(argv).run()


if __name__ == "__main__":
    sys.exit(main())
