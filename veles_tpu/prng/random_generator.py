"""Seeded random generators with snapshot-safe state.

Re-design of /root/reference/veles/prng/random_generator.py:64 (numpy
RandomState wrapper with state save/restore and global keyed instances) plus
a TPU-side answer to the reference's device xorshift1024* unit
(cuda/random.cu:40-60): stateless :mod:`jax.random` keys derived from a
:class:`KeyTree`, so every unit's device randomness is a pure function of
(seed, unit name, step counter) — reproducible across restarts and shardings
without device-side state, which is the JAX-idiomatic replacement for
replaying RandomState per unit (reference units.py:859-885).
"""

import threading

import numpy


class RandomGenerator:
    """Deterministic numpy generator with pickle-able state."""

    def __init__(self, key=None):
        self.key = key
        self._state = numpy.random.RandomState()
        self._seed_value = None

    def seed(self, seed, dtype=None, count=None):
        """Seed from an int, bytes, or an array (the reference accepts raw
        seed files and hex strings, __main__.py:483-539)."""
        if isinstance(seed, (bytes, bytearray)):
            pad = (-len(seed)) % 4
            seed = numpy.frombuffer(bytes(seed) + b"\0" * pad,
                                    dtype=numpy.uint32)
        if isinstance(seed, numpy.ndarray):
            raw = seed.tobytes()
            raw += b"\0" * ((-len(raw)) % 4)
            seed = int(numpy.bitwise_xor.reduce(
                numpy.frombuffer(raw, numpy.uint32)))
        self._seed_value = int(seed) & 0xFFFFFFFF
        self._state = numpy.random.RandomState(self._seed_value)
        return self

    @property
    def seed_value(self):
        return self._seed_value

    # numpy-compatible sampling surface -------------------------------------
    def normal(self, loc=0.0, scale=1.0, size=None):
        return self._state.normal(loc, scale, size)

    def uniform(self, low=0.0, high=1.0, size=None):
        return self._state.uniform(low, high, size)

    def randint(self, low, high=None, size=None, dtype=int):
        return self._state.randint(low, high, size, dtype)

    def shuffle(self, arr):
        self._state.shuffle(arr)

    def permutation(self, n):
        return self._state.permutation(n)

    def choice(self, a, size=None, replace=True, p=None):
        return self._state.choice(a, size, replace, p)

    def bytes(self, n):
        return self._state.bytes(n)

    def fill(self, arr, vmin=-1.0, vmax=1.0):
        """In-place uniform fill (reference RandomGenerator.fill)."""
        arr[...] = self._state.uniform(vmin, vmax, arr.shape).astype(
            arr.dtype)

    # state save/restore (snapshot determinism) ------------------------------
    @property
    def state(self):
        return self._state.get_state()

    @state.setter
    def state(self, value):
        self._state.set_state(value)

    def __getstate__(self):
        return {"key": self.key, "seed": self._seed_value,
                "state": self._state.get_state()}

    def __setstate__(self, state):
        self.key = state["key"]
        self._seed_value = state["seed"]
        self._state = numpy.random.RandomState()
        self._state.set_state(state["state"])


_lock = threading.Lock()
_generators = {}


def get(key=0):
    """Global keyed generator instances (reference ``prng.get(n)``)."""
    with _lock:
        gen = _generators.get(key)
        if gen is None:
            import zlib
            gen = _generators[key] = RandomGenerator(key)
            gen.seed(42 + (key if isinstance(key, int)
                           else zlib.crc32(str(key).encode())))
        return gen


class KeyTree:
    """Stateless JAX PRNG keys for units: key = fold_in(root, name, step).

    The per-unit step counters are plain ints, so they pickle with the
    workflow snapshot and restore deterministic randomness on resume.
    """

    def __init__(self, seed=42):
        self.seed = int(seed)
        self.counters = {}

    def key_for(self, name, advance=True):
        import jax
        import zlib
        c = self.counters.get(name, 0)
        if advance:
            self.counters[name] = c + 1
        key = jax.random.key(self.seed)
        key = jax.random.fold_in(
            key, zlib.crc32(str(name).encode()) & 0x7FFFFFFF)
        return jax.random.fold_in(key, c)

    def __getstate__(self):
        return {"seed": self.seed, "counters": dict(self.counters)}

    def __setstate__(self, state):
        self.seed = state["seed"]
        self.counters = state["counters"]
