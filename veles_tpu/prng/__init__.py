"""Reproducible random number generation.

Reference: /root/reference/veles/prng/ (RandomGenerator at
random_generator.py:64, keyed global instances via ``prng.get(n)``).
"""

from .random_generator import RandomGenerator, get, KeyTree  # noqa: F401
