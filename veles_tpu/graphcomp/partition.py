"""Graph introspection + region partition over a workflow's unit DAG.

Walks the ``link_from`` control DAG and the ``link_attrs`` data links of an
initialized workflow, asks every unit for its :mod:`trace face <.faces>`,
and partitions the traceable units into maximal regions — weakly-connected
components of the control graph restricted to traceable nodes.  Host-side
units (loaders, deciders, plotters, snapshotters, plumbing) sit at region
boundaries with a recorded *fallback reason*: the debugging face behind
``tools/dump_graph.py`` ("why didn't my unit fuse?") and the
``veles_graph_fallback_units`` gauge.

The partition is DESCRIPTIVE: at run time the interpreter's own worklist
order decides what actually batches into one compiled program (gates and
all — see :mod:`.runtime`), so the region report and the executed programs
agree by construction rather than by a second scheduler.
"""

from .faces import NoFace, TraceFace


def _default_reason(unit):
    """Reason a unit without a face stays host-side, by family."""
    from ..loader.base import Loader
    from ..plumbing import StartPoint, EndPoint, Repeater, FireStarter
    if isinstance(unit, Loader):
        return ("host-side loader: minibatch serving, shuffling and "
                "epoch bookkeeping stay on the host")
    if isinstance(unit, (StartPoint, EndPoint, Repeater, FireStarter)):
        return "control plumbing (no data math)"
    try:
        from ..znicz.decision import DecisionBase
        if isinstance(unit, DecisionBase):
            return ("host-side control: epoch decisions, early stopping "
                    "and metric resets")
    except Exception:  # noqa: BLE001 — znicz optional in odd builds
        pass
    try:
        from ..snapshotter import SnapshotterBase
        if isinstance(unit, SnapshotterBase):
            return "host-side snapshot I/O"
    except Exception:  # noqa: BLE001
        pass
    return "no pure trace face (host-side unit)"


def _is_snapshotter(unit):
    try:
        from ..snapshotter import SnapshotterBase
        return isinstance(unit, SnapshotterBase)
    except Exception:  # noqa: BLE001
        return False


class UnitInfo:
    __slots__ = ("unit", "face", "reason", "region")

    def __init__(self, unit, face, reason=None, region=None):
        self.unit = unit
        self.face = face          # TraceFace | None
        self.reason = reason      # fallback reason when face is None/opaque
        self.region = region      # region index | None

    @property
    def traceable(self):
        return self.face is not None and not self.face.opaque

    @property
    def opaque(self):
        return self.face is not None and self.face.opaque


class Region:
    __slots__ = ("index", "units", "kind")

    def __init__(self, index, units, kind):
        self.index = index
        self.units = units        # dependency order
        self.kind = kind          # "traced" | "precompiled"


class GraphPlan:
    """The analysis result: per-unit faces + reasons, regions, data edges,
    and the flush-trigger sets the runtime installs."""

    def __init__(self, workflow):
        self.workflow = workflow
        self.infos = []           # UnitInfo, dependency order
        self.by_id = {}           # id(unit) -> UnitInfo
        self.regions = []
        self.data_edges = []      # (dst_unit, dst_attr, src_unit, src_attr)
        #: non-members that overwrite attrs members read as inputs
        #: (the loader): flush BEFORE they run
        self.source_triggers = set()     # id(unit)
        #: non-members that link-read member outputs: flush before they run
        self.reader_triggers = set()     # id(unit)
        #: non-members that link-read boundary-synced attrs (weights):
        #: flush + full state sync before they run
        self.sync_triggers = set()       # id(unit)

    # -- construction --------------------------------------------------------
    @classmethod
    def analyze(cls, workflow):
        from ..workflow import Workflow
        plan = cls(workflow)
        order = [u for u in workflow._dependency_order()
                 if u is not workflow and not isinstance(u, Workflow)]
        for unit in order:
            face, reason = None, None
            maker = getattr(unit, "make_trace", None)
            made = None
            if not unit.links_from and not unit.links_to:
                # outside the control graph entirely (fused-mode
                # forwards/GDs are driven by the step unit, not fired)
                made = NoFace("outside the control graph (driven by "
                              "another unit)")
            elif callable(maker):
                try:
                    made = maker()
                except Exception as exc:  # noqa: BLE001 — a broken face
                    # must degrade to interpreted dispatch, never error
                    made = NoFace("make_trace failed: %s: %s"
                                  % (type(exc).__name__, exc))
            if isinstance(made, TraceFace):
                face = made
                if made.opaque:
                    reason = made.label
            elif isinstance(made, NoFace):
                reason = made.reason
            else:
                reason = _default_reason(unit)
            plan.infos.append(UnitInfo(unit, face, reason))
        plan.by_id = {id(i.unit): i for i in plan.infos}
        plan._build_regions()
        plan._build_data_edges()
        plan._build_triggers()
        return plan

    def _build_regions(self):
        """Weakly-connected components of traceable units over control
        links; opaque (pre-compiled) units are singleton regions."""
        traceable = [i for i in self.infos if i.traceable]
        index = {id(i.unit): n for n, i in enumerate(traceable)}
        parent = list(range(len(traceable)))

        def find(a):
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        def union(a, b):
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[rb] = ra

        for n, info in enumerate(traceable):
            for dst in info.unit.links_to:
                m = index.get(id(dst))
                if m is not None:
                    union(n, m)
        groups = {}
        for n, info in enumerate(traceable):
            groups.setdefault(find(n), []).append(info)
        for members in groups.values():  # insertion = dependency order
            region = Region(len(self.regions),
                            [i.unit for i in members], "traced")
            self.regions.append(region)
            for i in members:
                i.region = region.index
        for info in self.infos:
            if info.opaque:
                region = Region(len(self.regions), [info.unit],
                                "precompiled")
                self.regions.append(region)
                info.region = region.index

    def _build_data_edges(self):
        for info in self.infos:
            unit = info.unit
            links = unit.__dict__.get("_linked_attrs") or {}
            for name in links:
                src, sname = unit.resolve_linked(name)
                self.data_edges.append((unit, name, src, sname))

    def _build_triggers(self):
        members = {id(i.unit) for i in self.infos if i.traceable}
        outputs = {}
        sync_attrs = {}
        for info in self.infos:
            if not info.traceable:
                continue
            for o in info.face.outputs:
                outputs[(id(info.unit), o)] = True
            for a in info.face.sync_attrs:
                sync_attrs[(id(info.unit), a)] = True
        # (a) sources: terminals of member inputs owned by non-members
        for info in self.infos:
            if not info.traceable:
                continue
            for name in info.face.inputs + info.face.statics:
                owner, attr = info.unit.resolve_linked(name)
                if id(owner) not in members and owner is not self.workflow:
                    self.source_triggers.add(id(owner))
        # (b)/(c) readers of member outputs / synced attrs
        for dst, _name, src, sattr in self.data_edges:
            if id(dst) in members:
                continue
            if (id(src), sattr) in outputs:
                self.reader_triggers.add(id(dst))
            if (id(src), sattr) in sync_attrs:
                self.sync_triggers.add(id(dst))
        # snapshotters deepcopy everything: full sync before they run
        for info in self.infos:
            if not info.traceable and _is_snapshotter(info.unit):
                self.sync_triggers.add(id(info.unit))

    # -- reporting -----------------------------------------------------------
    @property
    def traced_unit_count(self):
        return sum(1 for i in self.infos if i.traceable)

    @property
    def fallback_units(self):
        return [(i.unit, i.reason) for i in self.infos
                if i.face is None]

    def describe(self):
        """Human-readable DAG + partition report (tools/dump_graph.py)."""
        wf = self.workflow
        lines = ["workflow %r: %d units, %d traceable, %d regions"
                 % (wf.name, len(self.infos), self.traced_unit_count,
                    len(self.regions))]
        lines.append("")
        lines.append("control DAG:")
        for info in self.infos:
            dsts = ", ".join(d.name for d in info.unit.links_to) or "-"
            lines.append("  %-28s -> %s" % (info.unit.name, dsts))
        lines.append("")
        lines.append("regions:")
        if not self.regions:
            lines.append("  (none — nothing traceable)")
        for region in self.regions:
            lines.append("  region %d [%s, %d unit%s]: %s" % (
                region.index, region.kind, len(region.units),
                "s" if len(region.units) != 1 else "",
                ", ".join(u.name for u in region.units)))
        lines.append("")
        lines.append("host-side / fallback units:")
        for unit, reason in self.fallback_units:
            lines.append("  %-28s %s" % (unit.name, reason))
        opaques = [i for i in self.infos if i.opaque]
        if opaques:
            lines.append("")
            lines.append("pre-compiled steps (regions of one):")
            for info in opaques:
                lines.append("  %-28s %s" % (info.unit.name, info.reason))
        lines.append("")
        lines.append("data links (dst.attr <- src.attr):")
        for dst, name, src, sattr in self.data_edges:
            lines.append("  %s.%s <- %s.%s"
                         % (dst.name, name, src.name, sattr))
        return "\n".join(lines)


def analyze(workflow):
    """Public entry: introspect ``workflow`` into a :class:`GraphPlan`."""
    return GraphPlan.analyze(workflow)
