"""GraphCompiler: batch interpreted unit firings into compiled XLA programs.

The interpreter (``Workflow.run``'s worklist + AND-gates) stays the single
source of truth for control flow — gates, skips, blocks, loops.  This
controller wraps every traceable unit's ``run()`` to merely RECORD the
firing; the deferred sequence is *flushed* — composed face-by-face into ONE
jitted, buffer-donating program — exactly when a host-side unit needs the
results:

- before a non-member that overwrites member inputs runs (the loader
  starting the next minibatch);
- before a non-member that link-reads member outputs runs (plotters);
- when anyone touches a metric accumulator Array (Decision reading
  ``n_err`` at a class boundary — the Array is shadowed by a
  materialize-on-read proxy, so a Decision's early-return steps cost no
  sync at all);
- at workflow-run exit and before snapshot capture (full state sync).

Because the recorded sequence already reflects every gate decision the
interpreter made, gate semantics are free: a ``gate_skip``'d unit was never
recorded; a flipped gate simply keys a different compiled variant.  Any
failure to compose or execute permanently falls back to the units' original
``run()`` methods — interpreted dispatch, never an error.

Programs compile through the persistent executable cache
(:mod:`veles_tpu.compilecache`) when one is configured: warm restarts
deserialize every variant (zero XLA compiles) and each variant lands in the
warmup manifest like every other executable.
"""

import hashlib
import logging
import time

from ..logger import events
from ..memory import Array
from ..observability.registry import REGISTRY
from .partition import analyze

log = logging.getLogger("veles_tpu.graphcomp")

#: hard cap on units batched into one program (a runaway inner loop of
#: traceable units flushes in segments instead of unrolling unboundedly)
MAX_SEGMENT = 64


def _transient(fn):
    """Mark a wrapper transient so ``Pickleable.__getstate__`` (and the
    snapshotter's deepcopy capture) drops it — profiler/prefetcher idiom."""
    fn.transient_ = True
    return fn


class TracedStateArray(Array):
    """Stand-in for a metric Array whose live value rides a traced region's
    carry.  Any host access first *materializes*: flushes pending units and
    installs the current device value.  Unpickled copies (a snapshot taken
    while tracing was attached) have no callback and behave as plain
    Arrays."""

    def init_unpickled(self):
        super().init_unpickled()
        self._materialize_ = None

    def _pull(self):
        cb = getattr(self, "_materialize_", None)
        if cb is not None:
            cb()

    def map_read(self):
        self._pull()
        return super().map_read()

    def map_write(self):
        self._pull()
        mem = super().map_write()
        if mem is not None and not mem.flags.writeable:
            # the materialized pull is a read-only view of the device
            # buffer; a host WRITE (Decision resetting an accumulator)
            # needs its own mutable copy
            import numpy
            self._mem = mem = numpy.array(mem)
        return mem

    def map_invalidate(self):
        self._pull()
        return super().map_invalidate()

    def __getstate__(self):
        self._pull()
        return super().__getstate__()


class _Variant:
    """One compiled program for one recorded firing sequence."""

    __slots__ = ("key", "name", "call", "aot", "donated", "kept",
                 "ext_specs", "writebacks", "n_units", "counted")

    def __init__(self, key, name, call, aot, donated, kept, ext_specs,
                 writebacks, n_units):
        self.key = key
        self.name = name
        self.call = call            # fn(donated_list, kept_list, ext_list)
        self.aot = aot              # AotStep | None
        self.donated = donated      # StateLeaf list
        self.kept = kept            # StateLeaf list
        self.ext_specs = ext_specs  # ((owner, attr, static_flag), ...)
        self.writebacks = writebacks  # ((unit, attr), ...)
        self.n_units = n_units
        self.counted = False        # fresh-compile counted once


def _harden(v):
    """Python scalars → fixed-width device scalars (AotStep convention)."""
    import numpy
    if isinstance(v, (bool, numpy.bool_)):
        return numpy.bool_(v)
    if isinstance(v, (int, numpy.integer)):
        return numpy.int32(v)
    if isinstance(v, (float, numpy.floating)):
        return numpy.float32(v)
    return v


class GraphCompiler:
    """Attach-time controller for one workflow (see module docstring)."""

    loss = None  # StepProfiler fence-probe parity with fused steps

    def __init__(self, workflow, cache="auto", registry=None,
                 max_segment=MAX_SEGMENT):
        self.workflow = workflow
        self.plan = analyze(workflow)
        self.max_segment = int(max_segment)
        self._pending = []        # units recorded this window
        self._window_ext = []     # their external input values, captured
        #                           AT RECORD TIME (a host unit may
        #                           mutate a member attr before flush)
        self._window_ext_index = {}   # (id(owner), attr) -> position
        self._window_produced = set()  # (id(unit), attr) seen so far
        self._window_statics = []
        self._state = {}          # leaf.key -> device pytree
        self._leaves = {}         # leaf.key -> StateLeaf (first claim)
        self._variants = {}
        self._key_skeletons = {}  # ids tuple -> (names, configs)
        self._unit_spec = {}      # id(unit) -> resolved face spec
        self._orig_runs = {}      # id(unit) -> original bound run
        self._proxies = {}        # (id(unit), attr) -> (unit, original)
        self._wrapped = []        # (obj, wrapper) for detach
        self._disabled = False
        self._syncing = False
        self.flushes = 0
        self.compiles = 0         # fresh XLA compiles (cache misses)
        self.cache_hits = 0
        if cache == "auto":
            from ..compilecache import default_cache
            cache = default_cache()
        self.cache = cache
        reg = registry or REGISTRY
        lbl = {"workflow": workflow.name}
        reg.gauge("veles_graph_regions",
                  "Traced regions in the compiled workflow graph",
                  ("workflow",)).labels(**lbl).set(len(self.plan.regions))
        reg.gauge("veles_graph_fallback_units",
                  "Units falling back to interpreted dispatch",
                  ("workflow",)).labels(**lbl).set(
            len(self.plan.fallback_units))
        self._c_flushes = reg.counter(
            "veles_graph_flushes_total",
            "Traced-region programs dispatched", ("workflow",)).labels(**lbl)
        if self.plan.traced_unit_count:
            self._install()

    # -- attach / detach -----------------------------------------------------
    @classmethod
    def attach(cls, workflow, **kwargs):
        """Build + install a controller, or return None when tracing is
        unsupported here (no jax, numpy backend) — never an error."""
        try:
            import jax  # noqa: F401
        except Exception:  # noqa: BLE001
            return None
        from ..backends import NumpyDevice
        from ..config import root
        device = getattr(workflow, "device", None)
        if device is None or isinstance(device, NumpyDevice) or \
                not getattr(device, "exists", False) or \
                bool(root.common.engine.get("force_numpy", False)):
            return None
        prior = getattr(workflow, "graph_controller_", None)
        if prior is not None:
            prior.detach()
        return cls(workflow, **kwargs)

    def _install(self):
        for info in self.plan.infos:
            unit = info.unit
            if info.traceable:
                self._orig_runs[id(unit)] = unit.run
                unit.run = _transient(self._member_wrapper(unit))
                self._wrapped.append((unit, unit.run))
                for leaf in info.face.state:
                    if leaf.key not in self._leaves:
                        self._leaves[leaf.key] = leaf
                        if leaf.array is not None:
                            self._install_proxy(leaf)
            elif not info.opaque:
                uid = id(unit)
                sync = uid in self.plan.sync_triggers
                if sync or uid in self.plan.source_triggers or \
                        uid in self.plan.reader_triggers:
                    orig = unit.run
                    self._orig_runs[uid] = orig
                    unit.run = _transient(
                        self._trigger_wrapper(orig, sync))
                    self._wrapped.append((unit, unit.run))
        wf = self.workflow
        orig_wf_run = wf.run
        controller = self

        @_transient
        def wf_run(*args, **kwargs):
            try:
                return orig_wf_run(*args, **kwargs)
            finally:
                controller.finish()
        self._orig_wf_run = orig_wf_run
        wf.run = wf_run
        self._wrapped.append((wf, wf_run))

    def _install_proxy(self, leaf):
        unit, attr = leaf.array
        orig = getattr(unit, attr)
        if not isinstance(orig, Array) or isinstance(orig,
                                                     TracedStateArray):
            return
        proxy = TracedStateArray()
        proxy._mem = orig.map_read()
        proxy._host_dirty_ = True
        key = leaf.key

        def materialize():
            self._materialize(key)
        proxy._materialize_ = materialize
        setattr(unit, attr, proxy)
        self._proxies[(id(unit), attr)] = (unit, attr, orig)

    def detach(self):
        """Flush, sync state back, restore every wrapper and proxy."""
        self.finish()
        for obj, wrapper in reversed(self._wrapped):
            if obj.__dict__.get("run") is wrapper:
                del obj.__dict__["run"]
                orig = self._orig_runs.get(id(obj),
                                           getattr(self, "_orig_wf_run",
                                                   None)
                                           if obj is self.workflow else
                                           None)
                if orig is not None and \
                        getattr(orig, "__func__", None) is not \
                        type(obj).run:
                    obj.__dict__["run"] = orig
        self._wrapped = []
        import numpy
        for (uid, attr), (unit, aname, orig) in self._proxies.items():
            proxy = getattr(unit, aname, None)
            if isinstance(proxy, TracedStateArray):
                proxy._materialize_ = None
                # numpy.array: a WRITABLE host copy (a materialized pull
                # is a read-only device view)
                orig.mem = numpy.array(proxy.map_read())
                setattr(unit, aname, orig)
        self._proxies = {}
        if getattr(self.workflow, "graph_controller_", None) is self:
            self.workflow.graph_controller_ = None

    # -- wrappers ------------------------------------------------------------
    def _spec(self, unit):
        """Memoized face wiring: resolved inputs/statics (links are
        static after attach)."""
        spec = self._unit_spec.get(id(unit))
        if spec is None:
            face = self.plan.by_id[id(unit)].face
            spec = (unit.name, face,
                    tuple((n,) + unit.resolve_linked(n)
                          for n in face.inputs),
                    tuple(unit.resolve_linked(s) for s in face.statics),
                    face.config())
            self._unit_spec[id(unit)] = spec
        return spec

    def _member_wrapper(self, unit):
        orig = self._orig_runs[id(unit)]
        # resolve the face wiring ONCE (links are static after attach):
        # the record path below runs for every member every step
        _name, face, inputs, statics, _cfg = self._spec(unit)
        input_keys = tuple(((id(owner), attr), owner, attr)
                           for _i, owner, attr in inputs)
        output_keys = tuple((id(unit), o) for o in face.outputs)
        fetch = self._fetch
        static_value = self._static_value

        def record():
            if self._disabled:
                return orig()
            # capture external inputs NOW — the values this unit would
            # have consumed had it run here (a host unit may overwrite
            # a member attr before the window flushes)
            produced = self._window_produced
            ext_index = self._window_ext_index
            ext = self._window_ext
            for k, owner, attr in input_keys:
                if k not in produced and k not in ext_index:
                    ext_index[k] = len(ext)
                    ext.append(fetch(owner, attr))
            for owner, attr in statics:
                self._window_statics.append(static_value(owner, attr))
            produced.update(output_keys)
            self._pending.append(unit)
            if len(self._pending) >= self.max_segment:
                self.run()
        return record

    def _trigger_wrapper(self, orig, sync):
        def trigger():
            if self._pending:
                self.run()
            if sync:
                self.sync_state()
            return orig()
        return trigger

    # -- the flush -----------------------------------------------------------
    def run(self):
        """Flush the recorded firing sequence through ONE compiled program
        (the traced-region 'step'; StepProfiler wraps this)."""
        pending = self._pending
        if not pending:
            return
        ext = self._window_ext
        statics = tuple(self._window_statics)
        self._pending = []
        self._window_ext = []
        self._window_ext_index = {}
        self._window_produced = set()
        self._window_statics = []
        t0 = time.perf_counter()
        try:
            ids = tuple(map(id, pending))
            skeleton = self._key_skeletons.get(ids)
            if skeleton is None:
                names, configs = [], []
                for u in pending:
                    spec = self._spec(u)
                    names.append(spec[0])
                    if spec[4] is not None:
                        configs.append((spec[0], spec[4]))
                skeleton = (tuple(names), tuple(configs))
                self._key_skeletons[ids] = skeleton
            key = (skeleton[0], statics, skeleton[1])
            variant = self._variants.get(key)
            if variant is None:
                variant = self._build_variant(pending, statics, key)
                self._variants[key] = variant
            self._execute(variant, ext)
        except Exception as exc:  # noqa: BLE001 — semantics of
            # Unit.run_dependent, never an error: permanent fallback
            self._fallback(pending, exc)
            return
        dt = time.perf_counter() - t0
        self.flushes += 1
        self._c_flushes.inc()
        if events.enabled:
            events.span("graph.flush", dt, workflow=self.workflow.name,
                        units=variant.n_units, variant=variant.name)

    def _fallback(self, pending, exc):
        log.warning(
            "graph tracing for %r disabled (%s: %s); falling back to "
            "interpreted dispatch", self.workflow.name,
            type(exc).__name__, str(exc)[:300])
        self._disabled = True
        try:
            self.sync_state()
        except Exception:  # noqa: BLE001 — best effort before interpret
            log.exception("graphcomp: state sync during fallback failed")
        for unit in pending:
            self._orig_runs[id(unit)]()

    @staticmethod
    def _static_value(owner, attr):
        v = getattr(owner, attr, None)
        if isinstance(v, Array):
            raise TypeError("static input %s.%s is an Array"
                            % (owner.name, attr))
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        return int(v)  # numpy integer scalars and friends

    def _build_variant(self, pending, flat_statics, key):
        """Compose the faces of one recorded firing sequence into one
        jitted program.  The internal/external wiring decisions replay
        the EXACT algorithm the recorder ran, so the recorder's captured
        ext list indexes this program's ext argument."""
        import jax
        donated, kept, seen = [], [], set()
        produced = {}
        ext_specs, ext_index = [], {}
        steps = []
        cursor = 0
        for unit in pending:
            _name, face, inputs, static_specs, _cfg = self._spec(unit)
            in_map = {}
            for name, owner, attr in inputs:
                k = (id(owner), attr)
                if k in produced:
                    in_map[name] = ("env", k)
                else:
                    if k not in ext_index:
                        ext_index[k] = len(ext_specs)
                        ext_specs.append((owner, attr))
                    in_map[name] = ("ext", ext_index[k])
            statics = dict(zip(face.statics,
                               flat_statics[cursor:cursor +
                                            len(static_specs)]))
            cursor += len(static_specs)
            st_map = {}
            for leaf in face.state:
                claimed = self._leaves.setdefault(leaf.key, leaf)
                if leaf.key not in seen:
                    seen.add(leaf.key)
                    (donated if claimed.donate else kept).append(claimed)
                st_map[leaf.name] = leaf.key
            for o in face.outputs:
                produced[(id(unit), o)] = True
            steps.append((face, in_map, st_map, statics))
        # EVERY fired unit's outputs write back (lazily, as devmem):
        # after a flush, member attrs read exactly as interpreted
        # dispatch would have left them — for link-readers, for
        # cross-segment wiring, and for anyone inspecting Arrays
        # after the run
        writebacks = tuple(
            (self.plan.by_id[uid].unit, attr)
            for (uid, attr) in sorted(
                produced,
                key=lambda k: (self.plan.by_id[k[0]].unit.name, k[1])))
        wb_ids = [(id(u), a) for u, a in writebacks]
        donated_keys = [lf.key for lf in donated]
        kept_keys = [lf.key for lf in kept]

        def program(donated_vals, kept_vals, ext_vals):
            state = dict(zip(donated_keys, donated_vals))
            state.update(zip(kept_keys, kept_vals))
            env = {}
            for face, in_map, st_map, statics in steps:
                ins = {}
                for name, (tag, ref) in in_map.items():
                    ins[name] = env[ref] if tag == "env" else ext_vals[ref]
                st_in = {ln: state[k] for ln, k in st_map.items()}
                updates, outs = face.fn(st_in, ins, statics)
                for ln, v in updates.items():
                    state[st_map[ln]] = v
                for o, v in outs.items():
                    env[(id(face.unit), o)] = v
            return ([state[k] for k in donated_keys],
                    [state[k] for k in kept_keys],
                    [env[k] for k in wb_ids])

        jitted = jax.jit(program, donate_argnums=(0,))
        digest = hashlib.sha256(repr(key).encode()).hexdigest()[:12]
        name = "graph.%s.%s" % (self.workflow.name, digest)
        aot = None
        call = jitted
        if self.cache is not None:
            from ..compilecache import AotStep
            aot = AotStep(jitted, self.cache, name)
            call = aot
            # manifest buckets are integers: the variant digest, so a
            # traced workflow's history reads like any other model's
            self.cache.manifest.record("graph:%s" % self.workflow.name,
                                       int(digest, 16))
        return _Variant(key, name, call, aot, donated, kept,
                        tuple(ext_specs), writebacks, len(pending))

    def _leaf_value(self, leaf):
        v = self._state.get(leaf.key)
        if v is None or leaf.dirty():
            v = leaf.init()
            self._state[leaf.key] = v
        return v

    def _fetch(self, owner, attr):
        v = getattr(owner, attr)
        if isinstance(v, Array):
            return v.devmem
        return _harden(v)

    def _execute(self, variant, ext):
        donated = [self._leaf_value(lf) for lf in variant.donated]
        kept = [self._leaf_value(lf) for lf in variant.kept]
        d_out, k_out, wb = variant.call(donated, kept, ext)
        for lf, v in zip(variant.donated, d_out):
            self._state[lf.key] = v
        for lf, v in zip(variant.kept, k_out):
            self._state[lf.key] = v
        for (unit, attr), v in zip(variant.writebacks, wb):
            target = getattr(unit, attr, None)
            if isinstance(target, Array):
                target.swap_devmem(v)
            else:
                setattr(unit, attr, v)
        if not variant.counted:
            variant.counted = True
            if variant.aot is not None and variant.aot.cache_hit:
                self.cache_hits += 1
            else:
                self.compiles += 1

    # -- materialization / sync ----------------------------------------------
    def _materialize(self, key):
        if self._syncing:
            return
        if self._pending:
            self.run()
        value = self._state.get(key)
        leaf = self._leaves.get(key)
        if value is None or leaf is None or leaf.array is None:
            return
        unit, attr = leaf.array
        arr = getattr(unit, attr)
        if not arr._host_dirty_:  # host writes stay authoritative
            arr.devmem = value

    def sync_state(self):
        """Flush pending work and write every carry back into its owning
        unit (params/solver copies, metric devmems) — run-exit, snapshot
        capture, and detach all come through here."""
        if self._syncing:
            return
        self._syncing = True
        try:
            if self._pending:
                self._syncing = False
                self.run()
                self._syncing = True
            for key, leaf in self._leaves.items():
                value = self._state.get(key)
                if value is None:
                    continue
                if leaf.sync is not None:
                    leaf.sync(value)
                elif leaf.array is not None:
                    unit, attr = leaf.array
                    arr = getattr(unit, attr)
                    if not arr._host_dirty_:
                        arr.devmem = value
        finally:
            self._syncing = False

    def finish(self):
        if self._pending:
            self.run()
        self.sync_state()

    # -- observability surfaces ----------------------------------------------
    @property
    def traced_unit_count(self):
        return self.plan.traced_unit_count

    @property
    def _params_(self):
        """StepProfiler fence probe: everything the last flush produced."""
        return list(self._state.values())

    def profiled_jits(self):
        """StepProfiler recompile accounting hook."""
        return [self]

    def _cache_size(self):
        """Fresh XLA compiles observed so far (StepProfiler recompile
        accounting): an AOT-cached variant that deserialized counts 0;
        a freshly-compiled one counts once; plain-jit variants report
        their own jit cache size (1 per compile, 0 extra later)."""
        total = 0
        for variant in self._variants.values():
            if variant.aot is not None and variant.counted and \
                    not variant.aot.cache_hit:
                total += 1
            fn = getattr(variant.call, "_cache_size", None)
            try:
                total += int(fn()) if callable(fn) else 0
            except Exception:  # noqa: BLE001 — diagnostics never raise
                pass
        return total

    def stats(self):
        return {"regions": len(self.plan.regions),
                "traced_units": self.plan.traced_unit_count,
                "fallback_units": len(self.plan.fallback_units),
                "variants": len(self._variants),
                "flushes": self.flushes,
                "compiles": self.compiles,
                "cache_hits": self.cache_hits,
                "disabled": self._disabled}
