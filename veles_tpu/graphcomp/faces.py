"""Trace faces: the pure per-step contract a unit exposes to the tracer.

A *face* (:class:`TraceFace`) is the jit-able view of one unit's ``run()``:
which linked attributes it reads (``inputs``), which host scalars select a
compiled variant (``statics``), which attributes it produces (``outputs``),
which persistent values thread through the compiled program as a donated
carry (``state``), and the pure function tying them together.  The region
compiler (:mod:`.runtime`) composes the faces of consecutively-fired units
into ONE jitted program — so a face's ``fn`` must execute the numerically
IDENTICAL operations the unit's own jitted path runs, which is what makes
traced execution bitwise-equal to interpreted dispatch (asserted by
tests/test_graphcomp.py).

Units opt in by implementing ``make_trace()`` (see
:meth:`veles_tpu.units.Unit.make_trace`); returning :class:`NoFace` with a
reason keeps the unit host-side and documents *why* in ``tools/dump_graph``.
Already-compiled step units (FusedTrainStep and kin) return
:class:`OpaqueFace`: they ARE a traced region of one, executed natively.
"""


class NoFace:
    """Marker: this unit stays host-side; ``reason`` is the debugging face
    surfaced by ``tools/dump_graph.py`` and the fallback gauges."""

    __slots__ = ("reason",)

    def __init__(self, reason):
        self.reason = reason

    def __repr__(self):
        return "<NoFace %s>" % self.reason


class StateLeaf:
    """One persistent carry value threaded through compiled programs.

    - ``name``:  face-local binding (``fn`` sees ``state[name]``);
    - ``key``:   process-global identity — faces of different units naming
      the same key SHARE the value (a GD unit updates the params its
      forward reads);
    - ``init()``: build the initial device pytree (decoupled copies when
      ``donate`` — donated buffers must never alias live unit Arrays);
    - ``dirty()``: True when the host rewrote the backing attribute since
      the tracer last synced, forcing a re-seed via ``init`` (a Decision
      resetting ``n_err`` to 0, a restored snapshot's solver state);
    - ``sync(value)``: boundary write-back into the owning unit (params →
      forward Arrays, solver state → GD dicts); None for leaves whose
      visibility is handled by a lazy Array proxy;
    - ``donate``: thread through ``donate_argnums`` (params/solver);
      metric accumulators stay undonated so materialized views stay valid;
    - ``array``: optional ``(unit, attr)`` of a :class:`memory.Array` the
      leaf shadows — the runtime swaps it for a materialize-on-read proxy.
    """

    __slots__ = ("name", "key", "init", "dirty", "sync", "donate", "array")

    def __init__(self, name, key, init, dirty=None, sync=None, donate=True,
                 array=None):
        self.name = name
        self.key = key
        self.init = init
        self.dirty = dirty or (lambda: False)
        self.sync = sync
        self.donate = donate
        self.array = array


class TraceFace:
    """The pure face of one unit (see module docstring).

    ``fn(state, inputs, statics) -> (state_updates, outputs)`` where every
    argument/return is a dict keyed by the declared names.  ``config()``
    returns a hashable fingerprint of closed-over hyperparameters — a
    changed config keys a fresh compiled variant instead of silently
    running stale math.
    """

    opaque = False

    def __init__(self, unit, fn, inputs=(), statics=(), outputs=(),
                 state=(), sync_attrs=(), config=None):
        self.unit = unit
        self.fn = fn
        self.inputs = tuple(inputs)
        self.statics = tuple(statics)
        self.outputs = tuple(outputs)
        self.state = tuple(state)
        #: unit attrs mirrored only at boundary sync (weights/bias): a
        #: non-member reading them forces a flush+sync first
        self.sync_attrs = tuple(sync_attrs)
        self._config = config

    def config(self):
        return self._config

    def __repr__(self):
        return "<TraceFace %s>" % self.unit.name


class OpaqueFace(TraceFace):
    """A unit that is ALREADY one compiled program (FusedTrainStep, the
    scan/mesh steps).  It executes natively and is reported as its own
    traced region — the hand-fused step becomes one producer of traced
    regions instead of a special case."""

    opaque = True

    def __init__(self, unit, label):
        super().__init__(unit, fn=None)
        self.label = label


# -- shared leaf builders ------------------------------------------------------

def forward_params_leaf(fwd):
    """Donated params carry for a ForwardBase unit, shared (by key) with
    the GD unit that updates it.  Copies on seed and on sync: the live
    carry is donated every step and must never alias the unit's Arrays."""

    def init():
        import jax.numpy as jnp
        return {k: jnp.array(v) for k, v in fwd.params.items()}

    def dirty():
        arrays = [fwd.weights]
        if fwd.include_bias and fwd.bias:
            arrays.append(fwd.bias)
        return any(a._host_dirty_ for a in arrays if a)

    def sync(value):
        import jax.numpy as jnp
        fwd.set_params({k: jnp.array(v) for k, v in value.items()})

    return StateLeaf("params", (id(fwd), "params"), init, dirty=dirty,
                     sync=sync, donate=True)


def gd_params_leaf(gd):
    """Params carry for a GD unit with no linked forward (hand-built test
    graphs): backed directly by the GD unit's weights/bias Arrays."""

    def init():
        import jax.numpy as jnp
        return {k: jnp.array(v)
                for k, v in gd._gather_params(host=False).items()}

    def dirty():
        arrays = [a for a in (gd.weights, gd.bias) if a]
        return any(a._host_dirty_ for a in arrays)

    def sync(value):
        import jax.numpy as jnp
        gd._store_params({k: jnp.array(v) for k, v in value.items()},
                         host=False)

    return StateLeaf("params", (id(gd), "params"), init, dirty=dirty,
                     sync=sync, donate=True)


def solver_state_leaf(gd, params_of):
    """Solver-state carry for a GD unit.  Seeds from ``gd.solver_state``
    when present (snapshot restore) else ``solver.init``; boundary sync
    writes copies back into ``gd.solver_state`` — the same dict the
    interpreted path and the snapshotter use — and records their ids so
    an EXTERNAL rewrite (restore, rollback) is detected and re-seeded."""
    synced = {}

    def init():
        import jax.numpy as jnp
        state = {}
        for name, p in params_of().items():
            have = gd.solver_state.get(name)
            if have:
                state[name] = tuple(jnp.asarray(s) for s in have)
            else:
                state[name] = gd.solver.init(p, jnp)
        return state

    def dirty():
        if not synced:
            return False  # first use goes through init anyway
        current = {n: id(v) for n, v in gd.solver_state.items()}
        return current != synced

    def sync(value):
        import jax.numpy as jnp
        synced.clear()
        for name, st in value.items():
            gd.solver_state[name] = tuple(jnp.array(s) for s in st)
            synced[name] = id(gd.solver_state[name])

    return StateLeaf("solver", (id(gd), "solver"), init, dirty=dirty,
                     sync=sync, donate=True)


def array_state_leaf(unit, attr):
    """Metric-accumulator carry bound to a :class:`memory.Array` attr
    (``n_err``, ``confusion_matrix``, ``metrics``): undonated, shadowed by
    a materialize-on-read proxy installed by the runtime, re-seeded from
    host whenever the host writes (a Decision's per-class reset)."""

    def init():
        return getattr(unit, attr).devmem  # uploads, clears host-dirty

    def dirty():
        return getattr(unit, attr)._host_dirty_

    return StateLeaf(attr, (id(unit), attr), init, dirty=dirty,
                     donate=False, array=(unit, attr))
