"""Whole-workflow compilation: trace any ``link_from`` unit DAG into
compiled XLA programs (ROADMAP item 3; the "full compilation of programs
to TPUs" idiom of arXiv 1810.09868 applied to VELES dataflow graphs).

Public surface:

- :func:`analyze` — introspect an initialized workflow into a
  :class:`~.partition.GraphPlan` (regions, fallback reasons, data edges);
- :class:`GraphCompiler` — the runtime controller
  (``Workflow.attach_graph_compiler()`` / ``root.common.engine
  .graph_compile`` wire it up);
- the face protocol (:mod:`.faces`) units implement via ``make_trace()``.
"""

from .faces import NoFace, OpaqueFace, StateLeaf, TraceFace   # noqa: F401
from .partition import GraphPlan, analyze                     # noqa: F401
from .runtime import GraphCompiler, TracedStateArray          # noqa: F401
