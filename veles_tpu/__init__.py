"""veles_tpu — a TPU-native deep-learning platform.

A brand-new framework with the capability surface of Samsung VELES
(dataflow unit/workflow engine, config-driven standard NN workflows,
full-batch and streaming loaders, reproducible RNG, snapshot/resume, elastic
distributed training, GA hyperparameter optimization, ensembles,
observability, REST serving, compiled export) built idiomatically on
JAX/XLA/Pallas: units trace into jitted, donated, mesh-sharded step
functions; datasets live as HBM-resident sharded arrays; gradients
all-reduce over ICI via in-program collectives.
"""

__version__ = "0.1.0"

from .config import root, Config, Range                     # noqa: F401
from .mutable import Bool                                   # noqa: F401
from .units import Unit, TrivialUnit, IDistributable        # noqa: F401
from .workflow import Workflow, NoMoreJobs                  # noqa: F401
from .plumbing import StartPoint, EndPoint, Repeater, FireStarter  # noqa: F401
from .result_provider import IResultProvider                # noqa: F401
