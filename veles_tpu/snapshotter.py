"""Snapshotter: periodic whole-workflow checkpoints with resume.

TPU-native re-design of /root/reference/veles/snapshotter.py
(SnapshotterToFile :360-426 — pickle of the full Workflow with compression
none/gz/bz2/xz, ``<name>_current`` symlink; throttling :159-175;
master-only skip :160; size diagnostics :203-226; restore path
Snapshotter.import_file :522-535).  Device arrays are pulled to host by
``Array.__getstate__`` before pickling (memory.py analog); the fused
step's params/opt-state are synced into the forward units' Arrays first,
so a snapshot of a fused workflow restores into either execution mode.

Zero-stall asynchronous snapshotting (ISSUE 4): the reference shape —
pickle + gzip + write inline in the unit graph — stalls the step loop
for the whole durable write.  Here every shot is split into

- a **capture phase** on the training thread: sync the fused step's
  weights/solver state to host (the only part that must see a quiescent
  step) and deep-copy the workflow's picklable state (the
  ``Pickleable.__getstate__`` machinery — the same one pickle uses — so
  ``transient_`` wrappers and ``_``-suffixed state are dropped
  identically), then return to training immediately; and
- a **durable-write phase** on a single writer thread
  (:class:`SnapshotWriter`): pickle + compression + ``*.tmp`` write +
  fsync + atomic ``os.rename`` + ``_current`` symlink flip (or the
  serialized SQLite insert for :class:`SnapshotterToDB`).

The writer queue coalesces periodic shots (drop-oldest — at most one
periodic shot is ever pending) but never drops improvement shots; writer
exceptions re-raise on the next :meth:`SnapshotterBase.run`; workflow
finish flushes and joins the writer (no leaked threads, mirroring the
prefetcher's lifecycle contract).  ``root.common.snapshot.async_write =
False`` (or ``async_write=False`` per unit) restores the exact
synchronous path — which is now atomic too: a kill mid-write can never
leave ``_current`` pointing at a truncated file.  On multi-host runs
only ``jax.process_index() == 0`` performs the write phase; the other
processes keep identical throttle bookkeeping but never touch the
(shared) filesystem.

Suffix convention kept: the best metric value lands in the filename, e.g.
``mnist_validation_1.48.4.pickle.gz``.
"""

import bz2
import collections
import copy
import gzip
import lzma
import os
import pickle
import threading
import time
import weakref

from .config import root
from .logger import Logger, events
from .mutable import Bool
from .observability.registry import REGISTRY
from .registry import MappedObjectsRegistry, UnitRegistry
from .result_provider import IResultProvider
from .units import Unit

#: compression → (fileobj, level) codec factory + filename extension.
#: The level comes from ``root.common.snapshot.compression_level``
#: (default 6: level 9 buys ~nothing on float weights and costs
#: multiples in CPU time — measured by the ``snapshot`` bench stage).
CODECS = {
    None: (lambda f, lvl: f, ""),
    "": (lambda f, lvl: f, ""),
    "gz": (lambda f, lvl: gzip.GzipFile(fileobj=f, mode="wb",
                                        compresslevel=lvl), ".gz"),
    "bz2": (lambda f, lvl: bz2.BZ2File(f, "wb",
                                       compresslevel=max(lvl, 1)), ".bz2"),
    "xz": (lambda f, lvl: lzma.LZMAFile(f, "wb", preset=lvl), ".xz"),
}

DECODERS = {
    ".gz": gzip.open,
    ".bz2": bz2.open,
    ".xz": lzma.open,
    ".pickle": open,
}

#: how long blocked writer waits sleep before re-checking stop/failure
_POLL_S = 0.05

_is_writer_process = None
_scalars_atomic = False


def _register_atomic_scalars():
    """Teach ``copy.deepcopy`` that numpy *number/bool scalars* are
    immutable — shared into the copy like Python's int/str instead of
    re-boxed one by one.  Loader label lists hold thousands of boxed
    ``numpy.int32``; without this they dominate the capture walk
    (measured: ~5 ms of an ~8 ms MNIST capture).  Registered via
    ``setdefault`` (user overrides win) and only for scalar types that
    really are immutable — ``numpy.void`` is item-assignable and stays
    out."""
    global _scalars_atomic
    if _scalars_atomic:
        return
    try:
        import numpy
        atomic = copy._deepcopy_atomic
        for t in set(numpy.sctypeDict.values()):
            if isinstance(t, type) and \
                    issubclass(t, (numpy.number, numpy.bool_)):
                copy._deepcopy_dispatch.setdefault(t, atomic)
    except Exception:  # noqa: BLE001 — an optimization, never a failure
        pass
    _scalars_atomic = True


def _writer_process():
    """True on the one process that materializes snapshots (multi-host:
    ``jax.process_index() == 0``; everywhere else: always True)."""
    global _is_writer_process
    if _is_writer_process is None:
        try:
            import jax
            _is_writer_process = jax.process_index() == 0
        except Exception:  # noqa: BLE001 — no jax backend ⇒ standalone
            _is_writer_process = True
    return _is_writer_process


class _WriteJob:
    __slots__ = ("fn", "improved", "label")

    def __init__(self, fn, improved, label):
        self.fn = fn
        self.improved = improved
        self.label = label


def _writer_main(ref, stop_evt):
    """Writer thread entry.  Holds only a WEAK reference between jobs
    (same rationale as the prefetcher's worker): an abandoned
    snapshotter must stay garbage-collectable."""
    while True:
        self = ref()
        if self is None:
            return
        if not self._work_once():
            del self
            if stop_evt.wait(_POLL_S):
                return


class SnapshotWriter:
    """Single background thread owning the durable-write phase.

    The queue is effectively depth-1: a newly submitted *periodic* shot
    replaces any still-pending periodic shot (drop-oldest coalescing —
    the newest weights are strictly more useful than stale ones), while
    *improvement* shots are never dropped (they are edge-triggered, at
    most one per validation epoch, so the queue stays tiny).  A job
    exception parks the writer and is re-delivered via
    :meth:`take_failure` (the snapshotter raises it on its next run);
    the un-failed remainder of the queue is retried when the writer
    restarts on the next submit.
    """

    def __init__(self, name="snapshot", registry=None):
        self.name = name
        self._jobs = collections.deque()
        self._lock = threading.Lock()
        self._busy = False
        self._thread = None
        self._stop_evt = threading.Event()
        self._failure = None
        self.written = 0
        self.coalesced = 0
        reg = registry or REGISTRY
        lbl = {"snapshotter": name}
        self._g_queue = reg.gauge(
            "veles_snapshot_writer_queue",
            "Snapshot write jobs queued behind the writer thread",
            ("snapshotter",)).labels(**lbl)
        self._c_coalesced = reg.counter(
            "veles_snapshot_coalesced_total",
            "Periodic snapshots dropped by drop-oldest queue coalescing",
            ("snapshotter",)).labels(**lbl)

    # -- producer side (training thread) -------------------------------------
    def submit(self, fn, improved=False, label=None):
        """Enqueue one durable-write job and return immediately."""
        with self._lock:
            if not improved:
                for i in range(len(self._jobs)):
                    if not self._jobs[i].improved:
                        del self._jobs[i]
                        self.coalesced += 1
                        self._c_coalesced.inc()
                        break
            self._jobs.append(_WriteJob(fn, improved, label))
            self._g_queue.set(len(self._jobs))
            self._ensure_thread()

    def _ensure_thread(self):
        # caller holds self._lock
        t = self._thread
        if t is not None and t.is_alive():
            return
        if self._failure is not None:
            return  # parked until take_failure() delivers the exception
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=_writer_main,
            args=(weakref.ref(self), self._stop_evt), daemon=True,
            name="veles-snapwriter-%s" % self.name)
        self._thread.start()

    # -- consumer side (writer thread) ---------------------------------------
    def _work_once(self):
        """Run one queued job; returns False when the queue was empty."""
        with self._lock:
            if not self._jobs:
                return False
            job = self._jobs.popleft()
            self._busy = True
            self._g_queue.set(len(self._jobs))
        try:
            job.fn()
        except BaseException as exc:  # noqa: BLE001 — re-raised at run()
            with self._lock:
                self._failure = exc
                self._busy = False
            return False
        with self._lock:
            self._busy = False
            self.written += 1
        return True

    # -- lifecycle -----------------------------------------------------------
    def flush(self, timeout=60.0):
        """Block until every queued job is durably done (True) or the
        writer failed / the timeout expired (False)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._failure is not None:
                    return False
                if not self._jobs and not self._busy:
                    return True
                self._ensure_thread()
            time.sleep(0.01)
        return False

    def stop(self, timeout=60.0):
        """Flush then join the thread (workflow finish / detach); the
        writer restarts lazily on the next submit."""
        ok = self.flush(timeout)
        self._stop_evt.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10)
        with self._lock:
            self._thread = None
        return ok

    def take_failure(self):
        """Pop the stored writer exception (or None); popping un-parks
        the writer so the queue remainder is retried on the next
        submit."""
        with self._lock:
            exc, self._failure = self._failure, None
            return exc

    def stats(self):
        with self._lock:
            return {"written": self.written,
                    "coalesced": self.coalesced,
                    "queued": len(self._jobs),
                    "busy": self._busy}


class SnapshotterRegistry(UnitRegistry, MappedObjectsRegistry):
    """Units that are also a string-keyed family ("file", "db", ...)."""


class SnapshotterBase(Unit, IResultProvider, Logger,
                      metaclass=SnapshotterRegistry):
    """Base: throttling + gate protocol (runs when Decision.improved)."""

    mapping = "snapshotter"
    hide_from_registry = True

    #: pickle backends gate the whole export to process 0; sharded
    #: checkpoints (checkpoint/snapshot.py) flip this so EVERY process
    #: exports — each writes only its own addressable shards
    WRITES_ON_ALL_PROCESSES = False

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.prefix = kwargs.get("prefix", "wf")
        self.interval = kwargs.get("interval", 1)     # epochs between shots
        self.time_interval = kwargs.get("time_interval", 15)  # seconds
        self.compression = kwargs.get("compression", "gz")
        # None = follow the root.common.snapshot.* config defaults
        self.async_write = kwargs.get("async_write")
        self.compression_level = kwargs.get("compression_level")
        self.report_size_threshold = kwargs.get("report_size_threshold")
        self.suffix = None
        self.destination = None
        self.skip = Bool(False)
        self.decision = None
        self._counter = 0
        self._last_exported_best = None
        self.stall_s = 0.0        # cumulative training-thread stall
        self.last_stall_s = 0.0

    def init_unpickled(self):
        super().init_unpickled()
        # monotonic-clock bookkeeping: a value pickled in another
        # process/boot is meaningless here — reset so the first shot
        # after a restore is never spuriously throttled
        self._last_time_ = None

    def link_decision(self, decision):
        """Wire a Decision so improved-model snapshots carry the best
        validation metric in the filename (reference snapshotter.py:178-202
        ``validation_1.48`` convention) and bypass the *time* throttle — an
        improvement is never dropped merely for landing <``time_interval``
        seconds after the last shot.  The ``interval`` counter gate is NOT
        bypassed: it is an explicit every-Nth thinning knob the user asked
        for, and applies to improvements like everything else."""
        self.decision = decision
        return self

    def _decision_best(self):
        d = self.decision
        return (getattr(d, "best_n_err_pt", None),
                getattr(d, "best_rmse", None),
                getattr(d, "best_epoch", None))

    def _decision_suffix(self):
        best_pt, best_rmse, _ = self._decision_best()
        if best_pt is not None:
            return "validation_%.2f" % best_pt
        if best_rmse is not None:
            return "validation_%.4f" % best_rmse
        return None

    def _fresh_improvement(self):
        """Edge-triggered improvement: Decision.improved stays True for a
        whole epoch after a validation win, so a level check would bypass
        the time throttle on every minibatch; instead compare the current
        best to the best at our last export."""
        d = self.decision
        if d is None or not bool(d.improved):
            return False
        return self._decision_best() != self._last_exported_best

    # -- config-or-kwarg knobs ----------------------------------------------
    def _async_enabled(self):
        v = self.async_write
        if v is None:
            v = root.common.snapshot.get("async_write", True)
        return bool(v)

    def _compression_level(self):
        lvl = self.compression_level
        if lvl is None:
            lvl = root.common.snapshot.get("compression_level", 6)
        return max(0, min(9, int(lvl)))

    # -- writer / metrics plumbing (transient — recreated lazily) ------------
    def _get_writer(self):
        w = getattr(self, "_writer_", None)
        if w is None:
            w = self._writer_ = SnapshotWriter(name=self.prefix)
        return w

    def _obs(self):
        m = getattr(self, "_obs_", None)
        if m is None:
            lbl = {"snapshotter": self.prefix}
            m = self._obs_ = {
                "stall": REGISTRY.counter(
                    "veles_snapshot_stall_seconds_total",
                    "Training-thread seconds stalled per snapshot "
                    "(capture + submit; the full write when synchronous)",
                    ("snapshotter",)).labels(**lbl),
                "bytes": REGISTRY.counter(
                    "veles_snapshot_bytes_written_total",
                    "Snapshot bytes durably written",
                    ("snapshotter",)).labels(**lbl),
                "written": REGISTRY.counter(
                    "veles_snapshots_written_total",
                    "Snapshots durably written",
                    ("snapshotter",)).labels(**lbl),
            }
        return m

    def _capture(self, target):
        """Capture phase: deep-copy the workflow's picklable state on
        the training thread.  ``copy.deepcopy`` routes through the same
        ``Pickleable.__getstate__`` machinery as pickle itself — the
        ``transient_`` instrumentation wrappers (prefetcher/profiler)
        and ``_``-suffixed state are dropped identically, and Arrays
        pull device values to host — so the writer thread serializes a
        frozen, race-free twin while training mutates the original.
        Returns None (→ synchronous fallback) when the copy fails."""
        _register_atomic_scalars()
        t0 = time.perf_counter()
        try:
            snapshot = copy.deepcopy(target)
        except Exception as exc:  # noqa: BLE001 — fall back, never lose a shot
            self.warning(
                "snapshot capture failed (%s: %s); falling back to a "
                "synchronous write", type(exc).__name__, exc)
            return None
        events.span("snapshot.capture", time.perf_counter() - t0,
                    snapshotter=self.prefix)
        return snapshot

    def run(self):
        w = getattr(self, "_writer_", None)
        if w is not None:
            exc = w.take_failure()
            if exc is not None:
                raise exc
        if bool(self.skip):
            return
        self._counter += 1
        if self._counter % max(self.interval, 1):
            return
        fresh = self._fresh_improvement()
        # monotonic, not time.time(): an NTP step / wall-clock jump must
        # never suppress (or force) a shot (same fix as the EventLog)
        last = self._last_time_
        if not fresh and last is not None and \
                time.monotonic() - last < self.time_interval:
            return
        self._last_time_ = time.monotonic()
        if fresh:
            # the suffix names the metric these weights actually achieved;
            # non-improved periodic shots keep the previous suffix only if
            # the weights haven't trained past it (they have) — so clear it
            self.suffix = self._decision_suffix()
            self._last_exported_best = self._decision_best()
        elif self.decision is not None:
            self.suffix = None
        if not (_writer_process() or self.WRITES_ON_ALL_PROCESSES):
            # multi-host: process 0 owns the (shared) filesystem; the
            # others keep identical throttle state but skip the write
            # phase entirely instead of racing on it (sharded backends
            # opt out — every process owns its own shards)
            return
        self._exporting_improvement_ = fresh
        t0 = time.perf_counter()
        try:
            self.export()
        finally:
            self._exporting_improvement_ = False
            stall = time.perf_counter() - t0
            self.last_stall_s = stall
            self.stall_s += stall
            self._obs()["stall"].inc(stall)

    def export(self):
        raise NotImplementedError

    # -- lifecycle -----------------------------------------------------------
    def flush(self, timeout=60.0):
        """Block until every queued snapshot is durably written."""
        w = getattr(self, "_writer_", None)
        return w.flush(timeout) if w is not None else True

    def stop(self):
        """Workflow finished: flush + join the writer so no thread (and
        no buffered shot) outlives ``Workflow.run()``."""
        w = getattr(self, "_writer_", None)
        if w is None:
            return
        if not w.stop():
            # finish-time failures can't surface on a next run() that
            # may never come — at least say so loudly
            self.error("snapshot writer did not drain cleanly at "
                       "workflow finish: %s", w.stats())

    def writer_stats(self):
        w = getattr(self, "_writer_", None)
        return w.stats() if w is not None else None

    def get_metric_values(self):
        """Surface the last snapshot path in the results JSON (reference
        optimization_workflow.py:249 reads result.get("Snapshot"); the
        ensemble test mode restores instances from it)."""
        return {"Snapshot": self.destination}


class SnapshotterToFile(SnapshotterBase):
    """Pickle the whole workflow to disk with a ``_current`` symlink."""

    MAPPING = "file"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.directory = kwargs.get(
            "directory", os.path.expanduser(
                root.common.dirs.get("snapshots", ".")))

    def export(self):
        os.makedirs(self.directory, exist_ok=True)
        target = self.workflow
        fused = getattr(target, "fused_step", None)
        if fused is not None:
            # the only part that must see a quiescent step: pull the
            # fused params/opt-state back into the units' host Arrays
            fused.sync_weights()
            fused.sync_solver_state()
        name = "%s%s.%d.pickle" % (
            self.prefix, ("_" + self.suffix) if self.suffix else "",
            self._counter)
        path = os.path.join(
            self.directory, name + CODECS[self.compression or None][1])
        payload = self._capture(target) if self._async_enabled() else None
        if payload is None:
            self._write_file(target, path)
        else:
            self._get_writer().submit(
                lambda: self._write_file(payload, path),
                improved=bool(getattr(self, "_exporting_improvement_",
                                      False)),
                label=name)
        self.destination = path
        return path

    def _write_file(self, obj, path):
        """Durable-write phase (writer thread; inline when synchronous):
        pickle+compress into ``<path>.tmp``, fsync, atomically rename,
        then flip the ``_current`` symlink — a kill at ANY point leaves
        either the old snapshot set intact or the new file complete,
        never a truncated file at its final name."""
        t0 = time.perf_counter()
        codec, _ = CODECS[self.compression or None]
        tmp = path + ".tmp"
        with open(tmp, "wb") as raw:
            stream = codec(raw, self._compression_level())
            try:
                pickle.dump(obj, stream,
                            protocol=pickle.HIGHEST_PROTOCOL)
            finally:
                if stream is not raw:
                    stream.close()
            raw.flush()
            os.fsync(raw.fileno())
        os.replace(tmp, path)
        self._fsync_dir()
        self._flip_current(path)
        size = os.path.getsize(path)
        obs = self._obs()
        obs["bytes"].inc(size)
        obs["written"].inc()
        events.span("snapshot.write", time.perf_counter() - t0,
                    snapshotter=self.prefix, path=path, bytes=size)
        # gate BEFORE the diagnostic: _report_size re-pickles every
        # unit, which doubles serialization work — only pay for it when
        # the snapshot actually crossed the report threshold
        threshold = self._size_threshold()
        if threshold > 0 and size >= threshold:
            self._report_size(path, size, obj)
        return path

    def _size_threshold(self):
        threshold = self.report_size_threshold
        if threshold is None:
            threshold = root.common.snapshot.get(
                "report_size_threshold", 64 << 20)
        return int(threshold)

    def _fsync_dir(self):
        try:
            fd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def _flip_current(self, path):
        """Atomically repoint ``<prefix>_current``: build the new
        symlink beside it and rename over — readers never observe a
        missing or dangling link."""
        link = os.path.join(self.directory, "%s_current" % self.prefix)
        tmp_link = link + ".tmp"
        try:
            if os.path.lexists(tmp_link):
                os.remove(tmp_link)
            os.symlink(os.path.basename(path), tmp_link)
            os.replace(tmp_link, link)
        except OSError:
            pass

    def _report_size(self, path, size, workflow, top=5):
        """Top-N fattest units diagnostic (reference snapshotter.py:
        203-226).  Runs on the writer thread in async mode — the
        per-unit re-pickle never stalls the step loop — and only when
        the caller's threshold gate passed (SnapshotterToShards skips
        this entirely: its manifest already measured every tensor)."""
        sizes = []
        for unit in workflow:
            try:
                sizes.append((len(pickle.dumps(unit, -1)), unit.name))
            except Exception:  # noqa: BLE001 — diagnostics never raise
                pass
        lines = ["  %-30s %.1f MiB" % (name, sz / 1048576)
                 for sz, name in sorted(sizes, reverse=True)[:top]]
        self.warning("snapshot %s is %.1f MiB; fattest units:\n%s",
                     path, size / 1048576, "\n".join(lines))

    @staticmethod
    def import_file(path):
        """Load a snapshot back into a Workflow object (reference
        snapshotter.py:522-535 + __main__.py:539).  Sharded checkpoint
        directories (checkpoint/) route to their own importer, so the
        launcher's ``--snapshot`` flag accepts either format."""
        path = os.path.realpath(os.path.expanduser(path))
        if os.path.isdir(path):
            from .checkpoint import import_dir
            return import_dir(path)
        ext = os.path.splitext(path)[1]
        opener = DECODERS.get(ext, open)
        with opener(path, "rb") as f:
            wf = pickle.load(f)
        wf._restored_from_snapshot = True
        return wf


class SnapshotterToDB(SnapshotterBase):
    """Snapshots into a SQLite database (reference SnapshotterToDB,
    snapshotter.py:428-520, used ODBC; SQLite is the zero-dependency
    equivalent — same pickle blobs, queryable history, single file).
    Async mode uses the same single writer thread as the file path, so
    database access is naturally serialized."""

    MAPPING = "db"

    SCHEMA = ("CREATE TABLE IF NOT EXISTS snapshots ("
              "id INTEGER PRIMARY KEY AUTOINCREMENT, "
              "prefix TEXT, suffix TEXT, counter INTEGER, "
              "timestamp REAL, blob BLOB)")

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.database = kwargs.get("database") or os.path.join(
            os.path.expanduser(root.common.dirs.get("snapshots", ".")),
            "snapshots.sqlite3")

    def export(self):
        os.makedirs(os.path.dirname(os.path.abspath(self.database)),
                    exist_ok=True)
        target = self.workflow
        fused = getattr(target, "fused_step", None)
        if fused is not None:
            fused.sync_weights()
            fused.sync_solver_state()
        # wall clock ON PURPOSE: a queryable history column, not
        # throttle bookkeeping
        row = (self.prefix, self.suffix, self._counter, time.time())
        payload = self._capture(target) if self._async_enabled() else None
        if payload is None:
            self._write_db(target, row)
        else:
            self._get_writer().submit(
                lambda: self._write_db(payload, row),
                improved=bool(getattr(self, "_exporting_improvement_",
                                      False)),
                label="%s.%d" % (self.prefix, self._counter))
        self.destination = "sqlite://%s#%s" % (self.database, self.prefix)
        return self.destination

    def _write_db(self, obj, row):
        import sqlite3
        t0 = time.perf_counter()
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        with sqlite3.connect(self.database) as conn:
            conn.execute(self.SCHEMA)
            conn.execute(
                "INSERT INTO snapshots (prefix, suffix, counter, "
                "timestamp, blob) VALUES (?, ?, ?, ?, ?)",
                row + (sqlite3.Binary(blob),))
        obs = self._obs()
        obs["bytes"].inc(len(blob))
        obs["written"].inc()
        events.span("snapshot.write", time.perf_counter() - t0,
                    snapshotter=self.prefix, database=self.database,
                    bytes=len(blob))

    @staticmethod
    def import_db(uri):
        """``sqlite://<path>[#prefix]`` → newest matching snapshot."""
        import sqlite3
        body = uri[len("sqlite://"):]
        path, _, prefix = body.partition("#")
        if not os.path.exists(path):
            # connect() would CREATE an empty junk db at the typo'd path
            raise ValueError("no such snapshot database: %s" % path)
        with sqlite3.connect(path) as conn:
            if prefix:
                row = conn.execute(
                    "SELECT blob FROM snapshots WHERE prefix = ? "
                    "ORDER BY id DESC LIMIT 1", (prefix,)).fetchone()
            else:
                row = conn.execute(
                    "SELECT blob FROM snapshots "
                    "ORDER BY id DESC LIMIT 1").fetchone()
        if row is None:
            raise ValueError("no snapshot in %s" % uri)
        wf = pickle.loads(row[0])
        wf._restored_from_snapshot = True
        return wf


def restore(path):
    """Convenience resume entry: returns the restored (uninitialized)
    workflow; call .initialize(device=...) then .run().

    Sources (reference __main__.py:539-589 file/odbc/http): a snapshot
    file path, a sharded checkpoint directory (or its snapshot root /
    ``_current`` link / ``manifest.json``), ``sqlite://db.sqlite3
    [#prefix]``, or an ``http(s)://`` URL (fetched to a temp file
    first)."""
    if path.startswith("sqlite://"):
        return SnapshotterToDB.import_db(path)
    real = os.path.realpath(os.path.expanduser(path))
    if os.path.isdir(real) or os.path.basename(real) == "manifest.json":
        from .checkpoint import import_dir
        return import_dir(path)
    if path.startswith(("http://", "https://")):
        import tempfile
        import urllib.request
        suffix = os.path.splitext(path)[1] or ".pickle"
        fd, tmp = tempfile.mkstemp(suffix=suffix)
        os.close(fd)
        try:
            urllib.request.urlretrieve(path, tmp)
            return SnapshotterToFile.import_file(tmp)
        finally:
            os.unlink(tmp)
    return SnapshotterToFile.import_file(path)
