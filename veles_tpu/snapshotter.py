"""Snapshotter: periodic whole-workflow checkpoints with resume.

TPU-native re-design of /root/reference/veles/snapshotter.py
(SnapshotterToFile :360-426 — pickle of the full Workflow with compression
none/gz/bz2/xz, ``<name>_current`` symlink; throttling :159-175;
master-only skip :160; size diagnostics :203-226; restore path
Snapshotter.import_file :522-535).  Device arrays are pulled to host by
``Array.__getstate__`` before pickling (memory.py analog); the fused
step's params/opt-state are synced into the forward units' Arrays first,
so a snapshot of a fused workflow restores into either execution mode.

Suffix convention kept: the best metric value lands in the filename, e.g.
``mnist_validation_1.48.4.pickle.gz``.
"""

import bz2
import gzip
import lzma
import os
import pickle
import sys
import time

from .config import root
from .mutable import Bool
from .registry import MappedObjectsRegistry, UnitRegistry
from .result_provider import IResultProvider
from .units import Unit

CODECS = {
    None: (lambda f: f, ""),
    "": (lambda f: f, ""),
    "gz": (lambda f: gzip.GzipFile(fileobj=f, mode="wb"), ".gz"),
    "bz2": (lambda f: bz2.BZ2File(f, "wb"), ".bz2"),
    "xz": (lambda f: lzma.LZMAFile(f, "wb"), ".xz"),
}

DECODERS = {
    ".gz": gzip.open,
    ".bz2": bz2.open,
    ".xz": lzma.open,
    ".pickle": open,
}


class SnapshotterRegistry(UnitRegistry, MappedObjectsRegistry):
    """Units that are also a string-keyed family ("file", "db", ...)."""


class SnapshotterBase(Unit, IResultProvider, metaclass=SnapshotterRegistry):
    """Base: throttling + gate protocol (runs when Decision.improved)."""

    mapping = "snapshotter"
    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.prefix = kwargs.get("prefix", "wf")
        self.interval = kwargs.get("interval", 1)     # epochs between shots
        self.time_interval = kwargs.get("time_interval", 15)  # seconds
        self.compression = kwargs.get("compression", "gz")
        self.suffix = None
        self.destination = None
        self.skip = Bool(False)
        self.decision = None
        self._last_time = 0.0
        self._counter = 0
        self._last_exported_best = None

    def link_decision(self, decision):
        """Wire a Decision so improved-model snapshots carry the best
        validation metric in the filename (reference snapshotter.py:178-202
        ``validation_1.48`` convention) and bypass the *time* throttle — an
        improvement is never dropped merely for landing <``time_interval``
        seconds after the last shot.  The ``interval`` counter gate is NOT
        bypassed: it is an explicit every-Nth thinning knob the user asked
        for, and applies to improvements like everything else."""
        self.decision = decision
        return self

    def _decision_best(self):
        d = self.decision
        return (getattr(d, "best_n_err_pt", None),
                getattr(d, "best_rmse", None),
                getattr(d, "best_epoch", None))

    def _decision_suffix(self):
        best_pt, best_rmse, _ = self._decision_best()
        if best_pt is not None:
            return "validation_%.2f" % best_pt
        if best_rmse is not None:
            return "validation_%.4f" % best_rmse
        return None

    def _fresh_improvement(self):
        """Edge-triggered improvement: Decision.improved stays True for a
        whole epoch after a validation win, so a level check would bypass
        the time throttle on every minibatch; instead compare the current
        best to the best at our last export."""
        d = self.decision
        if d is None or not bool(d.improved):
            return False
        return self._decision_best() != self._last_exported_best

    def run(self):
        if bool(self.skip):
            return
        self._counter += 1
        if self._counter % max(self.interval, 1):
            return
        fresh = self._fresh_improvement()
        if not fresh and \
                time.time() - self._last_time < self.time_interval:
            return
        self._last_time = time.time()
        if fresh:
            # the suffix names the metric these weights actually achieved;
            # non-improved periodic shots keep the previous suffix only if
            # the weights haven't trained past it (they have) — so clear it
            self.suffix = self._decision_suffix()
            self._last_exported_best = self._decision_best()
        elif self.decision is not None:
            self.suffix = None
        self.export()

    def export(self):
        raise NotImplementedError

    def get_metric_values(self):
        """Surface the last snapshot path in the results JSON (reference
        optimization_workflow.py:249 reads result.get("Snapshot"); the
        ensemble test mode restores instances from it)."""
        return {"Snapshot": self.destination}


class SnapshotterToFile(SnapshotterBase):
    """Pickle the whole workflow to disk with a ``_current`` symlink."""

    MAPPING = "file"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.directory = kwargs.get(
            "directory", os.path.expanduser(
                root.common.dirs.get("snapshots", ".")))

    def export(self):
        os.makedirs(self.directory, exist_ok=True)
        target = self.workflow
        fused = getattr(target, "fused_step", None)
        if fused is not None:
            fused.sync_weights()
            fused.sync_solver_state()
        name = "%s%s.%d.pickle" % (
            self.prefix, ("_" + self.suffix) if self.suffix else "",
            self._counter)
        codec, ext = CODECS[self.compression or None]
        path = os.path.join(self.directory, name + ext)
        with open(path, "wb") as raw:
            stream = codec(raw)
            try:
                pickle.dump(target, stream,
                            protocol=pickle.HIGHEST_PROTOCOL)
            finally:
                if stream is not raw:
                    stream.close()
        self.destination = path
        link = os.path.join(self.directory, "%s_current" % self.prefix)
        try:
            if os.path.islink(link):
                os.remove(link)
            os.symlink(os.path.basename(path), link)
        except OSError:
            pass
        self._report_size(path, target)
        return path

    def _report_size(self, path, workflow, top=5):
        """Top-N fattest units diagnostic (reference snapshotter.py:
        203-226)."""
        size = os.path.getsize(path)
        if size < 64 << 20:
            return
        sizes = []
        for unit in workflow:
            try:
                sizes.append((len(pickle.dumps(unit, -1)), unit.name))
            except Exception:
                pass
        print("snapshot %s is %.1f MiB; fattest units:" %
              (path, size / 1048576), file=sys.stderr)
        for sz, name in sorted(sizes, reverse=True)[:top]:
            print("  %-30s %.1f MiB" % (name, sz / 1048576),
                  file=sys.stderr)

    @staticmethod
    def import_file(path):
        """Load a snapshot back into a Workflow object (reference
        snapshotter.py:522-535 + __main__.py:539)."""
        path = os.path.realpath(os.path.expanduser(path))
        ext = os.path.splitext(path)[1]
        opener = DECODERS.get(ext, open)
        with opener(path, "rb") as f:
            wf = pickle.load(f)
        wf._restored_from_snapshot = True
        return wf


class SnapshotterToDB(SnapshotterBase):
    """Snapshots into a SQLite database (reference SnapshotterToDB,
    snapshotter.py:428-520, used ODBC; SQLite is the zero-dependency
    equivalent — same pickle blobs, queryable history, single file)."""

    MAPPING = "db"

    SCHEMA = ("CREATE TABLE IF NOT EXISTS snapshots ("
              "id INTEGER PRIMARY KEY AUTOINCREMENT, "
              "prefix TEXT, suffix TEXT, counter INTEGER, "
              "timestamp REAL, blob BLOB)")

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.database = kwargs.get("database") or os.path.join(
            os.path.expanduser(root.common.dirs.get("snapshots", ".")),
            "snapshots.sqlite3")

    def export(self):
        import sqlite3
        os.makedirs(os.path.dirname(os.path.abspath(self.database)),
                    exist_ok=True)
        target = self.workflow
        fused = getattr(target, "fused_step", None)
        if fused is not None:
            fused.sync_weights()
            fused.sync_solver_state()
        blob = pickle.dumps(target, protocol=pickle.HIGHEST_PROTOCOL)
        with sqlite3.connect(self.database) as conn:
            conn.execute(self.SCHEMA)
            conn.execute(
                "INSERT INTO snapshots (prefix, suffix, counter, "
                "timestamp, blob) VALUES (?, ?, ?, ?, ?)",
                (self.prefix, self.suffix, self._counter, time.time(),
                 sqlite3.Binary(blob)))
        self.destination = "sqlite://%s#%s" % (self.database, self.prefix)
        return self.destination

    @staticmethod
    def import_db(uri):
        """``sqlite://<path>[#prefix]`` → newest matching snapshot."""
        import sqlite3
        body = uri[len("sqlite://"):]
        path, _, prefix = body.partition("#")
        if not os.path.exists(path):
            # connect() would CREATE an empty junk db at the typo'd path
            raise ValueError("no such snapshot database: %s" % path)
        with sqlite3.connect(path) as conn:
            if prefix:
                row = conn.execute(
                    "SELECT blob FROM snapshots WHERE prefix = ? "
                    "ORDER BY id DESC LIMIT 1", (prefix,)).fetchone()
            else:
                row = conn.execute(
                    "SELECT blob FROM snapshots "
                    "ORDER BY id DESC LIMIT 1").fetchone()
        if row is None:
            raise ValueError("no snapshot in %s" % uri)
        wf = pickle.loads(row[0])
        wf._restored_from_snapshot = True
        return wf


def restore(path):
    """Convenience resume entry: returns the restored (uninitialized)
    workflow; call .initialize(device=...) then .run().

    Sources (reference __main__.py:539-589 file/odbc/http): a snapshot
    file path, ``sqlite://db.sqlite3[#prefix]``, or an ``http(s)://``
    URL (fetched to a temp file first)."""
    if path.startswith("sqlite://"):
        return SnapshotterToDB.import_db(path)
    if path.startswith(("http://", "https://")):
        import tempfile
        import urllib.request
        suffix = os.path.splitext(path)[1] or ".pickle"
        fd, tmp = tempfile.mkstemp(suffix=suffix)
        os.close(fd)
        try:
            urllib.request.urlretrieve(path, tmp)
            return SnapshotterToFile.import_file(tmp)
        finally:
            os.unlink(tmp)
    return SnapshotterToFile.import_file(path)
