"""Device backends: the layer that binds the unit graph to hardware.

TPU-native re-design of /root/reference/veles/backends.py (Device base +
BackendRegistry :166-197, OpenCLDevice :426, CUDADevice :745, NumpyDevice
:918, AutoDevice :406).  The reference selects an OpenCL/CUDA context and
hands units raw queues; here a Device owns a set of JAX devices and a
:class:`jax.sharding.Mesh`, and hands units jit/compile services instead of
command queues.  The reference's per-device autotune database
(``device_infos.json``, backends.py:623-731) is unnecessary: XLA autotunes
tiling for the MXU at compile time, and the persistent compilation cache
plays the role of the kernel binary cache.

Backend names: ``tpu``, ``cpu`` (JAX cpu — the multi-device virtual mesh in
tests), ``numpy`` (pure-numpy pseudo-device for parity tests), ``auto``.
Selection precedence mirrors the reference (-a flag > env > auto,
backends.py:184-197): explicit name > $VELES_BACKEND > auto.
"""

import os
import threading
import time

import numpy

from .config import root


def apply_compilation_cache_config():
    """One-knob wiring of JAX's built-in persistent compilation cache:
    ``root.common.engine.compilation_cache_dir`` (+ min-entry-size)
    applied at backend init — every ``jax.jit`` in the process then
    reuses XLA binaries across restarts, covering what the executable
    cache (veles_tpu/compilecache/) doesn't own.  Unset = untouched
    (exact default behavior).  Returns the directory applied or None."""
    directory = root.common.engine.get("compilation_cache_dir", None)
    if not directory:
        return None
    import jax
    directory = os.path.abspath(str(directory))
    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes",
        int(root.common.engine.get("compilation_cache_min_entry_bytes",
                                   0)))
    # the default 1 s floor would skip every small-model compile this
    # knob exists to persist; the entry-size knob is the filter here
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return directory


class BackendRegistry(type):
    """Metaclass registering Device subclasses by their ``BACKEND`` name
    (reference backends.py:166-181)."""

    backends = {}

    def __init__(cls, name, bases, clsdict):
        super().__init__(name, bases, clsdict)
        backend = clsdict.get("BACKEND")
        if backend is not None:
            BackendRegistry.backends[backend] = cls


class Device(metaclass=BackendRegistry):
    """Base device.  ``Device(backend="tpu")`` dispatches to the registered
    subclass the way the reference's ``__new__`` trick does
    (backends.py:190-197)."""

    BACKEND = None

    def __new__(cls, *args, **kwargs):
        if cls is not Device:
            return super().__new__(cls)
        backend = kwargs.get("backend") or os.environ.get(
            "VELES_BACKEND", root.common.engine.get("backend", "auto"))
        if backend == "auto":
            backend = AutoDevice.pick()
        try:
            impl = BackendRegistry.backends[backend]
        except KeyError:
            raise ValueError(
                "unknown backend %r (have: %s)" %
                (backend, ", ".join(sorted(BackendRegistry.backends))))
        return super().__new__(impl)

    #: config precision_level → jax matmul precision.  The reference's
    #: GEMM PRECISION_LEVEL 0/1/2 (plain / Kahan / 32-partial summation,
    #: ocl/matrix_multiplication_precise.cl:37,119-170) maps onto the
    #: MXU's pass-decomposition knob: DEFAULT (fast bf16 passes), HIGH
    #: (3-pass), HIGHEST (6-pass / f32 accumulation) — same
    #: speed-vs-summation-error trade, implemented by the hardware.
    PRECISION_LEVELS = {0: "default", 1: "high", 2: "highest"}

    def __init__(self, **kwargs):
        self._compute_power = None
        self._lock = threading.Lock()
        level = kwargs.get("precision_level")
        if level is None:
            level = root.common.engine.get("precision_level", 0)
        level = int(level)
        if level not in self.PRECISION_LEVELS:
            raise ValueError(
                "precision_level must be one of %s, got %r"
                % (sorted(self.PRECISION_LEVELS), level))
        import jax
        # always applied — level 0 must RESET a prior device's elevated
        # precision, or every later workflow silently pays 3-6x matmuls
        jax.config.update("jax_default_matmul_precision",
                          self.PRECISION_LEVELS[level])
        apply_compilation_cache_config()

    # Devices ride along in workflow snapshots only as stubs: locks and
    # PJRT handles cannot pickle, and a restored workflow is re-attached
    # to a fresh Device by initialize(device=...) anyway (the reference
    # drops device state the same way, memory.py:284-299).
    def __getstate__(self):
        return {}

    def __setstate__(self, state):
        self._compute_power = None
        self._lock = threading.Lock()
        self._devices = []

    # -- identity ------------------------------------------------------------
    @property
    def backend_name(self):
        return self.BACKEND

    @property
    def is_attached(self):
        return True

    def __repr__(self):
        return "<%s>" % type(self).__name__

    # -- services ------------------------------------------------------------
    @property
    def jax_devices(self):
        """The JAX devices this Device drives (empty for numpy)."""
        return []

    @property
    def default_jax_device(self):
        devs = self.jax_devices
        return devs[0] if devs else None

    def sync(self):
        """Barrier until all dispatched work completes (reference
        device.sync(); CUDA ctx sync / OCL queue finish)."""

    def memory_stats(self):
        """Bytes in use / limit on the first device, when the platform
        reports them (reference Watcher accounting, memory.py:56-107)."""
        return {}

    @property
    def compute_power(self):
        """GFLOPS-ish rating used for load balancing (reference
        DeviceBenchmark "points", accelerated_units.py:843-858)."""
        if self._compute_power is None:
            self._compute_power = self.benchmark()
        return self._compute_power

    def benchmark(self, size=1024, dtype=None, repeats=4):
        raise NotImplementedError

    @property
    def exists(self):
        """False only for the numpy pseudo-device (reference
        backends.py:918)."""
        return True


class _JaxDevice(Device):
    """Shared implementation for JAX-backed devices."""

    PLATFORM = None

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        import jax
        self._jax = jax
        try:
            self._devices = jax.devices(self.PLATFORM)
        except RuntimeError as e:
            raise RuntimeError(
                "no %s devices visible to JAX: %s" % (self.PLATFORM, e))

    @property
    def jax_devices(self):
        return list(self._devices)

    def sync(self):
        # A tiny transfer to each device acts as the queue barrier.
        import jax
        for d in self._devices:
            jax.device_put(0, d).block_until_ready()

    def memory_stats(self):
        try:
            stats = self._devices[0].memory_stats()
        except Exception:
            return {}
        return stats or {}

    def benchmark(self, size=1024, dtype=None, repeats=4):
        """Time a square matmul; returns achieved GFLOP/s.  Plays the role
        of the reference DeviceBenchmark (accelerated_units.py:706-824)."""
        import jax
        import jax.numpy as jnp
        dtype = dtype or jnp.bfloat16
        a = jax.device_put(jnp.ones((size, size), dtype), self._devices[0])
        f = jax.jit(lambda x: x @ x)
        f(a).block_until_ready()  # compile outside the timed region
        t0 = time.perf_counter()
        for _ in range(repeats):
            r = f(a)
        r.block_until_ready()
        dt = (time.perf_counter() - t0) / repeats
        return 2.0 * size ** 3 / dt / 1e9


class TPUDevice(_JaxDevice):
    """The flagship backend: JAX TPU devices over PJRT.

    Accepts whatever the default accelerator platform is (``tpu``, or the
    tunneled single-chip ``axon`` platform in the build environment) but
    refuses to run on a CPU-only host — an explicit ``tpu`` request must not
    silently degrade (the reference raises on a missing CUDA/OCL device,
    backends.py:452-467).
    """

    BACKEND = "tpu"
    PLATFORM = None  # resolved to the default accelerator platform

    def __init__(self, **kwargs):
        import jax
        super().__init__(**kwargs)
        self._devices = jax.devices()
        if self._devices and self._devices[0].platform == "cpu":
            raise RuntimeError(
                "backend 'tpu' requested but JAX only sees CPU devices; "
                "use backend='cpu' explicitly for the virtual mesh")


class CPUDevice(_JaxDevice):
    """JAX CPU backend — used by tests as a virtual multi-device mesh
    (XLA_FLAGS=--xla_force_host_platform_device_count=N)."""

    BACKEND = "cpu"
    PLATFORM = "cpu"


class NumpyDevice(Device):
    """Pure-numpy pseudo-device: the parity-test twin (reference
    backends.py:918-949).  Units run their ``numpy_run`` path against it."""

    BACKEND = "numpy"

    @property
    def exists(self):
        return False

    def sync(self):
        pass

    def benchmark(self, size=512, dtype=numpy.float32, repeats=2):
        a = numpy.ones((size, size), dtype)
        t0 = time.perf_counter()
        for _ in range(repeats):
            a @ a
        dt = (time.perf_counter() - t0) / repeats
        return 2.0 * size ** 3 / dt / 1e9


class AutoDevice(Device):
    """Backend auto-selection (reference backends.py:406-423)."""

    BACKEND = "auto"

    @staticmethod
    def pick():
        import jax
        try:
            platform = jax.default_backend()
        except Exception:
            return "numpy"
        return "cpu" if platform == "cpu" else "tpu"

    def __new__(cls, *args, **kwargs):
        return Device(backend=AutoDevice.pick(), **kwargs)


# -- dtype table (reference veles/opencl_types.py:39-77) ----------------------
#: mapping of the config-level dtype names onto numpy/jax dtypes
dtype_map = {
    "float16": numpy.float16,
    "bfloat16": "bfloat16",   # resolved lazily through ml_dtypes via jnp
    "float32": numpy.float32,
    "float64": numpy.float64,
    "int8": numpy.int8,
    "int16": numpy.int16,
    "int32": numpy.int32,
    "int64": numpy.int64,
    "uint8": numpy.uint8,
}


def resolve_dtype(name=None):
    """Config dtype name -> numpy dtype object (jnp understands all)."""
    name = name or root.common.engine.get("dtype", "float32")
    dt = dtype_map[name]
    if dt == "bfloat16":
        import ml_dtypes
        return numpy.dtype(ml_dtypes.bfloat16)
    return numpy.dtype(dt)
