"""Workflow: a container of linked units with a run lifecycle.

TPU-native re-design of /root/reference/veles/workflow.py:87-1051.  Kept:
unit multiset with add_ref/del_ref, initialize in dependency order with
deferred-init retries, run/stop lifecycle via StartPoint/EndPoint, aggregation
of the IDistributable 5-method protocol across member units
(workflow.py:478-574), Graphviz graph generation (:628), results gathering
(:827), checksum (:852), per-unit timing table (:788-825).

Changed: execution is an iterative worklist loop (see units.py docstring) and
``package_export`` lives in :mod:`veles_tpu.export` producing a
StableHLO+weights archive instead of pickled OpenCL workflows.
"""

import collections
import hashlib
import json
import sys

from .plumbing import StartPoint, EndPoint
from .result_provider import IResultProvider
from .units import Container


class NoMoreJobs(Exception):
    """Raised by generate_data_for_slave when the epoch is exhausted."""


class Workflow(Container):
    """A directed graph of units executed from start_point to end_point."""

    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self._units = []
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self._sync_jax = bool(kwargs.get("sync_jax", False))
        self.device = None
        self.launcher_ref = None
        self.result_file = kwargs.get("result_file")
        self._restored_from_snapshot = False

    def init_unpickled(self):
        super().init_unpickled()
        self._queue_ = collections.deque()
        self._is_finished_ = False
        self._is_running_ = False
        self._run_after_stop_warned_ = set()
        self._on_finished_callbacks_ = []

    # -- container protocol --------------------------------------------------
    def add_ref(self, unit):
        if unit is self:
            raise ValueError("a workflow cannot contain itself")
        if unit not in self._units:
            # unique member names: links, stats, and the export archive
            # (per-unit .npy paths, package contents.json) are all keyed
            # by name — two default-named Conv units must not collide
            taken = {u.name for u in self._units}
            if unit.name in taken:
                base = unit.name
                i = 1
                while "%s.%d" % (base, i) in taken:
                    i += 1
                unit.name = "%s.%d" % (base, i)
            self._units.append(unit)
        unit.workflow = self

    def del_ref(self, unit):
        if unit in self._units:
            self._units.remove(unit)

    @property
    def units(self):
        return list(self._units)

    def __iter__(self):
        return iter(self._units)

    def __len__(self):
        return len(self._units)

    def __getitem__(self, key):
        if isinstance(key, str):
            for u in self._units:
                if u.name == key:
                    return u
            raise KeyError(key)
        return self._units[key]

    def index_of(self, unit):
        return self._units.index(unit)

    # -- state ---------------------------------------------------------------
    @property
    def is_finished(self):
        return self._is_finished_

    @property
    def is_running(self):
        return self._is_running_

    @property
    def restored_from_snapshot(self):
        return self._restored_from_snapshot

    # -- lifecycle -----------------------------------------------------------
    def initialize(self, device=None, **kwargs):
        """Initialize all units in dependency order.

        A unit returning True from initialize() means "dependencies not yet
        satisfied" — it is retried after the others (reference
        workflow.py:303-350 deferred init).
        """
        super().initialize(**kwargs)
        self.device = device
        order = self._dependency_order()
        pending = collections.deque(order)
        retries = 0
        max_retries = len(pending) ** 2 + 10
        while pending:
            unit = pending.popleft()
            if unit is self:
                continue
            unit.verify_demands()
            deferred = unit.initialize(device=device, **kwargs)
            if deferred:
                pending.append(unit)
                retries += 1
                if retries > max_retries:
                    raise RuntimeError(
                        "initialization deadlock; still pending: %s" %
                        ([u.name for u in pending]))
        for unit in order:
            unit.reset_gates()
        self._is_finished_ = False
        return self

    def _dependency_order(self):
        """Topological order over control links from start_point, then any
        unlinked units in insertion order."""
        order, seen = [], set()
        queue = collections.deque([self.start_point])
        indeg = {}
        for u in self._units:
            indeg[u] = len(u.links_from)
        while queue:
            u = queue.popleft()
            if id(u) in seen:
                continue
            seen.add(id(u))
            order.append(u)
            for dst in u.links_to:
                if id(dst) not in seen:
                    indeg[dst] = indeg.get(dst, 1) - 1
                    if indeg[dst] <= 0 or dst.ignores_gate:
                        queue.append(dst)
        # break cycles / pick up stragglers in insertion order
        for u in self._units:
            if id(u) not in seen:
                seen.add(id(u))
                order.append(u)
        return order

    def run(self):
        """Execute the graph from start_point until the workflow finishes or
        no unit is ready (reference workflow.py:351-400)."""
        self._is_running_ = True
        self._is_finished_ = False
        for unit in self._units:
            unit.reset_gates()  # no stale AND-gate latches from a prior run
        schedule = self._queue_.append
        try:
            self.start_point.execute(schedule)
            while self._queue_:
                unit = self._queue_.popleft()
                if self._is_finished_ and not (unit.runs_after_stop or
                                               unit.ignores_gate):
                    # scheduled before EndPoint fired this iteration;
                    # only service side-branches (plotters, reporters)
                    # still observe the final state
                    continue
                unit.execute(schedule)
        finally:
            self._queue_.clear()
            self._is_running_ = False
        return self

    def on_workflow_finished(self):
        self._is_finished_ = True
        for unit in self._units:
            unit.stop()
        for cb in self._on_finished_callbacks_:
            cb()

    def add_finished_callback(self, cb):
        self._on_finished_callbacks_.append(cb)

    def stop(self):
        if not self._is_finished_:
            self.on_workflow_finished()

    def warning_run_after_stop(self, unit):
        if unit.name not in self._run_after_stop_warned_:
            self._run_after_stop_warned_.add(unit.name)
            print("WARNING: %s signaled after the workflow finished "
                  "(check your links)" % unit, file=sys.stderr)

    def make_train_gate(self, loader):
        """A gate_skip Bool that is True while the loader serves non-train
        minibatches — wire it to GD units so updates happen only on the
        train class (the reference links gds through Decision the same
        way)."""
        from .loader.base import TRAIN
        from .mutable import Bool
        return Bool.from_callable(
            lambda: loader.minibatch_class != TRAIN,
            name="not_train")

    # -- IDistributable aggregation (reference workflow.py:478-574) ----------
    def generate_data_for_master(self):
        data = []
        for unit in self._units:
            data.append(unit.generate_data_for_master())
        return data

    def generate_data_for_slave(self, slave=None):
        data = []
        has_any = False
        for unit in self._units:
            if not unit.has_data_for_slave:
                data.append(None)
                continue
            data.append(unit.generate_data_for_slave(slave))
            has_any = True
        if not has_any:
            raise NoMoreJobs()
        return data

    def apply_data_from_master(self, data):
        for unit, d in zip(self._units, data):
            if d is not None:
                unit.apply_data_from_master(d)

    def apply_data_from_slave(self, data, slave=None):
        with self:
            for unit, d in zip(self._units, data):
                if d is not None:
                    unit.apply_data_from_slave(d, slave)

    def drop_slave(self, slave=None):
        for unit in self._units:
            unit.drop_slave(slave)

    def do_job(self, data, update, callback):
        """Slave-side: apply master data, run one pass, call back with the
        update (reference workflow.py:558-574)."""
        self.apply_data_from_master(data)
        if update is not None:
            self.apply_data_from_slave(update)
        self.run()
        callback(self.generate_data_for_master())

    # -- input pipeline ------------------------------------------------------
    def attach_prefetcher(self, loader=None, **kwargs):
        """Attach a background
        :class:`~veles_tpu.loader.prefetch.MinibatchPrefetcher` to this
        workflow's loader (``root.common.loader.prefetch_depth`` deep
        unless ``depth=`` is given; 0 disables).  Call after
        ``initialize`` — minibatch buffers and the device path must
        exist.  When the training step exposes a batch sharding
        (``_batch_sharding_``, set by the distributed per-step trainer)
        prefetched minibatches are device_put straight onto it.  Attach
        BEFORE ``attach_profiler`` so the profiler's data-wait phase
        measures time blocked on the prefetch queue.  Returns the
        prefetcher, or None when disabled/unsupported."""
        from .loader.prefetch import MinibatchPrefetcher
        if loader is None:
            loader = getattr(self, "loader", None)
        if loader is None:
            raise ValueError("no loader to prefetch for %r" % self)
        step = getattr(self, "fused_step", None)
        kwargs.setdefault("sharding",
                          getattr(step, "_batch_sharding_", None))
        self.prefetcher_ = MinibatchPrefetcher.attach(loader, **kwargs)
        return self.prefetcher_

    # -- whole-workflow compilation ------------------------------------------
    def attach_graph_compiler(self, **kwargs):
        """Trace this workflow's unit DAG into compiled XLA programs
        (:mod:`veles_tpu.graphcomp`): consecutively-fired units with pure
        trace faces batch into ONE jitted, buffer-donating program per
        flush; host-side units (loader, decision, plotters) stay
        interpreted at region boundaries with recorded fallback reasons.
        Call after ``initialize`` (faces need shapes and params) and
        BEFORE ``attach_profiler`` (the profiler then wraps the traced
        flush).  Returns the controller, or None when tracing is
        unsupported (no jax, numpy backend).  Stored transiently
        (``graph_controller_``): snapshots never pickle the controller;
        restored workflows re-attach through their own initialize."""
        from .graphcomp import GraphCompiler
        self.graph_controller_ = GraphCompiler.attach(self, **kwargs)
        return self.graph_controller_

    @property
    def graph_controller(self):
        return getattr(self, "graph_controller_", None)

    def __getstate__(self):
        # a snapshot taken while tracing is attached must capture the
        # CURRENT carry (weights, solver state, metric accumulators), so
        # it restores/resumes identically on a process without tracing
        controller = getattr(self, "graph_controller_", None)
        if controller is not None:
            controller.sync_state()
        return super().__getstate__()

    # -- observability -------------------------------------------------------
    def attach_profiler(self, **kwargs):
        """Instrument this workflow's training step with a
        :class:`~veles_tpu.observability.profiler.StepProfiler`
        (data-wait/host/device/snapshot split, recompile count,
        examples/sec, memory watermarks → registry metrics + EventLog
        spans).  Call after ``initialize`` — the step's jitted functions
        must exist for recompile accounting.  The profiler is also
        reachable as ``self.profiler``; ``profiler.detach()`` removes
        its wrappers.  Stored transiently (``profiler_``): a snapshot
        taken while profiling must never try to serialize the profiler
        (registry series hold locks)."""
        from .observability.profiler import StepProfiler
        self.profiler_ = StepProfiler(self, **kwargs)
        return self.profiler_

    @property
    def profiler(self):
        return getattr(self, "profiler_", None)

    # -- results / stats -----------------------------------------------------
    def gather_results(self):
        """Collect metrics from every IResultProvider unit
        (reference workflow.py:827-849)."""
        results = {}
        for unit in self._units:
            if isinstance(unit, IResultProvider):
                results.update(unit.get_metric_values())
        return results

    def write_results(self, file=None, results=None):
        """Serialize results JSON (the single serialization path — the
        Launcher passes its enriched dict through ``results``)."""
        results = results if results is not None else self.gather_results()
        path = file or self.result_file
        if path == "-":
            json.dump(results, sys.stdout, indent=2, default=str)
            sys.stdout.write("\n")
        elif path:
            with open(path, "w") as f:
                json.dump(results, f, indent=2, default=str)
        return results

    def print_stats(self, top=10, file=None):
        """Top-N unit run-time table (reference workflow.py:788-825)."""
        file = file or sys.stdout
        total = sum(u.timers["run"] for u in self._units) or 1e-12
        rows = sorted(((u.timers["run"], u.timers["runs"], u.name)
                       for u in self._units), reverse=True)[:top]
        print("%-28s %10s %8s %7s" % ("unit", "time,s", "runs", "%"),
              file=file)
        for t, n, name in rows:
            print("%-28s %10.3f %8d %6.1f%%" % (name, t, n, 100 * t / total),
                  file=file)

    # -- graph / identity ----------------------------------------------------
    def generate_graph(self, filename=None):
        """Emit the unit graph in Graphviz dot format
        (reference workflow.py:628)."""
        lines = ["digraph %s {" % self.name.replace(" ", "_")]
        for u in self._units:
            lines.append('  "%s" [label="%s\\n%s"];' %
                         (u.name, u.name, u.__class__.__name__))
        for u in self._units:
            for dst in u.links_to:
                lines.append('  "%s" -> "%s";' % (u.name, dst.name))
        lines.append("}")
        text = "\n".join(lines)
        if filename:
            with open(filename, "w") as f:
                f.write(text)
        return text

    @property
    def checksum(self):
        """Stable digest of the unit graph used in the master/slave handshake
        (reference workflow.py:852-866)."""
        desc = json.dumps([u.describe() for u in self._units],
                          sort_keys=True, default=str)
        return hashlib.sha256(desc.encode()).hexdigest()

    def package_export(self, path, precision=32):
        from .export.packager import package_export
        return package_export(self, path, precision=precision)
