"""Avatar: decoupled copies of another unit's output attributes.

Re-creation of /root/reference/veles/avatar.py:84: multi-consumer graphs
sometimes need a frozen copy of the loader's minibatch (e.g. one branch
mutates/normalizes while another needs the original).  ``clone()``
registers which attributes to copy; each run snapshots them into this
unit's own Arrays.
"""

import numpy

from .memory import Array
from .units import Unit


class Avatar(Unit):
    MAPPING = "avatar"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self._cloned = []

    def clone(self, unit, *attrs):
        """Copy ``unit.<attr>`` into ``self.<attr>`` on every run."""
        for attr in attrs:
            # no leading underscore: linked attrs resolve through
            # the Unit attribute machinery, which bypasses _-names
            self.link_attrs(unit, ("src_%s" % attr, attr))
            setattr(self, attr, Array())
            self._cloned.append(attr)
        return self

    def run(self):
        for attr in self._cloned:
            src = getattr(self, "src_%s" % attr)
            dst = getattr(self, attr)
            if isinstance(src, Array):
                if src.devmem is not None:
                    # device-side copy: one fused kernel, no host trip
                    import jax.numpy as jnp
                    dst.devmem = jnp.array(src.devmem)
                else:
                    dst.mem = numpy.array(src.map_read())
            else:
                setattr(self, attr, numpy.copy(src))
