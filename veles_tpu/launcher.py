"""Launcher: the runtime owner that takes a built workflow end-to-end.

TPU-native re-design of /root/reference/veles/launcher.py:100-906.  The
reference Launcher's job was mode selection (master/slave/standalone), the
Twisted reactor, SSH node spawning, and service side-cars.  On TPU the
tensor-level distribution lives *inside* the jitted step (mesh shardings,
parallel/dp.py), so the Launcher keeps the surviving responsibilities:

- device construction and workflow ``initialize``/``run`` lifecycle
  (reference launcher.py:431-512, :550-564);
- run modes: ``standalone`` (this process computes) and the dry-run
  levels consumed by the CLI (reference __main__.py "--dry-run");
- results gathering + ``--result-file`` JSON (reference workflow.py:827);
- per-run stats printing and wall-clock accounting (launcher.py:779-786);
- graceful stop + finished callbacks;
- service side-cars (web status reporter, event log) hook in here once
  built — the attachment points are ``on_initialized``/``on_finished``.

Mesh parallelism is requested by the *workflow* (``mesh=`` kwarg), not the
launcher; meta-level multi-process scheduling (ensembles, GA) re-invokes
the CLI per trial, as the reference did via subprocess (SURVEY.md §2.11).
"""

import sys
import time

from .config import root
from .observability import trace as _trace


def memory_report(device=None):
    """Peak host RSS + per-device HBM peak for the devices the RUN
    actually used, as printable lines (the reference printed max RSS
    and device memory at exit, /root/reference/veles/__main__.py:
    787-799).  Only inspects ``device`` (the Launcher's) — never calls
    global ``jax.devices()``, which could first-time-initialize an
    unused (and possibly wedged tunneled) backend from an exit
    diagnostic."""
    lines = []
    try:
        import resource
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        if sys.platform == "darwin":
            peak /= 1024.0  # BSD reports bytes, Linux kilobytes
        lines.append("Peak host RSS: %.1f MiB" % (peak / 1024.0))
    except Exception:  # noqa: BLE001 — diagnostics must never raise
        pass
    for dev in getattr(device, "jax_devices", None) or []:
        try:  # per device: one platform's failure must not hide the rest
            stats = dev.memory_stats() or {}
            peak = stats.get("peak_bytes_in_use")
        except Exception:  # noqa: BLE001
            continue
        if peak:
            lines.append(
                "Device %s peak memory: %.1f MiB" %
                (dev, peak / (1024.0 * 1024.0)))
    return lines


class Launcher:
    """Owns device + lifecycle for one workflow run."""

    def __init__(self, backend=None, result_file=None, stealth=False,
                 **kwargs):
        self.backend = backend or root.common.engine.get("backend", "auto")
        self.result_file = result_file
        self.stealth = stealth          # no external reporting side-cars
        self.workflow = None
        self.device = None
        self.profiler = None
        # a parent process (jobserver worker, ElasticRunner, GA trial
        # farm) may have handed us its trace context — join it so this
        # run's events share the distributed trace_id
        _trace.adopt_env()
        self.start_time = None
        self.finish_time = None
        self.on_initialized = []        # callbacks(workflow)
        self.on_finished = []           # callbacks(workflow)
        self.status_server = None
        status_port = kwargs.pop("status_port", None)
        if status_port is None:
            status_port = root.common.web_status.get("port", None)
        if status_port is not None and not stealth:
            # in-process HTTP status side-car (reference launcher.py:
            # 852-885 posted heartbeats to an external Tornado server);
            # serve() reuses a live server on the same port
            from .web_status import serve
            self.status_server = serve(int(status_port))
        self._extra = kwargs

    # -- lifecycle -----------------------------------------------------------
    def add_workflow(self, workflow):
        self.workflow = workflow
        return workflow

    def initialize(self, **kwargs):
        from .backends import Device
        if self.workflow is None:
            raise ValueError("no workflow attached (call add_workflow)")
        if self.device is None:
            self.device = Device(backend=self.backend)
        self.workflow.initialize(device=self.device, **kwargs)
        if root.common.observability.get("profile", False) and \
                not self.stealth:
            # opt-in step profiler side-car (fencing is honest but not
            # free — see observability/profiler.py): CLI flag or
            # root.common.observability.profile = True
            try:
                self.profiler = self.workflow.attach_profiler()
            except ValueError:
                self.profiler = None    # no training step (e.g. eval wf)
        for cb in self.on_initialized:
            cb(self.workflow)
        return self

    def run(self):
        self.start_time = time.time()
        # one span context per run: every event the run emits (unit
        # spans, train.step, serving) then shares a trace_id — fresh
        # unless a parent process's context was adopted at construction
        with _trace.span_context():
            try:
                self.workflow.run()
            finally:
                self.finish_time = time.time()
        for cb in self.on_finished:
            cb(self.workflow)
        if self.result_file:
            self.write_results(self.result_file)
        return self.workflow

    def stop(self):
        if self.workflow is not None:
            self.workflow.stop()
        if self.status_server is not None:
            self.status_server.stop()
            self.status_server = None

    # -- results -------------------------------------------------------------
    def gather_results(self):
        results = self.workflow.gather_results()
        results.setdefault("name", self.workflow.name)
        if self.start_time is not None:
            results["seconds"] = round(
                (self.finish_time or time.time()) - self.start_time, 3)
        results["backend"] = getattr(self.device, "backend", self.backend)
        if self.profiler is not None:
            results["profile"] = self.profiler.summary()
        return results

    def write_results(self, file):
        return self.workflow.write_results(file,
                                           results=self.gather_results())

    def print_stats(self, file=None):
        self.workflow.print_stats(file=file)
        if self.start_time is not None:
            print("Total run time: %.3f s" %
                  ((self.finish_time or time.time()) - self.start_time),
                  file=file or sys.stdout)
        for line in memory_report(self.device):
            print(line, file=file or sys.stdout)
