"""Genetic hyperparameter optimization (reference veles/genetics/).

``Range`` placeholders in the config tree (veles_tpu.config.Range) mark
tuneable values; the optimizer evolves a population of chromosomes over
them, evaluating each by running the model — in-process via a callable,
or as a subprocess of the CLI exactly like the reference re-invoked
``veles.__main__`` per trial (reference optimization_workflow.py:223-296).
"""

from .core import Chromosome, Population, schwefel
from .optimizer import GeneticsOptimizer, optimize

__all__ = ["Chromosome", "Population", "schwefel", "GeneticsOptimizer",
           "optimize"]
