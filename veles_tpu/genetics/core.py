"""GA core: chromosomes, mutation, selection, crossover, population loop.

Re-creation of /root/reference/veles/genetics/core.py (:257-760) with the
same operator families:

- mutation: altering (gene swap), gaussian (bounded additive noise),
  uniform (bounded resample) — core.py:277-370;
- selection: roulette, random, tournament — core.py:573-617;
- crossover: pointed (k-point), uniform, arithmetic, geometric —
  core.py:633-760, driven as a probability-weighted pipeline.

Dropped deliberately: the reference's binary/gray *bitstring* coding of
floats (core.py Chromosome.binary) — an artifact of its OpenCL bit-level
mutation kernels; numeric coding covers the same search capability and
is what the reference itself defaults to (optimization.code "float").
Choice-typed genes (``Range(v, [choices])``) mutate by resampling the
choice list, mirroring the reference's ``choice == "or"`` mode.
"""

import numpy


def schwefel(values):
    """Schwefel test function (reference core.py:58): global max 0 at
    x_i = 420.9687, used by the self-tests."""
    values = numpy.asarray(values, numpy.float64)
    return -418.9829 * len(values) + float(
        (values * numpy.sin(numpy.sqrt(numpy.abs(values)))).sum())


class Chromosome:
    """Numeric-coded chromosome: one gene per tuneable."""

    def __init__(self, genes, min_values, max_values, rand, choices=None):
        self.genes = list(genes)
        self.min_values = list(min_values)
        self.max_values = list(max_values)
        self.choices = choices or [None] * len(self.genes)
        self.rand = rand
        self.fitness = None
        self.config_snapshot = None      # filled by the optimizer

    @classmethod
    def random(cls, min_values, max_values, rand, choices=None):
        choices = choices or [None] * len(min_values)
        genes = []
        for lo, hi, ch in zip(min_values, max_values, choices):
            if ch is not None:
                genes.append(ch[rand.randint(0, len(ch))])
            else:
                g = rand.uniform(lo, hi)
                if isinstance(lo, int) and isinstance(hi, int):
                    g = int(round(g))
                genes.append(g)
        return cls(genes, min_values, max_values, rand, choices)

    def copy(self):
        c = Chromosome(self.genes, self.min_values, self.max_values,
                       self.rand, self.choices)
        c.fitness = self.fitness
        return c

    def _clip(self, i, value):
        lo, hi = self.min_values[i], self.max_values[i]
        # reflect back into range (reference wraps by +/- diff)
        diff = hi - lo
        if diff <= 0:
            return lo  # degenerate Range(v) with no bounds: pinned
        while value < lo or value > hi:
            value = value + diff if value < lo else value - diff
        if isinstance(lo, int) and isinstance(hi, int):
            value = int(round(value))
        return value

    # -- mutation ops (reference core.py:277-370) ---------------------------
    def mutation_altering(self, n_points, probability):
        """Swap two gene positions."""
        for _ in range(n_points):
            if self.rand.uniform(0, 1) < probability and len(self.genes) > 1:
                i = self.rand.randint(0, len(self.genes))
                j = self.rand.randint(0, len(self.genes))
                if self.choices[i] is None and self.choices[j] is None:
                    self.genes[i], self.genes[j] = (self.genes[j],
                                                    self.genes[i])
                    self.fitness = None

    def mutation_gaussian(self, n_points, probability):
        """Add bounded gaussian noise to up to n_points genes."""
        pool = list(range(len(self.genes)))
        for _ in range(min(n_points, len(pool))):
            i = pool.pop(self.rand.randint(0, len(pool)))
            if self.rand.uniform(0, 1) >= probability:
                continue
            self.fitness = None
            if self.choices[i] is not None:
                ch = self.choices[i]
                self.genes[i] = ch[self.rand.randint(0, len(ch))]
                continue
            lo, hi = self.min_values[i], self.max_values[i]
            diff = hi - lo
            noise = self.rand.normal(0.0, numpy.sqrt(max(diff, 1e-12) / 6))
            sign = 1.0 if self.rand.uniform(0, 1) < 0.5 else -1.0
            self.genes[i] = self._clip(i, self.genes[i] + sign * noise)

    def mutation_uniform(self, n_points, probability):
        """Resample up to n_points genes uniformly in range."""
        pool = list(range(len(self.genes)))
        for _ in range(min(n_points, len(pool))):
            i = pool.pop(self.rand.randint(0, len(pool)))
            if self.rand.uniform(0, 1) >= probability:
                continue
            self.fitness = None
            if self.choices[i] is not None:
                ch = self.choices[i]
                self.genes[i] = ch[self.rand.randint(0, len(ch))]
                continue
            lo, hi = self.min_values[i], self.max_values[i]
            self.genes[i] = self._clip(i, self.rand.uniform(lo, hi))

    def mutate(self, name, n_points=1, probability=0.4):
        getattr(self, "mutation_" + name)(n_points, probability)


class Population:
    """Fixed-size population with the reference's evolve cycle:
    select parents → crossover pipeline adds offspring → mutate →
    evaluate → sort by fitness → truncate (reference core.py:573-880)."""

    #: hard backstop against unbounded evolution (reference core.py
    #: MAX_GENERATIONS)
    MAX_GENERATIONS = 1000

    def __init__(self, min_values, max_values, size, rand, choices=None,
                 max_generations=None, patience=3, crossing_attempts=10):
        self.patience = patience
        self._stale_generations = 0
        assert len(min_values) == len(max_values)
        self.min_values = list(min_values)
        self.max_values = list(max_values)
        self.choices = choices or [None] * len(min_values)
        self.size = int(size)
        self.rand = rand
        self.max_generations = max_generations
        self.crossing_attempts = crossing_attempts
        self.generation = 0
        self.best_fit = None
        self.average_fit = None
        self.improved = True
        # reference crossing pipeline shares (core.py:612-632)
        self.roulette_select_size = 0.75
        self.crossings = (("uniform", 0.15, 0.9),
                          ("arithmetic", 0.15, 0.9),
                          ("geometric", 0.2, 0.9),
                          ("pointed", 0.2, 1.0))
        self.mutations = (("gaussian", 1, 0.35),
                          ("uniform", 1, 0.35),
                          ("altering", 1, 0.1))
        self.chromosomes = [
            Chromosome.random(self.min_values, self.max_values, rand,
                              self.choices)
            for _ in range(self.size)]

    def __len__(self):
        return len(self.chromosomes)

    def __iter__(self):
        return iter(self.chromosomes)

    def __getitem__(self, i):
        return self.chromosomes[i]

    # -- selection (reference core.py:573-617) ------------------------------
    def select_roulette(self, count=None):
        count = count or int(len(self) * self.roulette_select_size)
        fits = numpy.array([c.fitness for c in self.chromosomes],
                           numpy.float64)
        # failed evaluations (-inf) get zero weight; the finite worst
        # keeps a sliver so diversity survives
        finite = numpy.isfinite(fits)
        if not finite.any():
            fits = numpy.ones(len(fits))
        else:
            lo = fits[finite].min()
            span = fits[finite].max() - lo
            fits = numpy.where(finite, fits - lo + max(span, 1.0) * 1e-3,
                               0.0)
        probs = numpy.cumsum(fits / fits.sum())
        out = []
        for _ in range(count):
            r = self.rand.uniform(0, 1)
            out.append(self.chromosomes[int(numpy.searchsorted(probs, r))])
        return out

    def select_random(self, count=None):
        count = count or len(self) // 2
        return [self.chromosomes[self.rand.randint(0, len(self))]
                for _ in range(count)]

    def select_tournament(self, count=None, pool_ratio=0.5):
        count = count or max(2, len(self) // 10)
        pool = sorted(
            (self.chromosomes[self.rand.randint(0, len(self))]
             for _ in range(int(len(self) * pool_ratio))),
            key=lambda c: -(c.fitness if c.fitness is not None
                            else -numpy.inf))
        return pool[:count]

    # -- crossover ops (reference core.py:633-760) --------------------------
    def _parents(self, parents):
        a = parents[self.rand.randint(0, len(parents))]
        b = parents[self.rand.randint(0, len(parents))]
        return a, b

    def cross_pointed(self, parents, n_points=1):
        a, b = self._parents(parents)
        cut = sorted(self.rand.randint(0, len(a.genes) + 1)
                     for _ in range(n_points))
        genes1, genes2 = list(a.genes), list(b.genes)
        flip = False
        prev = 0
        for c in cut + [len(a.genes)]:
            if flip:
                genes1[prev:c], genes2[prev:c] = (genes2[prev:c],
                                                  genes1[prev:c])
            flip = not flip
            prev = c
        return [Chromosome(genes1, self.min_values, self.max_values,
                           self.rand, self.choices)]

    def cross_uniform(self, parents, probability=0.9):
        a, b = self._parents(parents)
        genes = [ga if self.rand.uniform(0, 1) < 0.5 else gb
                 for ga, gb in zip(a.genes, b.genes)]
        return [Chromosome(genes, self.min_values, self.max_values,
                           self.rand, self.choices)]

    def cross_arithmetic(self, parents, probability=0.9):
        a, b = self._parents(parents)
        genes = []
        for i, (ga, gb) in enumerate(zip(a.genes, b.genes)):
            if self.choices[i] is not None:
                genes.append(ga if self.rand.uniform(0, 1) < 0.5 else gb)
                continue
            k = self.rand.uniform(0, 1)
            g = k * ga + (1 - k) * gb
            if isinstance(self.min_values[i], int) and \
                    isinstance(self.max_values[i], int):
                g = int(round(g))
            genes.append(g)
        return [Chromosome(genes, self.min_values, self.max_values,
                           self.rand, self.choices)]

    def cross_geometric(self, parents, probability=0.9):
        a, b = self._parents(parents)
        genes = []
        for i, (ga, gb) in enumerate(zip(a.genes, b.genes)):
            if self.choices[i] is not None:
                genes.append(ga if self.rand.uniform(0, 1) < 0.5 else gb)
                continue
            lo = self.min_values[i]
            # geometric mean in the shifted-positive domain
            sa, sb = ga - lo + 1e-9, gb - lo + 1e-9
            g = lo + float(numpy.sqrt(sa * sb)) - 1e-9
            if isinstance(lo, int) and isinstance(self.max_values[i], int):
                g = int(round(g))
            genes.append(g)
        return [Chromosome(genes, self.min_values, self.max_values,
                           self.rand, self.choices)]

    # -- evolve -------------------------------------------------------------
    def evolve(self, evaluate, evaluate_many=None):
        """One generation: returns True while the population keeps
        improving and max_generations is not exhausted.

        ``evaluate_many(chromosomes) -> [fitness]`` , when given, scores a
        whole cohort in one call — the hook the cross-host trial
        scheduler uses to farm a generation over workers (the reference
        evaluated a generation across its slaves the same way)."""
        def run_eval(chromos):
            todo = [c for c in chromos if c.fitness is None]
            if not todo:
                return
            if evaluate_many is not None:
                for c, fit in zip(todo, evaluate_many(todo)):
                    c.fitness = fit
            else:
                for c in todo:
                    c.fitness = evaluate(c)

        run_eval(self.chromosomes)
        prev_best = self.best_fit
        parents = self.select_roulette()
        offspring = []
        for name, share, prob in self.crossings:
            op = getattr(self, "cross_" + name)
            for _ in range(max(1, int(len(self) * share))):
                if self.rand.uniform(0, 1) < prob:
                    offspring.extend(op(parents))
        for child in offspring:
            name, pts, prob = self.mutations[
                self.rand.randint(0, len(self.mutations))]
            child.mutate(name, pts, prob)
        run_eval(offspring)
        pool = self.chromosomes + offspring
        pool.sort(key=lambda c: -c.fitness)
        self.chromosomes = pool[:self.size]
        self.best_fit = self.chromosomes[0].fitness
        self.average_fit = float(numpy.mean(
            [c.fitness for c in self.chromosomes]))
        self.generation += 1
        self.improved = prev_best is None or self.best_fit > prev_best
        self._stale_generations = 0 if self.improved else \
            self._stale_generations + 1
        if self.max_generations is not None and \
                self.generation >= self.max_generations:
            return False
        if self.generation >= self.MAX_GENERATIONS:
            return False
        # no explicit generation budget: run until the population stops
        # improving for `patience` generations (the reference stopped on
        # ~population.improved the same way)
        if self.max_generations is None and \
                self._stale_generations >= self.patience:
            return False
        return True

    @property
    def best(self):
        return self.chromosomes[0]
