"""GeneticsOptimizer: evolve Range-marked config values over model runs.

Re-creation of /root/reference/veles/genetics/optimization_workflow.py
(:70-296).  The reference evaluated each chromosome by re-invoking
``veles.__main__`` as a subprocess with a patched pickled config; here
each trial is a subprocess of *our* CLI (``python -m veles_tpu``) with
plain ``root.x.y=value`` overrides — same isolation (fresh process, fresh
jit cache, fresh devices), simpler plumbing.  An in-process ``evaluator``
callable is supported for tests and for cheap objectives.

Fitness: the reference looked up ``EvaluationFitness`` in the result
JSON; we read ``fitness_key`` (default ``best_validation_error_pt``) and
negate it when ``minimize`` (default) so the GA always maximizes.
"""

import sys

from ..config import root, get_config_ranges
from ..prng import RandomGenerator
from .core import Population


class GeneticsOptimizer:
    """Drives a Population over the Range placeholders of a config tree.

    Parameters
    ----------
    model: workflow file path or module name (subprocess mode), or None
        when ``evaluator`` is given.
    config: the Config node to scan for Range placeholders (e.g.
        ``root.mnist``); default: the whole root.
    evaluator: optional callable({path: value}) -> float fitness
        (maximized).  When absent, trials run as CLI subprocesses.
    size: population size.  generations: max generations.
    fitness_key / minimize: how to read the result JSON (subprocess mode).
    argv: extra CLI arguments for every trial (config file, overrides,
        ``--backend`` etc.).
    """

    def __init__(self, model=None, config=None, evaluator=None, size=10,
                 generations=None, fitness_key="best_validation_error_pt",
                 minimize=True, argv=(), rand=None, python=None,
                 timeout=None, silent=False, env=None, scheduler=None):
        self.env = env
        #: optional jobserver.JobMaster: farm each generation's trials to
        #: connected workers instead of running them serially in-process
        self.scheduler = scheduler
        self.model = model
        self.config_node = config if config is not None else root
        self.evaluator = evaluator
        self.fitness_key = fitness_key
        self.minimize = minimize
        self.argv = list(argv)
        self.python = python or sys.executable
        self.timeout = timeout
        self.silent = silent
        self.tuneables = get_config_ranges(self.config_node)
        if not self.tuneables:
            raise ValueError(
                "no tuneable parameters: wrap at least one config value "
                "in veles_tpu.config.Range (reference "
                "optimization_workflow.py:82-86)")
        mins, maxs, choices = [], [], []
        for _path, rng in self.tuneables:
            if rng.choices is not None:
                mins.append(0)
                maxs.append(len(rng.choices) - 1)
                choices.append(list(rng.choices))
            else:
                mins.append(rng.min_value)
                maxs.append(rng.max_value)
                choices.append(None)
        self.population = Population(
            mins, maxs, size, rand or RandomGenerator().seed(8),
            choices=choices, max_generations=generations)
        self.trials = 0
        self.failures = 0
        self._last_failure = None

    # -- evaluation ----------------------------------------------------------
    def overrides_for(self, chromo):
        return {path: gene
                for (path, _rng), gene in zip(self.tuneables, chromo.genes)}

    def _evaluate(self, chromo):
        assignments = self.overrides_for(chromo)
        self.trials += 1
        if self.evaluator is not None:
            fitness = float(self.evaluator(assignments))
        else:
            fitness = self._evaluate_subprocess(assignments)
        chromo.config_snapshot = assignments
        if not self.silent:
            print("trial %d: %s -> fitness %.6f" %
                  (self.trials, assignments, fitness))
        return fitness

    def _evaluate_subprocess(self, assignments):
        from ..subproc import run_trial
        rc, result, error = run_trial(self.model,
                                      self._trial_argv_for(assignments),
                                      timeout=self.timeout, env=self.env,
                                      python=self.python)
        # failed trial = worst possible fitness (the reference raised
        # EvaluationError and dropped the chromosome)
        return self._fitness_from(result, error)

    def _trial_argv_for(self, assignments):
        return self.argv + ["%s=%r" % (path, value)
                            for path, value in assignments.items()]

    def _evaluate_many(self, chromos):
        """Score a cohort by farming one CLI trial per chromosome to the
        scheduler's workers (reference: one chromosome per slave job,
        server.py:369-430)."""
        payloads = []
        for c in chromos:
            assignments = self.overrides_for(c)
            c.config_snapshot = assignments
            payloads.append({"kind": "trial", "model": self.model,
                             "argv": self._trial_argv_for(assignments),
                             "timeout": self.timeout,
                             "env": dict(self.env) if self.env else None})
        # per-trial timeouts are enforced by run_trial on the worker; the
        # cohort as a whole gets no deadline (a queue longer than the
        # worker count must not fail legitimate trials)
        outcomes = self.scheduler.map(payloads)
        fits = []
        for c, out in zip(chromos, outcomes):
            self.trials += 1
            fit = self._fitness_from(out.get("results"), out.get("error"))
            if fit > -float("inf") and not self.silent:
                print("trial %d (worker %s): %s -> fitness %.6f" % (
                    self.trials, out.get("worker"), c.config_snapshot,
                    fit))
            fits.append(fit)
        return fits

    def _fitness_from(self, result, error):
        """Shared result-JSON -> fitness conversion for the serial and
        scheduler paths."""
        if result is None:
            return self._trial_failed(error)
        try:
            value = float(result[self.fitness_key])
        except (KeyError, TypeError, ValueError):
            return self._trial_failed(
                "result JSON lacks numeric %r: %s"
                % (self.fitness_key, sorted(result)))
        return -value if self.minimize else value

    def _trial_failed(self, reason):
        self.failures += 1
        self._last_failure = reason
        if not self.silent:
            print("trial FAILED: %s" % reason, file=sys.stderr)
        return -float("inf")

    # -- driving -------------------------------------------------------------
    def run(self):
        """Evolve until max_generations (or, when None, until the
        population stops improving — Population.patience)."""
        evaluate_many = self._evaluate_many if self.scheduler else None
        while self.population.evolve(self._evaluate,
                                     evaluate_many=evaluate_many):
            if not self.silent:
                print("generation %d: best %.6f avg %.6f" % (
                    self.population.generation, self.population.best_fit,
                    self.population.average_fit))
        if self.population.best_fit == -float("inf"):
            # total failure must not masquerade as an optimization result
            # (the reference raised EvaluationError per failed chromosome)
            raise RuntimeError(
                "all %d trials failed; last failure: %s" %
                (self.trials, self._last_failure))
        return self.best

    @property
    def best(self):
        b = self.population.best
        return {"fitness": b.fitness,
                "assignments": self.overrides_for(b),
                "generations": self.population.generation,
                "trials": self.trials}


def optimize(model=None, **kwargs):
    """One-call API: build the optimizer, run it, return the best."""
    return GeneticsOptimizer(model, **kwargs).run()
