"""The fleet front router: one logical service over N replicas.

A byte-level HTTP proxy (it never parses request payloads — routing
must stay cheap next to model latency) in front of the replica set,
following the one-service-many-single-host-processes discipline of the
multi-host TPU serving literature (arXiv 2112.09017, ROADMAP item 2):

- **least-loaded dispatch**: a background thread polls every replica's
  ``GET /readyz`` (readiness + the schedulers' load snapshot) at
  ``poll_interval``; each request is forwarded to the admitting, ready
  replica with the lowest score = router-side in-flight count + queue
  utilization + KV occupancy.  The router's own in-flight counter moves
  per request, so burst skew is corrected between polls;
- **exactly-once retry**: inference requests are idempotent (pure
  functions of the payload), so a request that fails at the
  *connection* level — the replica died mid-flight — is retried ONCE
  against a different replica and the first replica is marked down
  immediately (the poll thread revives it after respawn).  Replica
  HTTP statuses (429 backpressure included) pass through untouched:
  shed is a replica decision, not a router retry;
- **merged control plane**: ``/healthz`` (router liveness + per-replica
  up/ready/admitting), ``/readyz`` (200 iff ≥1 replica is ready),
  ``/models`` (union of the replicas' registries), ``/metrics``
  (router dispatch/retry counters + every replica's own snapshot) —
  plus ``veles_fleet_*`` series in the process-global registry;
- **trace propagation**: every request runs in a ``fleet.route`` span
  (trace id from the client's ``X-Trace-Id`` or fresh) and the id is
  forwarded, so the merged Chrome trace reads router → replica request
  → ``serving.batch`` under one trace id.
"""

import http.client
import json
import socket
import threading
import time
from http.server import ThreadingHTTPServer

from ..httpjson import JsonRequestHandler
from ..logger import events
from ..observability import trace as _trace
from ..observability.registry import REGISTRY

#: connection-level failures that mark a replica down and allow the
#: one retry; anything the replica ANSWERED is passed through instead
_DISPATCH_ERRORS = (OSError, http.client.HTTPException)


def get_json(host, port, path, timeout=2.0, method="GET", body=None):
    """One short-lived JSON request to a replica (poll/merge paths —
    the proxy hot path keeps persistent connections instead)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        headers = {}
        if body is not None:
            body = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        conn.request(method, path, body, headers)
        resp = conn.getresponse()
        data = resp.read()
        return resp.status, json.loads(data) if data else None
    finally:
        conn.close()


class _Replica:
    """Router-side view of one replica."""

    __slots__ = ("id", "host", "port", "up", "ready", "admitting",
                 "inflight", "load", "generation")

    def __init__(self, rid, host, port):
        self.id = rid
        self.host = host
        self.port = port
        self.up = False
        self.ready = False
        self.admitting = True       # rollout drain flips this off
        self.inflight = 0
        self.load = {}
        self.generation = 0         # bumps on re-register (respawn)

    def score(self):
        """Lower = less loaded.  In-flight dominates (it is exact and
        instant); the polled queue/KV signals break ties and catch
        pressure the router did not itself create."""
        s = float(self.inflight)
        for model_load in (self.load or {}).values():
            s += float(model_load.get("utilization") or 0.0)
            s += float(model_load.get("kv_occupancy") or 0.0)
        return s

    def describe(self):
        return {"host": self.host, "port": self.port, "up": self.up,
                "ready": self.ready, "admitting": self.admitting,
                "inflight": self.inflight, "load": self.load}


class _RouterHandler(JsonRequestHandler):
    server_ref = None
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True
    timeout = 60

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path != "/api" and not path.startswith("/api/"):
            self.send_json(404, {"error": "not found"})
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        router = self.server_ref
        with _trace.span_context(
                trace_id=self.headers.get("X-Trace-Id") or None) as ctx:
            t0 = time.perf_counter()
            status, rid, retried = router.dispatch(self, path, body, ctx)
            events.span("fleet.route", time.perf_counter() - t0,
                        replica=rid, status=status, retried=retried,
                        path=path)

    def do_GET(self):
        router = self.server_ref
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/healthz":
            self.send_json(200, router.health())
        elif path == "/readyz":
            ready = router.ready_count() > 0
            self.send_json(200 if ready else 503,
                           {"ready": ready,
                            "ready_replicas": router.ready_count(),
                            "replicas": len(router.replica_ids())})
        elif path == "/models":
            self.send_json(200, router.merged_models())
        elif path == "/metrics":
            self.send_json(200, router.merged_metrics())
        else:
            self.send_json(404, {"error": "not found"})


class FleetRouter:
    """Least-loaded HTTP front end over registered replicas.

    Replicas are registered by the supervisor (:meth:`add_replica`) —
    the router never spawns processes; it only watches, scores, and
    forwards.  Usable standalone against hand-started replicas too.
    """

    def __init__(self, port=0, host="127.0.0.1", poll_interval=0.2,
                 request_timeout=60.0, registry=None):
        self.request_timeout = float(request_timeout)
        self.poll_interval = float(poll_interval)
        self._replicas = {}
        self._lock = threading.Lock()
        self._rr = 0                    # tie-break rotation
        self._tl = threading.local()    # per-thread persistent conns
        registry = registry or REGISTRY
        self._g_up = registry.gauge(
            "veles_fleet_replica_up",
            "1 while the replica answers its readiness poll",
            ("replica",))
        self._g_ready = registry.gauge(
            "veles_fleet_replica_ready",
            "1 while the replica reports ready (warmup ladder done, "
            "not draining)", ("replica",))
        self._c_dispatch = registry.counter(
            "veles_fleet_dispatch_total",
            "Requests forwarded to the replica", ("replica",))
        self._c_retry = registry.counter(
            "veles_fleet_retries_total",
            "Requests retried on another replica after a dead one",
            ("replica",))
        self._c_no_replica = registry.counter(
            "veles_fleet_no_replica_total",
            "Requests shed because no ready replica was available")
        handler = type("Handler", (_RouterHandler,),
                       {"server_ref": self})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.block_on_close = False
        self.host = host
        self.port = self._httpd.server_address[1]
        self._closed = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="veles-fleet-router")
        self._thread.start()
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True,
            name="veles-fleet-router-poll")
        self._poller.start()

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    # -- replica set ---------------------------------------------------------
    def add_replica(self, rid, host, port):
        """(Re-)register a replica; a respawn re-registers the same id
        with its new port and starts not-ready until the poll sees it."""
        with self._lock:
            prior = self._replicas.get(rid)
            rep = _Replica(rid, host, int(port))
            if prior is not None:
                rep.admitting = prior.admitting
                rep.generation = prior.generation + 1
            self._replicas[rid] = rep
        self._g_up.labels(replica=rid).set(0)
        self._g_ready.labels(replica=rid).set(0)
        self._probe(rep)            # first state without poll latency
        return rep

    def remove_replica(self, rid):
        with self._lock:
            rep = self._replicas.pop(rid, None)
        if rep is not None:
            self._g_up.labels(replica=rid).set(0)
            self._g_ready.labels(replica=rid).set(0)
        return rep is not None

    def replica_ids(self):
        with self._lock:
            return list(self._replicas)

    def replica(self, rid):
        with self._lock:
            return self._replicas.get(rid)

    def ready_count(self):
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.up and r.ready)

    def set_admitting(self, rid, admitting):
        """Rollout drain control: an un-admitting replica gets no NEW
        dispatches but keeps its in-flight ones (watch ``inflight``)."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.admitting = bool(admitting)

    def mark_down(self, rid):
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.up = rep.ready = False
        self._g_up.labels(replica=rid).set(0)
        self._g_ready.labels(replica=rid).set(0)

    # -- health polling ------------------------------------------------------
    def _probe(self, rep):
        try:
            status, body = get_json(rep.host, rep.port, "/readyz",
                                    timeout=max(self.poll_interval * 4,
                                                1.0))
        except _DISPATCH_ERRORS + (ValueError,):
            rep.up = rep.ready = False
        else:
            rep.up = True
            rep.ready = status == 200 and bool(
                isinstance(body, dict) and body.get("ready"))
            if isinstance(body, dict):
                rep.load = body.get("load") or {}
        self._g_up.labels(replica=rep.id).set(int(rep.up))
        self._g_ready.labels(replica=rep.id).set(int(rep.ready))

    def _poll_loop(self):
        while not self._closed:
            self.refresh()
            time.sleep(self.poll_interval)

    def refresh(self):
        """Probe every replica NOW (the poll loop's body; also called
        synchronously when dispatch finds no candidate, so a request
        arriving right after a replica turned ready — or right after
        the last candidate died — sees fresh state instead of a stale
        503)."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if self._closed:
                return
            self._probe(rep)

    # -- dispatch ------------------------------------------------------------
    def pick(self, exclude=()):
        with self._lock:
            candidates = [r for r in self._replicas.values()
                          if r.up and r.ready and r.admitting
                          and r.id not in exclude]
            if not candidates:
                return None
            best = min(r.score() for r in candidates)
            ties = [r for r in candidates if r.score() == best]
            # round-robin among equally-loaded replicas: a light load
            # must not pin itself to whichever replica sorts first
            rep = ties[self._rr % len(ties)]
            self._rr += 1
            rep.inflight += 1   # reserve under the lock (burst-safe)
            return rep

    def _conn_for(self, rep):
        conns = getattr(self._tl, "conns", None)
        if conns is None:
            conns = self._tl.conns = {}
        key = (rep.id, rep.generation)
        conn = conns.get(key)
        if conn is None:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.request_timeout)
            conn.connect()
            conn.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
            conns[key] = conn
        return key, conn

    def _forward(self, rep, path, body, headers):
        key, conn = self._conn_for(rep)
        try:
            conn.request("POST", path, body, headers)
            resp = conn.getresponse()
            data = resp.read()
        except _DISPATCH_ERRORS:
            conn.close()
            self._tl.conns.pop(key, None)
            raise
        return resp.status, resp.getheaders(), data

    def dispatch(self, handler, path, body, ctx):
        """Forward one request; writes the response through ``handler``.
        Returns ``(status, replica_id, retried)`` for the route span."""
        headers = {"Content-Type": handler.headers.get("Content-Type")
                   or "application/json",
                   **_trace.http_headers(ctx)}
        tried = []
        for attempt in (0, 1):
            rep = self.pick(exclude=tried)
            if rep is None:
                self.refresh()      # stale view ≠ empty fleet
                rep = self.pick(exclude=tried)
            if rep is None:
                self._c_no_replica.inc()
                handler.send_json(
                    503, {"error": "no ready replica"},
                    headers={"Retry-After": "1",
                             **_trace.http_headers(ctx)})
                return 503, None, bool(tried)
            tried.append(rep.id)
            try:
                status, resp_headers, data = self._forward(
                    rep, path, body, headers)
            except _DISPATCH_ERRORS:
                # the replica died under us: it gets no new traffic
                # until the poll (or supervisor re-register) revives
                # it, and THIS request retries exactly once elsewhere
                self.mark_down(rep.id)
                self._c_retry.labels(replica=rep.id).inc()
                continue
            finally:
                with self._lock:
                    rep.inflight -= 1
            self._c_dispatch.labels(replica=rep.id).inc()
            self._respond(handler, status, resp_headers, data)
            return status, rep.id, attempt > 0
        handler.send_json(502, {"error": "dispatch failed on %d "
                                "replicas" % len(tried),
                                "replicas": tried},
                          headers=_trace.http_headers(ctx))
        return 502, tried[-1] if tried else None, True

    @staticmethod
    def _respond(handler, status, resp_headers, data):
        """Pass a replica answer through byte-for-byte (429 Retry-After
        and trace headers included)."""
        handler.send_response(status)
        passed = {"content-type", "retry-after", "x-trace-id"}
        for name, value in resp_headers or ():
            if name.lower() in passed:
                handler.send_header(name, value)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    # -- merged control plane ------------------------------------------------
    def health(self):
        with self._lock:
            reps = {rid: rep.describe()
                    for rid, rep in self._replicas.items()}
        return {"status": "ok", "replicas": reps,
                "ready_replicas": sum(1 for r in reps.values()
                                      if r["up"] and r["ready"])}

    def merged_models(self):
        """Union of the replicas' ``/models`` — per-model, per-replica
        (versions differ mid-rollout, and that must be visible)."""
        out = {"models": {}, "replicas": {}}
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if not rep.up:
                continue
            try:
                _, body = get_json(rep.host, rep.port, "/models",
                                   timeout=2.0)
            except _DISPATCH_ERRORS + (ValueError,):
                continue
            if not isinstance(body, dict):
                continue
            out["replicas"][rep.id] = body
            for name, desc in body.items():
                out["models"].setdefault(name, {})[rep.id] = {
                    "version": desc.get("version"),
                    "ready": desc.get("ready")}
        return out

    def merged_metrics(self):
        """Router counters + every live replica's own /metrics."""
        with self._lock:
            reps = list(self._replicas.values())
        router = {"replicas": {}, "no_replica_sheds":
                  int(self._c_no_replica.value)}
        merged = {"router": router, "replicas": {}}
        for rep in reps:
            router["replicas"][rep.id] = {
                "up": rep.up, "ready": rep.ready,
                "admitting": rep.admitting, "inflight": rep.inflight,
                "dispatched": int(
                    self._c_dispatch.labels(replica=rep.id).value),
                "retries": int(
                    self._c_retry.labels(replica=rep.id).value),
            }
            if rep.up:
                try:
                    _, body = get_json(rep.host, rep.port, "/metrics",
                                       timeout=2.0)
                    merged["replicas"][rep.id] = body
                except _DISPATCH_ERRORS + (ValueError,):
                    merged["replicas"][rep.id] = {"error": "unreachable"}
        return merged

    def stop(self):
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
