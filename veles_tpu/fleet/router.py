"""The fleet front router: one logical service over N replicas.

A byte-level HTTP proxy (it never parses request payloads — routing
must stay cheap next to model latency) in front of the replica set,
following the one-service-many-single-host-processes discipline of the
multi-host TPU serving literature (arXiv 2112.09017, ROADMAP item 2):

- **least-loaded dispatch**: a background thread polls every replica's
  ``GET /readyz`` (readiness + the schedulers' load snapshot) at
  ``poll_interval``; each request is forwarded to the admitting, ready
  replica with the lowest score = router-side in-flight count + queue
  utilization + KV occupancy.  The router's own in-flight counter moves
  per request, so burst skew is corrected between polls;
- **exactly-once retry**: inference requests are idempotent (pure
  functions of the payload), so a request that fails at the
  *connection* level — the replica died mid-flight — is retried ONCE
  against a different replica and the first replica is marked down
  immediately (the poll thread revives it after respawn).  The retry
  window closes the moment any response byte reaches the client:
  small bodies (``Content-Length`` ≤ ``stream_threshold``) are fully
  buffered first, so a mid-body death is still retryable; larger
  bodies stream, and a mid-stream death ABORTS the client connection
  (a truncated answer must read as an error, never as a silent
  double-send).  Replica HTTP statuses (429 backpressure included)
  pass through untouched: shed is a replica decision, not a router
  retry;
- **circuit breaking**: ``breaker_threshold`` consecutive
  connection-level failures trip a replica's breaker open — no traffic
  until the health poll doubles as the half-open probe
  (open → half-open after ``breaker_cooldown``, half-open → closed on
  the next answered poll).  A replica that flaps on reconnect stops
  eating the retry budget of every request;
- **deadlines**: a client ``X-Deadline-Ms`` header (REMAINING budget in
  milliseconds — relative, so no cross-process clocks) is parsed once,
  checked before every dispatch leg (expired → 504 without touching a
  replica), and re-emitted with the budget left so the replica's
  scheduler can shed queued work that can no longer make it;
- **session affinity**: ``X-Session-Id`` pins follow-up requests to the
  replica that owns the live session (affinity survives a drain —
  ``prefer`` bypasses only the admitting flag).  A replica answering
  307 + ``X-Veles-Migrated`` means the session moved mid-flight; the
  router follows to ``X-Veles-Session-Target`` with ``X-Veles-Attach``
  so the client transparently gets the full answer from the new home;
- **merged control plane**: ``/healthz`` (router liveness + per-replica
  up/ready/admitting/breaker), ``/readyz`` (200 iff ≥1 replica is
  ready), ``/models`` (union of the replicas' registries), ``/metrics``
  (router dispatch/retry/breaker counters, every replica's own
  snapshot, and the supervisor's restart-budget view when wired) —
  plus ``veles_fleet_*`` series in the process-global registry;
- **trace propagation**: every request runs in a ``fleet.route`` span
  (trace id from the client's ``X-Trace-Id`` or fresh) and the id is
  forwarded, so the merged Chrome trace reads router → replica request
  → ``serving.batch`` under one trace id.
"""

import collections
import http.client
import json
import socket
import threading
import time
import urllib.parse
from http.server import ThreadingHTTPServer

from ..httpjson import JsonRequestHandler
from ..kvtier import PREFIX_HEADER, PrefixDirectory
from ..logger import events
from ..observability import trace as _trace
from ..observability.flight import RECORDER as _flight
from ..observability.registry import REGISTRY

#: connection-level failures that mark a replica down and allow the
#: one retry; anything the replica ANSWERED is passed through instead
_DISPATCH_ERRORS = (OSError, http.client.HTTPException)

#: breaker states → gauge values (monotone in badness)
_BREAKER_GAUGE = {"closed": 0, "half_open": 1, "open": 2}

#: sentinel: the response already streamed through to the client
_STREAMED = object()


class ResponseAborted(Exception):
    """Bytes already reached the client when the replica connection
    died — the response can neither be retried (double-send) nor
    completed (truncated); the only honest move is closing the client
    socket so the truncation reads as a transport error."""


class _Truncated(Exception):
    """The replica connection died mid-body BEFORE any byte reached
    the client (fully-buffered small response) — retryable."""


def get_json(host, port, path, timeout=2.0, method="GET", body=None):
    """One short-lived JSON request to a replica (poll/merge paths —
    the proxy hot path keeps persistent connections instead)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        headers = {}
        if body is not None:
            body = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        conn.request(method, path, body, headers)
        resp = conn.getresponse()
        data = resp.read()
        return resp.status, json.loads(data) if data else None
    finally:
        conn.close()


class _Replica:
    """Router-side view of one replica."""

    __slots__ = ("id", "host", "port", "up", "ready", "admitting",
                 "inflight", "load", "generation", "fail_streak",
                 "breaker", "breaker_opened_at")

    def __init__(self, rid, host, port):
        self.id = rid
        self.host = host
        self.port = port
        self.up = False
        self.ready = False
        self.admitting = True       # rollout drain flips this off
        self.inflight = 0
        self.load = {}
        self.generation = 0         # bumps on re-register (respawn)
        self.fail_streak = 0        # consecutive connection failures
        self.breaker = "closed"     # closed | open | half_open
        self.breaker_opened_at = 0.0

    def score(self):
        """Lower = less loaded.  In-flight dominates (it is exact and
        instant); the polled queue/KV signals break ties and catch
        pressure the router did not itself create."""
        s = float(self.inflight)
        for model_load in (self.load or {}).values():
            s += float(model_load.get("utilization") or 0.0)
            s += float(model_load.get("kv_occupancy") or 0.0)
        return s

    def describe(self):
        return {"host": self.host, "port": self.port, "up": self.up,
                "ready": self.ready, "admitting": self.admitting,
                "inflight": self.inflight, "load": self.load,
                "breaker": self.breaker,
                "fail_streak": self.fail_streak}


class _RouterHandler(JsonRequestHandler):
    server_ref = None
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True
    # reap idle keep-alive connections; overridden per router from
    # request_timeout (single source of truth — see FleetRouter)
    timeout = 60

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path != "/api" and not path.startswith("/api/"):
            self.send_json(404, {"error": "not found"})
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        router = self.server_ref
        with _trace.span_context(
                trace_id=self.headers.get("X-Trace-Id") or None) as ctx:
            t0 = time.perf_counter()
            status, rid, retried = router.dispatch(self, path, body, ctx)
            events.span("fleet.route", time.perf_counter() - t0,
                        replica=rid, status=status, retried=retried,
                        path=path)

    def do_GET(self):
        router = self.server_ref
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/healthz":
            self.send_json(200, router.health())
        elif path == "/readyz":
            ready = router.ready_count() > 0
            self.send_json(200 if ready else 503,
                           {"ready": ready,
                            "ready_replicas": router.ready_count(),
                            "replicas": len(router.replica_ids())})
        elif path == "/models":
            self.send_json(200, router.merged_models())
        elif path == "/metrics":
            self.send_json(200, router.merged_metrics())
        elif path == "/fleet/kv":
            query = urllib.parse.parse_qs(
                urllib.parse.urlparse(self.path).query)
            key = (query.get("key") or [None])[0]
            self.send_json(200, router.fleet_kv(key))
        elif path == "/fleet/requests":
            query = urllib.parse.parse_qs(
                urllib.parse.urlparse(self.path).query)
            rid = (query.get("id") or [None])[0]
            self.send_json(200, router.fleet_requests(rid))
        else:
            self.send_json(404, {"error": "not found"})


class FleetRouter:
    """Least-loaded HTTP front end over registered replicas.

    Replicas are registered by the supervisor (:meth:`add_replica`) —
    the router never spawns processes; it only watches, scores, and
    forwards.  Usable standalone against hand-started replicas too.
    """

    #: bound on 307 migration follows per request (a follow is not a
    #: retry: the source ANSWERED; it just answered "moved")
    max_follows = 4

    def __init__(self, port=0, host="127.0.0.1", poll_interval=0.2,
                 request_timeout=60.0, registry=None,
                 breaker_threshold=3, breaker_cooldown=1.0,
                 stream_threshold=65536):
        self.request_timeout = float(request_timeout)
        self.poll_interval = float(poll_interval)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown = float(breaker_cooldown)
        self.stream_threshold = int(stream_threshold)
        self._replicas = {}
        self._lock = threading.Lock()
        self._rr = 0                    # tie-break rotation
        self._tl = threading.local()    # per-thread persistent conns
        # session id → owning replica id (LRU-bounded)
        self._affinity = collections.OrderedDict()
        self._affinity_cap = 4096
        # wired by Fleet to supervisor.describe — restart budgets show
        # up in the one merged /metrics payload operators already poll
        self.supervisor_info = None
        registry = registry or REGISTRY
        self._g_up = registry.gauge(
            "veles_fleet_replica_up",
            "1 while the replica answers its readiness poll",
            ("replica",))
        self._g_ready = registry.gauge(
            "veles_fleet_replica_ready",
            "1 while the replica reports ready (warmup ladder done, "
            "not draining)", ("replica",))
        self._g_breaker = registry.gauge(
            "veles_fleet_breaker_state",
            "Circuit breaker: 0 closed, 1 half-open, 2 open",
            ("replica",))
        self._c_dispatch = registry.counter(
            "veles_fleet_dispatch_total",
            "Requests forwarded to the replica", ("replica",))
        self._c_retry = registry.counter(
            "veles_fleet_retries_total",
            "Requests retried on another replica after a dead one",
            ("replica",))
        self._c_no_replica = registry.counter(
            "veles_fleet_no_replica_total",
            "Requests shed because no ready replica was available")
        self._c_expired = registry.counter(
            "veles_fleet_deadline_expired_total",
            "Requests shed at the router because their X-Deadline-Ms "
            "budget ran out before a replica could answer")
        self._c_truncated = registry.counter(
            "veles_fleet_truncated_total",
            "Buffered replica responses that died mid-body (retried "
            "safely: no client byte had been written)", ("replica",))
        self._c_aborted = registry.counter(
            "veles_fleet_aborted_total",
            "Streamed responses aborted mid-body — client connection "
            "closed instead of retrying (exactly-once)", ("replica",))
        self._c_breaker = registry.counter(
            "veles_fleet_breaker_trips_total",
            "Times the replica's circuit breaker opened", ("replica",))
        self._c_follow = registry.counter(
            "veles_fleet_session_follows_total",
            "307 migration redirects followed to a session's new home")
        # fleet-wide prefix directory (veles_tpu/kvtier): replicas
        # advertise resident chain keys in their /readyz load payload;
        # requests carrying X-Veles-Prefix-Keys are steered to the
        # replica holding the longest resident run of them
        self.prefix_directory = PrefixDirectory()
        self._c_aff_hit = registry.counter(
            "veles_fleet_affinity_hits_total",
            "Requests routed to the replica holding the longest "
            "resident prefix of their prompt chain")
        self._c_aff_fallback = registry.counter(
            "veles_fleet_affinity_fallbacks_total",
            "Requests that carried prefix keys but fell back to "
            "least-loaded (no eligible replica held any of them)")
        handler = type("Handler", (_RouterHandler,),
                       {"server_ref": self,
                        "timeout": max(self.request_timeout, 1.0)})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.block_on_close = False
        self.host = host
        self.port = self._httpd.server_address[1]
        self._closed = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="veles-fleet-router")
        self._thread.start()
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True,
            name="veles-fleet-router-poll")
        self._poller.start()

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    # -- replica set ---------------------------------------------------------
    def add_replica(self, rid, host, port):
        """(Re-)register a replica; a respawn re-registers the same id
        with its new port and starts not-ready until the poll sees it."""
        with self._lock:
            prior = self._replicas.get(rid)
            rep = _Replica(rid, host, int(port))
            if prior is not None:
                rep.admitting = prior.admitting
                rep.generation = prior.generation + 1
            self._replicas[rid] = rep
        self._g_up.labels(replica=rid).set(0)
        self._g_ready.labels(replica=rid).set(0)
        self._g_breaker.labels(replica=rid).set(0)
        self._probe(rep)            # first state without poll latency
        return rep

    def remove_replica(self, rid):
        with self._lock:
            rep = self._replicas.pop(rid, None)
        if rep is not None:
            self._g_up.labels(replica=rid).set(0)
            self._g_ready.labels(replica=rid).set(0)
            self.prefix_directory.drop(rid)
        return rep is not None

    def replica_ids(self):
        with self._lock:
            return list(self._replicas)

    def replica(self, rid):
        with self._lock:
            return self._replicas.get(rid)

    def ready_count(self):
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.up and r.ready)

    def set_admitting(self, rid, admitting):
        """Rollout drain control: an un-admitting replica gets no NEW
        dispatches but keeps its in-flight ones (watch ``inflight``);
        session-affine requests still reach it (``prefer``) until the
        supervisor migrates its sessions away."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.admitting = bool(admitting)

    def mark_down(self, rid):
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.up = rep.ready = False
        self._g_up.labels(replica=rid).set(0)
        self._g_ready.labels(replica=rid).set(0)

    # -- session affinity ----------------------------------------------------
    def note_session_home(self, sid, rid):
        """Record (or move) a session's owning replica."""
        with self._lock:
            self._affinity.pop(sid, None)
            self._affinity[sid] = rid
            while len(self._affinity) > self._affinity_cap:
                self._affinity.popitem(last=False)

    def _session_home(self, sid):
        with self._lock:
            return self._affinity.get(sid)

    def _replica_at(self, hostport):
        """Map a ``host:port`` migration target to a replica id."""
        if not hostport:
            return None
        host, _, port = str(hostport).rpartition(":")
        try:
            port = int(port)
        except ValueError:
            return None
        with self._lock:
            for rep in self._replicas.values():
                if rep.port == port and (not host or rep.host == host):
                    return rep.id
        return None

    # -- circuit breaker -----------------------------------------------------
    def _note_failure(self, rep):
        """A connection-level dispatch failure: grow the streak; at
        ``breaker_threshold`` consecutive failures the breaker opens
        and the replica gets no traffic until its half-open probe
        passes (a half-open failure re-opens immediately)."""
        with self._lock:
            rep.fail_streak += 1
            tripped = (rep.breaker == "half_open"
                       or (rep.breaker == "closed"
                           and rep.fail_streak >= self.breaker_threshold))
            if tripped:
                rep.breaker = "open"
                rep.breaker_opened_at = time.monotonic()
        if tripped:
            self._c_breaker.labels(replica=rep.id).inc()
            self._g_breaker.labels(replica=rep.id).set(2)
            events.event("fleet.breaker_open", replica=rep.id,
                         streak=rep.fail_streak)

    def _note_success(self, rep):
        with self._lock:
            reopened = rep.breaker != "closed"
            rep.fail_streak = 0
            rep.breaker = "closed"
        if reopened:
            self._g_breaker.labels(replica=rep.id).set(0)

    def _breaker_probe(self, rep):
        """The health poll IS the half-open probe: open → half-open
        once the cooldown elapsed and the replica answered its poll,
        half-open → closed on the NEXT answered poll (two consecutive
        good polls before traffic returns)."""
        now = time.monotonic()
        with self._lock:
            if rep.breaker == "open" and \
                    now - rep.breaker_opened_at >= self.breaker_cooldown:
                rep.breaker = "half_open"
            elif rep.breaker == "half_open":
                rep.breaker = "closed"
                rep.fail_streak = 0
            else:
                return
            state = rep.breaker
        self._g_breaker.labels(replica=rep.id).set(_BREAKER_GAUGE[state])
        if state == "closed":
            events.event("fleet.breaker_closed", replica=rep.id)

    # -- health polling ------------------------------------------------------
    def _probe(self, rep):
        try:
            status, body = get_json(rep.host, rep.port, "/readyz",
                                    timeout=max(self.poll_interval * 4,
                                                1.0))
        except _DISPATCH_ERRORS + (ValueError,):
            rep.up = rep.ready = False
            with self._lock:
                if rep.breaker == "half_open":
                    rep.breaker = "open"
                    rep.breaker_opened_at = time.monotonic()
            self._g_breaker.labels(replica=rep.id).set(
                _BREAKER_GAUGE[rep.breaker])
        else:
            rep.up = True
            rep.ready = status == 200 and bool(
                isinstance(body, dict) and body.get("ready"))
            if isinstance(body, dict):
                rep.load = body.get("load") or {}
                # resident-chain advertisement piggybacked on the load
                # poll: merge every model's kv_tiers into the fleet
                # prefix directory (an answer without any clears stale
                # entries — the replica restarted tierless)
                tiers = {}
                for model_load in rep.load.values():
                    adv = (model_load or {}).get("kv_tiers")
                    if not isinstance(adv, dict):
                        continue
                    for tier, keys in adv.items():
                        tiers.setdefault(tier, []).extend(keys or ())
                self.prefix_directory.update(rep.id, tiers)
            self._breaker_probe(rep)
        self._g_up.labels(replica=rep.id).set(int(rep.up))
        self._g_ready.labels(replica=rep.id).set(int(rep.ready))

    def _poll_loop(self):
        while not self._closed:
            self.refresh()
            time.sleep(self.poll_interval)

    def refresh(self):
        """Probe every replica NOW (the poll loop's body; also called
        synchronously when dispatch finds no candidate, so a request
        arriving right after a replica turned ready — or right after
        the last candidate died — sees fresh state instead of a stale
        503)."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if self._closed:
                return
            self._probe(rep)

    # -- dispatch ------------------------------------------------------------
    def pick(self, exclude=(), prefer=None):
        """Least-loaded admitting replica; ``prefer`` names the
        session-affine home, honored even while it is DRAINING (only
        up/ready/breaker gate it — a drain must not orphan sessions
        mid-migration)."""
        with self._lock:
            if prefer is not None and prefer not in exclude:
                rep = self._replicas.get(prefer)
                if rep is not None and rep.up and rep.ready \
                        and rep.breaker == "closed":
                    rep.inflight += 1
                    return rep
            candidates = [r for r in self._replicas.values()
                          if r.up and r.ready and r.admitting
                          and r.breaker == "closed"
                          and r.id not in exclude]
            if not candidates:
                return None
            best = min(r.score() for r in candidates)
            ties = [r for r in candidates if r.score() == best]
            # round-robin among equally-loaded replicas: a light load
            # must not pin itself to whichever replica sorts first
            rep = ties[self._rr % len(ties)]
            self._rr += 1
            rep.inflight += 1   # reserve under the lock (burst-safe)
            return rep

    def _conn_for(self, rep):
        conns = getattr(self._tl, "conns", None)
        if conns is None:
            conns = self._tl.conns = {}
        key = (rep.id, rep.generation)
        conn = conns.get(key)
        if conn is None:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.request_timeout)
            conn.connect()
            conn.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
            conns[key] = conn
        return key, conn

    def _drop_conn(self, key):
        conns = getattr(self._tl, "conns", None)
        if conns is not None:
            conn = conns.pop(key, None)
            if conn is not None:
                conn.close()

    def _forward(self, rep, path, body, headers, handler):
        """One proxy leg.  Buffered responses return
        ``(status, headers, data)``; large responses stream straight
        through and return ``(status, headers, _STREAMED)``.

        Raises: ``_DISPATCH_ERRORS`` before the replica answered
        (retryable), :class:`_Truncated` when a buffered body died
        before any client byte (retryable), :class:`ResponseAborted`
        when the client already saw bytes (NOT retryable)."""
        key, conn = self._conn_for(rep)
        try:
            conn.request("POST", path, body, headers)
            resp = conn.getresponse()
        except _DISPATCH_ERRORS:
            self._drop_conn(key)
            raise
        length = resp.getheader("Content-Length")
        try:
            length = int(length) if length is not None else None
        except ValueError:
            length = None
        if length is not None and length <= self.stream_threshold:
            try:
                data = resp.read()
            except _DISPATCH_ERRORS as exc:
                self._drop_conn(key)
                raise _Truncated() from exc
            if len(data) != length:
                self._drop_conn(key)
                raise _Truncated()
            return resp.status, resp.getheaders(), data
        # streaming: the status line reaches the client immediately, so
        # any failure past this point is an abort, never a retry
        resp_headers = resp.getheaders()
        handler.send_response(resp.status)
        passed = {"content-type", "retry-after", "x-trace-id"}
        for name, value in resp_headers or ():
            if name.lower() in passed:
                handler.send_header(name, value)
        if length is not None:
            handler.send_header("Content-Length", str(length))
        else:
            # unsized upstream body: delimit by closing the connection
            handler.send_header("Connection", "close")
            handler.close_connection = True
        handler.end_headers()
        sent = 0
        try:
            while True:
                chunk = resp.read(65536)
                if not chunk:
                    break
                handler.wfile.write(chunk)
                sent += len(chunk)
        except _DISPATCH_ERRORS as exc:
            self._drop_conn(key)
            raise ResponseAborted() from exc
        if length is not None and sent != length:
            self._drop_conn(key)
            raise ResponseAborted()
        return resp.status, resp_headers, _STREAMED

    @staticmethod
    def _parse_deadline(handler):
        """Client ``X-Deadline-Ms`` (remaining budget) → absolute
        monotonic deadline, or None."""
        raw = handler.headers.get("X-Deadline-Ms")
        if not raw:
            return None
        try:
            ms = float(raw)
        except ValueError:
            return None
        return time.monotonic() + max(ms, 0.0) / 1e3

    def _affinity_pick(self, handler):
        """Cache-aware routing: map the request's X-Veles-Prefix-Keys
        (the prompt's chain keys, leading blocks first) to the replica
        holding the longest resident run of them.  Only *biases* the
        first dispatch leg among currently-eligible replicas — unlike
        session affinity it respects the admitting flag, and a holder
        that is down/draining degrades to least-loaded (counted as a
        fallback, never a failure)."""
        raw = handler.headers.get(PREFIX_HEADER)
        if not raw:
            return None
        keys = [k.strip() for k in raw.split(",") if k.strip()]
        if not keys:
            return None
        with self._lock:
            eligible = {r.id for r in self._replicas.values()
                        if r.up and r.ready and r.admitting
                        and r.breaker == "closed"}
        rid, matched = self.prefix_directory.best_replica(
            keys, candidates=eligible)
        if rid is not None and matched:
            self._c_aff_hit.inc()
            return rid
        self._c_aff_fallback.inc()
        return None

    def _retry_budget(self):
        """Connection-level legs allowed per request: one per known
        replica (min 2).  Retrying is always safe here — a leg that
        wrote ANY client byte ends in :class:`ResponseAborted`, not a
        retry — so the budget is about not looping forever, not about
        duplicate answers."""
        return max(2, len(self._replicas))

    def dispatch(self, handler, path, body, ctx):
        """Forward one request; writes the response through ``handler``.
        Returns ``(status, replica_id, retried)`` for the route span."""
        headers = {"Content-Type": handler.headers.get("Content-Type")
                   or "application/json",
                   **_trace.http_headers(ctx)}
        sid = handler.headers.get("X-Session-Id") or None
        if sid:
            headers["X-Session-Id"] = sid
        tenant = handler.headers.get("X-Veles-Tenant")
        if tenant:
            headers["X-Veles-Tenant"] = tenant
            _flight.annotate(ctx.trace_id, tenant=tenant)
        deadline = self._parse_deadline(handler)
        tried = []
        retried = False
        follows = 0
        attach = False
        prefer = self._session_home(sid) if sid else None
        if prefer is None:
            prefer = self._affinity_pick(handler)
            if prefer is not None:
                _flight.record(ctx.trace_id, "router.affinity",
                               replica=prefer)
        rep = None
        while True:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # shed BEFORE a replica spends device time on an
                    # answer nobody is waiting for
                    self._c_expired.inc()
                    _flight.anomaly(ctx.trace_id, "deadline_504")
                    _flight.finish(ctx.trace_id, status="deadline_504")
                    handler.send_json(
                        504, {"error": "deadline expired"},
                        headers=_trace.http_headers(ctx))
                    return 504, rep.id if rep else None, retried
                headers["X-Deadline-Ms"] = str(
                    max(int(remaining * 1e3), 1))
            if attach:
                headers["X-Veles-Attach"] = "1"
            rep = self.pick(exclude=tried, prefer=prefer)
            if rep is None:
                self.refresh()      # stale view ≠ empty fleet
                rep = self.pick(exclude=tried, prefer=prefer)
            if rep is None:
                self._c_no_replica.inc()
                _flight.anomaly(ctx.trace_id, "shed_429",
                                detail="no_replica")
                _flight.finish(ctx.trace_id, status="no_replica")
                handler.send_json(
                    503, {"error": "no ready replica"},
                    headers={"Retry-After": "1",
                             **_trace.http_headers(ctx)})
                return 503, None, retried
            tried.append(rep.id)
            prefer = None
            _flight.record(ctx.trace_id, "router.dispatch",
                           replica=rep.id, attempt=len(tried))
            try:
                status, resp_headers, data = self._forward(
                    rep, path, body, headers, handler)
            except ResponseAborted:
                self._note_failure(rep)
                self.mark_down(rep.id)
                self._c_aborted.labels(replica=rep.id).inc()
                handler.close_connection = True
                return 499, rep.id, retried
            except _Truncated:
                self._note_failure(rep)
                self.mark_down(rep.id)
                self._c_truncated.labels(replica=rep.id).inc()
                if len(tried) < self._retry_budget():
                    self._c_retry.labels(replica=rep.id).inc()
                    _flight.record(ctx.trace_id, "router.retry",
                                   replica=rep.id, reason="truncated")
                    _flight.anomaly(ctx.trace_id, "retry")
                    retried = True
                    continue
                break
            except _DISPATCH_ERRORS:
                # the replica died under us: it gets no new traffic
                # until the poll (or supervisor re-register) revives
                # it, and THIS request retries on a peer — safe, since
                # not one response byte reached the client (the
                # streamed case raises ResponseAborted instead)
                self._note_failure(rep)
                self.mark_down(rep.id)
                if len(tried) < self._retry_budget():
                    self._c_retry.labels(replica=rep.id).inc()
                    _flight.record(ctx.trace_id, "router.retry",
                                   replica=rep.id, reason="connection")
                    _flight.anomaly(ctx.trace_id, "recovery_replay")
                    retried = True
                    continue
                break
            finally:
                with self._lock:
                    rep.inflight -= 1
            self._note_success(rep)
            self._c_dispatch.labels(replica=rep.id).inc()
            lower = {name.lower(): value
                     for name, value in (resp_headers or ())}
            moved = lower.get("x-veles-migrated")
            if status == 307 and moved and data is not _STREAMED \
                    and follows < self.max_follows:
                # the session migrated mid-request: follow to its new
                # home and re-attach — one answer, no client redirect
                follows += 1
                self._c_follow.inc()
                _flight.record(
                    ctx.trace_id, "router.follow", session=sid,
                    target=lower.get("x-veles-session-target"))
                sid = moved
                headers["X-Session-Id"] = sid
                attach = True
                prefer = self._replica_at(
                    lower.get("x-veles-session-target"))
                if prefer is not None:
                    self.note_session_home(sid, prefer)
                tried = []      # a follow is an answer, not a failure
                continue
            if sid and status == 200:
                self.note_session_home(sid, rep.id)
            if data is not _STREAMED:
                self._respond(handler, status, resp_headers, data)
            _flight.finish(ctx.trace_id,
                           status="ok" if status < 400
                           else "status_%d" % status)
            return status, rep.id, retried
        _flight.anomaly(ctx.trace_id, "error", detail="dispatch_failed")
        _flight.finish(ctx.trace_id, status="dispatch_failed")
        handler.send_json(502, {"error": "dispatch failed on %d "
                                "replicas" % len(tried),
                                "replicas": tried},
                          headers=_trace.http_headers(ctx))
        return 502, tried[-1] if tried else None, True

    @staticmethod
    def _respond(handler, status, resp_headers, data):
        """Pass a replica answer through byte-for-byte (429 Retry-After
        and trace headers included)."""
        handler.send_response(status)
        passed = {"content-type", "retry-after", "x-trace-id"}
        for name, value in resp_headers or ():
            if name.lower() in passed:
                handler.send_header(name, value)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    # -- merged control plane ------------------------------------------------
    def health(self):
        with self._lock:
            reps = {rid: rep.describe()
                    for rid, rep in self._replicas.items()}
        return {"status": "ok", "replicas": reps,
                "ready_replicas": sum(1 for r in reps.values()
                                      if r["up"] and r["ready"])}

    def merged_models(self):
        """Union of the replicas' ``/models`` — per-model, per-replica
        (versions differ mid-rollout, and that must be visible)."""
        out = {"models": {}, "replicas": {}}
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if not rep.up:
                continue
            try:
                _, body = get_json(rep.host, rep.port, "/models",
                                   timeout=2.0)
            except _DISPATCH_ERRORS + (ValueError,):
                continue
            if not isinstance(body, dict):
                continue
            out["replicas"][rep.id] = body
            for name, desc in body.items():
                out["models"].setdefault(name, {})[rep.id] = {
                    "version": desc.get("version"),
                    "ready": desc.get("ready")}
        return out

    def fleet_kv(self, key=None):
        """The ``GET /fleet/kv`` payload: with ``key=``, that chain
        key's tier residency per replica (hbm / host / disk / absent);
        without, the whole advertised directory plus the affinity
        counters — tools/kv_inspect.py --fleet renders both."""
        if key:
            residency = self.prefix_directory.residency(str(key))
            return {"key": str(key),
                    "replicas": {rid: residency.get(rid, "absent")
                                 for rid in self.replica_ids()}}
        return {"replicas": self.prefix_directory.snapshot(max_keys=64),
                "affinity_hits": int(self._c_aff_hit.value),
                "affinity_fallbacks": int(self._c_aff_fallback.value)}

    def fleet_requests(self, trace_id=None):
        """The ``GET /fleet/requests`` payload: flight-recorder
        timelines merged across the router and every live replica,
        grouped by trace id — one request's full cross-process story
        (tools/request_inspect.py renders it).  With ``id=``, only
        that trace."""
        path = "/api/requests"
        if trace_id:
            path += "?id=" + urllib.parse.quote(str(trace_id))
        merged = {}

        def _absorb(source, timelines):
            for tl in timelines or ():
                tid = tl.get("trace_id") if isinstance(tl, dict) \
                    else None
                if not tid:
                    continue
                tl.setdefault("replica", source)
                merged.setdefault(tid, []).append(tl)

        _absorb("router", _flight.snapshot(trace_id=trace_id))
        with self._lock:
            reps = list(self._replicas.values())
        stats = {"router": _flight.stats()}
        for rep in reps:
            if not rep.up:
                continue
            try:
                _, body = get_json(rep.host, rep.port, path,
                                   timeout=2.0)
            except _DISPATCH_ERRORS + (ValueError,):
                continue
            if not isinstance(body, dict):
                continue
            _absorb(rep.id, body.get("requests"))
            stats[rep.id] = body.get("flight")
        return {"requests": merged, "flight": stats}

    def merged_metrics(self):
        """Router counters + every live replica's own /metrics + the
        supervisor's restart-budget view (when wired by Fleet)."""
        with self._lock:
            reps = list(self._replicas.values())
        router = {"replicas": {},
                  "no_replica_sheds": int(self._c_no_replica.value),
                  "deadline_expired": int(self._c_expired.value),
                  "session_follows": int(self._c_follow.value),
                  "affinity_hits": int(self._c_aff_hit.value),
                  "affinity_fallbacks": int(
                      self._c_aff_fallback.value)}
        merged = {"router": router, "replicas": {}}
        for rep in reps:
            router["replicas"][rep.id] = {
                "up": rep.up, "ready": rep.ready,
                "admitting": rep.admitting, "inflight": rep.inflight,
                "breaker": rep.breaker,
                "fail_streak": rep.fail_streak,
                "breaker_trips": int(
                    self._c_breaker.labels(replica=rep.id).value),
                "dispatched": int(
                    self._c_dispatch.labels(replica=rep.id).value),
                "retries": int(
                    self._c_retry.labels(replica=rep.id).value),
                "truncated": int(
                    self._c_truncated.labels(replica=rep.id).value),
                "aborted": int(
                    self._c_aborted.labels(replica=rep.id).value),
            }
            if rep.up:
                try:
                    _, body = get_json(rep.host, rep.port, "/metrics",
                                       timeout=2.0)
                    merged["replicas"][rep.id] = body
                except _DISPATCH_ERRORS + (ValueError,):
                    merged["replicas"][rep.id] = {"error": "unreachable"}
        if self.supervisor_info is not None:
            try:
                merged["supervisor"] = self.supervisor_info()
            except Exception:  # noqa: BLE001 — metrics must not 500
                merged["supervisor"] = {"error": "unavailable"}
        return merged

    def stop(self):
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
