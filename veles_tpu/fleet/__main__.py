"""CLI: stand up a serving fleet — ``python -m veles_tpu.fleet``.

Example::

    python -m veles_tpu.fleet --model mnist=mnist_pkg.zip \\
        --replicas 3 --port 8080 --cache-dir /var/cache/veles

Blocks until SIGINT, then drains replicas gracefully.
"""

import argparse
import signal
import threading


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles_tpu.fleet",
        description="N serving replicas behind a least-loaded router "
                    "with rolling updates (see veles_tpu.fleet).")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=SPEC", dest="models", required=True,
                   help="package zip path or sleep:SECONDS[:DIM] "
                        "(repeatable)")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--port", type=int, default=8080,
                   help="router port (replicas pick free ports)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--queue-limit", type=int, default=256)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--cache-dir", default=None,
                   help="persistent executable cache shared by every "
                        "replica (warm spawns do zero XLA compiles)")
    p.add_argument("--seconds", type=float, default=None,
                   help="serve N seconds then drain and exit "
                        "(default: until SIGINT)")
    args = p.parse_args(argv)

    from . import Fleet
    models = {}
    for spec in args.models:
        name, _, model = spec.partition("=")
        models[name] = model or name
    fleet = Fleet(models, replicas=args.replicas,
                  router_port=args.port, host=args.host,
                  max_batch=args.max_batch,
                  queue_limit=args.queue_limit, workers=args.workers,
                  cache_dir=args.cache_dir)
    fleet.start()
    print("fleet: %d replicas ready behind %s (POST %s/api/<model>; "
          "GET %s/metrics)" % (args.replicas, fleet.url, fleet.url,
                               fleet.url))
    try:
        if args.seconds:
            threading.Event().wait(args.seconds)
        else:
            signal.sigwait({signal.SIGINT, signal.SIGTERM})
    except KeyboardInterrupt:
        pass
    finally:
        fleet.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
