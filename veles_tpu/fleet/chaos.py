"""Deterministic fault injection for fleet replicas.

Robustness claims ("zero failed responses through a SIGKILL", "a
truncated body is never silently double-sent") are only testable if
the faults themselves are REPRODUCIBLE.  This module scripts them: a
:class:`FaultPlan` is a seedable list of rules, serialized as JSON
into the ``VELES_FAULT_PLAN`` environment variable by the supervisor
(``fault_plans={rid: plan}``) and installed inside the replica
subprocess around its HTTP handler — the faults happen at the exact
transport seam the router talks to, not in a mock.

Rules trigger on the ordinal of DATA requests (anything under
``/api``; health, metrics, and admin traffic is exempt so the harness
itself — readiness polls, session migration — stays controllable while
the data plane burns).  A rule is a dict::

    {"at": 3,          # fire on exactly the 3rd data request, or
     "after": 5,       #   on every data request from the 5th on, or
     "every": 7,       #   on every 7th, or
     "probability": p, #   i.i.d. with the plan's seeded RNG
     "path_prefix": "/api/toy",  # optional: only on matching paths
     "action": ...}    # what happens (below)

A ``path_prefix`` narrows a rule to one route (e.g. SIGKILL on the
Nth *generate* call specifically, leaving other data traffic alone);
the ordinal ``n`` still counts every data request, so adding a
narrowed rule never shifts when the other rules fire.

Actions:

- ``latency`` (``seconds``): sleep before handling — added tail.
- ``refuse``: close the connection without a response — the peer sees
  a clean connection error (retryable at the router).
- ``blackhole`` (``seconds``): accept, read, then hold the connection
  open saying nothing — the slow-failure mode that only a deadline or
  socket timeout can cut short.
- ``truncate`` (``bytes``): let the handler answer but cut the
  response BODY after N bytes and close — the exactly-once drill (a
  buffered router retry is safe; a streamed one must abort).
- ``sigkill``: ``SIGKILL`` the replica process — the crash drill.
- ``sigstop`` (``resume_after``): ``SIGSTOP`` the process (hung, not
  dead: the socket stays open, accepts back up) and optionally have a
  detached helper ``SIGCONT`` it later — the gray-failure drill.

Every trigger is counted/ordered deterministically, so the same plan
against the same request sequence produces the same drill, run after
run.
"""

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

__all__ = ["FaultPlan", "install_from_env", "PLAN_ENV"]

#: the environment variable the supervisor plants plans in
PLAN_ENV = "VELES_FAULT_PLAN"

#: actions that replace the real handler entirely
_PREEMPT = ("refuse", "blackhole", "sigkill", "sigstop")

_KNOWN = ("latency", "refuse", "blackhole", "truncate", "sigkill",
          "sigstop")


class _TruncatingFile:
    """A ``wfile`` stand-in that passes the header block through and
    cuts the response BODY after ``limit`` bytes.

    ``BaseHTTPRequestHandler`` buffers the status line + headers and
    flushes them as one write ending ``\\r\\n\\r\\n``; everything after
    that terminator is body and counts against the limit.  Writes past
    the limit vanish, so the client sees fewer bytes than
    ``Content-Length`` promised, then EOF — a mid-body death."""

    def __init__(self, raw, limit):
        self._raw = raw
        self._limit = int(limit)
        self._in_body = False
        self._sent = 0
        self.truncated = False

    def write(self, data):
        data = bytes(data)
        if not self._in_body:
            head, sep, rest = data.partition(b"\r\n\r\n")
            if not sep:
                self._raw.write(data)
                return len(data)
            self._raw.write(head + sep)
            self._in_body = True
            data = rest
        room = self._limit - self._sent
        if room <= 0:
            self.truncated = self.truncated or bool(data)
            return len(data)
        cut = data[:room]
        self._raw.write(cut)
        self._sent += len(cut)
        if len(cut) < len(data):
            self.truncated = True
        return len(data)

    def flush(self):
        self._raw.flush()

    def close(self):
        self._raw.close()

    def __getattr__(self, name):
        return getattr(self._raw, name)


class FaultPlan:
    """A seeded, scripted sequence of transport faults."""

    def __init__(self, rules, seed=0):
        self.rules = []
        for rule in rules:
            action = rule.get("action")
            if action not in _KNOWN:
                raise ValueError("unknown fault action %r (want one "
                                 "of %s)" % (action, ", ".join(_KNOWN)))
            self.rules.append(dict(rule))
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._count = 0
        self._lock = threading.Lock()
        self.fired = []                 # (ordinal, action) log

    # -- (de)serialization ---------------------------------------------------
    @classmethod
    def from_json(cls, text):
        """``{"seed": s, "rules": [...]}`` or a bare rule list."""
        payload = json.loads(text)
        if isinstance(payload, list):
            return cls(payload)
        return cls(payload.get("rules") or [],
                   seed=payload.get("seed") or 0)

    def to_json(self):
        return json.dumps({"seed": self.seed, "rules": self.rules})

    def env(self, base=None):
        """A copy of ``base`` (default ``os.environ``) carrying this
        plan — what the supervisor hands the replica subprocess."""
        env = dict(os.environ if base is None else base)
        env[PLAN_ENV] = self.to_json()
        return env

    # -- matching ------------------------------------------------------------
    def _matches(self, rule, n):
        if "at" in rule:
            return n == int(rule["at"])
        if "after" in rule:
            return n >= int(rule["after"])
        if "every" in rule:
            return n % int(rule["every"]) == 0
        if "probability" in rule:
            return self._rng.random() < float(rule["probability"])
        return True

    def _next(self, path):
        """Data-request ordinal + the rules that fire on it (empty for
        exempt control-plane paths)."""
        if not path.startswith("/api"):
            return 0, []
        with self._lock:
            self._count += 1
            n = self._count
            hits = [r for r in self.rules if self._matches(r, n)
                    and path.startswith(r.get("path_prefix", "/api"))]
            for rule in hits:
                self.fired.append((n, rule["action"]))
        return n, hits

    # -- the faults ----------------------------------------------------------
    @staticmethod
    def _sigstop(rule):
        resume = rule.get("resume_after")
        if resume:
            # a detached helper delivers the SIGCONT — this process is
            # about to be frozen and cannot resume itself
            subprocess.Popen(
                [sys.executable, "-c",
                 "import os, signal, time; time.sleep(%f); "
                 "os.kill(%d, signal.SIGCONT)"
                 % (float(resume), os.getpid())],
                start_new_session=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        os.kill(os.getpid(), signal.SIGSTOP)

    def _preempt(self, handler, rule):
        """Faults that replace the real response.  Returns True when
        the wrapped handler must NOT run."""
        action = rule["action"]
        if action == "refuse":
            # close without a status line: the peer sees EOF where a
            # response belonged — a clean, retryable connection error
            handler.close_connection = True
            return True
        if action == "blackhole":
            time.sleep(float(rule.get("seconds", 300.0)))
            handler.close_connection = True
            return True
        if action == "sigkill":
            os.kill(os.getpid(), signal.SIGKILL)
            return True                 # not reached
        if action == "sigstop":
            self._sigstop(rule)
            # resumed later: the request proceeds normally — a hung
            # replica answers late, it does not error
            return False
        return False

    def apply(self, handler, method):
        """Run one handler method under this plan."""
        _, hits = self._next(handler.path.split("?", 1)[0])
        truncate = None
        for rule in hits:
            action = rule["action"]
            if action == "latency":
                time.sleep(float(rule.get("seconds", 0.05)))
            elif action == "truncate":
                truncate = int(rule.get("bytes", 0))
            elif self._preempt(handler, rule):
                return None
        if truncate is None:
            return method(handler)
        wrapped = _TruncatingFile(handler.wfile, truncate)
        handler.wfile = wrapped
        try:
            return method(handler)
        finally:
            handler.wfile = wrapped._raw
            if wrapped.truncated:
                # the body is short of Content-Length: close so the
                # peer sees the truncation NOW, not at keep-alive reap
                handler.close_connection = True
                try:
                    wrapped.flush()
                except OSError:
                    pass

    # -- installation --------------------------------------------------------
    def install(self, httpd):
        """Wrap ``httpd``'s handler class so every ``do_*`` method runs
        under this plan.  Returns the plan (chainable)."""
        plan = self
        base = httpd.RequestHandlerClass

        def _wrap(name):
            orig = getattr(base, name)

            def method(handler_self):
                return plan.apply(handler_self, orig)
            method.__name__ = name
            return method

        overrides = {name: _wrap(name) for name in dir(base)
                     if name.startswith("do_")}
        overrides["fault_plan"] = plan
        httpd.RequestHandlerClass = type(
            "Faulty" + base.__name__, (base,), overrides)
        return self


def install_from_env(server, environ=None):
    """Install the ``VELES_FAULT_PLAN`` plan (if any) around an
    :class:`~veles_tpu.serving.server.InferenceServer` — called by the
    fleet replica at startup; a clean environment is a no-op."""
    text = (os.environ if environ is None else environ).get(PLAN_ENV)
    if not text:
        return None
    plan = FaultPlan.from_json(text)
    plan.install(server._httpd)
    return plan
