"""Replica lifecycle: spawn, watch, respawn-with-backoff, roll out.

The serving-side generalization of
:class:`~veles_tpu.distributed.ElasticRunner` (which supervises ONE
training process at checkpoint granularity): the supervisor owns N
replica subprocesses, each a :mod:`veles_tpu.fleet.replica` —

- **warm spawn**: every replica inherits the persistent compile-cache
  dir (``VELES_COMPILE_CACHE_DIR``) and the supervisor's trace context
  through its environment, so a respawn against a warm cache
  deserializes its whole executable ladder (``compiles == 0``) and its
  spans join the fleet trace;
- **crash recovery**: a monitor thread polls the child processes; a
  dead replica is marked down in the router immediately and respawned
  on the shared :class:`~veles_tpu.distributed.RestartBackoff` policy
  (exponential + jitter, max-restart budget) — a crash-looping replica
  backs off instead of hot-spinning and eventually parks as
  ``failed``;
- **rolling model updates**: :meth:`rolling_update` walks the replicas
  one at a time — stop new dispatch at the router, wait for the
  replica's in-flight requests to drain, hot-load the new model
  version through ``POST /admin/models`` (the registry warms the new
  scheduler fully BEFORE the swap and drains the old one after), then
  re-admit — so an open-loop load across the fleet sees zero failed
  responses while every replica flips to the new version.
"""

import collections
import json
import os
import signal
import subprocess
import sys
import threading
import time

from ..compilecache import inject_env as _cache_inject_env
from ..distributed import RestartBackoff
from ..logger import events
from ..observability import trace as _trace
from .router import _DISPATCH_ERRORS, get_json


class _ReplicaProc:
    """One supervised replica subprocess."""

    def __init__(self, rid, backoff):
        self.id = rid
        self.backoff = backoff
        self.proc = None
        self.port = None
        self.state = "new"        # new|starting|up|respawning|failed|stopped
        self.spawned_at = None
        self.respawn_due = None
        self.announce = threading.Event()
        self.log_tail = collections.deque(maxlen=200)

    @property
    def pid(self):
        return self.proc.pid if self.proc is not None else None

    def describe(self):
        return {"state": self.state, "port": self.port, "pid": self.pid,
                "failed": self.state == "failed",
                "restarts": self.backoff.restarts,
                "restarts_remaining": self.backoff.remaining,
                "crash_streak": self.backoff.streak}


class ReplicaSupervisor:
    """Spawn and babysit ``replicas`` serving subprocesses.

    ``models``: mapping/iterable of ``name -> spec`` handed to every
    replica (package zip path or a :func:`~veles_tpu.fleet.replica
    .resolve_model_spec` spec).  ``router``: a
    :class:`~veles_tpu.fleet.router.FleetRouter` kept in sync with the
    replica set (optional — the supervisor also works headless).
    """

    def __init__(self, models, replicas=2, router=None, *,
                 host="127.0.0.1", max_batch=64, queue_limit=256,
                 workers=1, cache_dir=None, kvtier_dir=None,
                 flight_dir=None, python=None, env=None,
                 backoff=None, spawn_timeout=180.0, poll_interval=0.1,
                 fault_plans=None, clock=time.monotonic):
        items = models.items() if hasattr(models, "items") else models
        self.models = [(str(n), s) for n, s in items]
        self.router = router
        self.host = host
        self.max_batch = int(max_batch)
        self.queue_limit = int(queue_limit)
        self.workers = int(workers)
        self.cache_dir = cache_dir
        self.kvtier_dir = kvtier_dir
        self.flight_dir = flight_dir
        self.python = python or sys.executable
        self.spawn_timeout = float(spawn_timeout)
        self.poll_interval = float(poll_interval)
        self._clock = clock
        self._backoff_kw = backoff or {}
        self._replicas = {}
        for i in range(int(replicas)):
            rid = "r%d" % i
            self._replicas[rid] = _ReplicaProc(
                rid, RestartBackoff(**self._backoff_kw))
        self._env = env
        # rid → fault plan (dict or JSON string) injected into that
        # replica's environment — the deterministic chaos hook (see
        # veles_tpu.fleet.chaos); replicas without a plan run clean
        self.fault_plans = dict(fault_plans or {})
        self._lock = threading.Lock()
        self._stopping = False
        self._monitor = None

    # -- spawning ------------------------------------------------------------
    def _child_env(self, rid=None):
        env = dict(os.environ if self._env is None else self._env)
        if self.cache_dir:
            # the replica resolves its CompileCache/manifest from this
            # (compilecache.resolve_config reads the env var), so every
            # spawn after the first deserializes instead of compiling
            env["VELES_COMPILE_CACHE_DIR"] = str(self.cache_dir)
        if self.kvtier_dir and rid is not None:
            # per-replica disk tier, path keyed by the STABLE replica id
            # so a respawn re-opens the same index and re-advertises its
            # surviving chains (the chaos drill's warm-restart invariant)
            env["VELES_KVTIER_DIR"] = os.path.join(
                str(self.kvtier_dir), rid)
        if self.flight_dir and rid is not None:
            # per-replica flight-record dir: anomalous request
            # timelines persist here and SURVIVE a SIGKILL — the
            # chaos drill's evidence trail (tools/request_inspect.py
            # --dir reads them offline)
            env["VELES_FLIGHT_DIR"] = os.path.join(
                str(self.flight_dir), rid)
        plan = self.fault_plans.get(rid) if rid is not None else None
        if plan is not None:
            env["VELES_FAULT_PLAN"] = (plan if isinstance(plan, str)
                                       else json.dumps(plan))
        env = _trace.inject_env(env) or env
        return _cache_inject_env(env) or env

    def _argv(self, rid):
        argv = [self.python, "-m", "veles_tpu.fleet.replica",
                "--replica-id", rid, "--port", "0",
                "--host", self.host,
                "--max-batch", str(self.max_batch),
                "--queue-limit", str(self.queue_limit),
                "--workers", str(self.workers)]
        for name, spec in self.models:
            argv += ["--model", "%s=%s" % (name, spec)]
        return argv

    def _spawn(self, handle):
        handle.state = "starting"
        handle.announce = threading.Event()
        handle.spawned_at = self._clock()
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        handle.proc = subprocess.Popen(
            self._argv(handle.id), cwd=repo,
            env=self._child_env(handle.id),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        threading.Thread(target=self._drain_stdout, args=(handle,),
                         daemon=True,
                         name="veles-fleet-%s-log" % handle.id).start()
        events.event("fleet.spawn", replica=handle.id,
                     pid=handle.proc.pid)

    def _drain_stdout(self, handle):
        """Read the child's output forever: parse the announce line,
        keep a tail for diagnostics, never let the pipe fill."""
        proc = handle.proc
        for line in proc.stdout:
            line = line.rstrip("\n")
            handle.log_tail.append(line)
            if handle.port is None or not handle.announce.is_set():
                try:
                    announced = json.loads(line).get("fleet_replica")
                except (ValueError, AttributeError):
                    announced = None
                if announced and proc is handle.proc:
                    handle.port = int(announced["port"])
                    handle.state = "up"
                    if self.router is not None:
                        self.router.add_replica(handle.id, self.host,
                                                handle.port)
                    handle.announce.set()

    def start(self):
        """Spawn every replica (concurrently — they warm in parallel)
        and register each with the router as it announces."""
        with self._lock:
            for handle in self._replicas.values():
                self._spawn(handle)
        for handle in self._replicas.values():
            if not handle.announce.wait(self.spawn_timeout):
                raise RuntimeError(
                    "replica %s did not announce within %.0fs:\n%s"
                    % (handle.id, self.spawn_timeout,
                       "\n".join(handle.log_tail)))
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="veles-fleet-supervisor")
        self._monitor.start()
        return self

    # -- monitoring / respawn ------------------------------------------------
    def _monitor_loop(self):
        while not self._stopping:
            now = self._clock()
            with self._lock:
                handles = list(self._replicas.values())
            for handle in handles:
                self._check(handle, now)
            time.sleep(self.poll_interval)

    def _check(self, handle, now):
        if handle.state in ("failed", "stopped", "new"):
            return
        if handle.state == "respawning":
            if now >= handle.respawn_due:
                handle.respawn_due = None
                self._spawn(handle)
            return
        if handle.proc is None or handle.proc.poll() is None:
            return
        # the replica died: out of the router NOW, respawn on backoff
        rc = handle.proc.returncode
        if self.router is not None:
            self.router.mark_down(handle.id)
        handle.backoff.note_uptime(now - (handle.spawned_at or now))
        delay = handle.backoff.next_delay()
        events.event("fleet.replica_died", replica=handle.id, rc=rc,
                     respawn_in=delay)
        if delay is None:
            handle.state = "failed"
            return
        handle.state = "respawning"
        handle.respawn_due = now + delay

    # -- readiness -----------------------------------------------------------
    def _replica_ready(self, handle):
        if handle.state != "up" or handle.port is None:
            return False
        try:
            status, body = get_json(self.host, handle.port, "/readyz",
                                    timeout=2.0)
        except _DISPATCH_ERRORS + (ValueError,):
            return False
        return status == 200 and bool(body and body.get("ready"))

    def wait_ready(self, timeout=180.0, replicas=None):
        """Block until every (non-failed) replica answers ready;
        returns the ready ids.  Raises on timeout."""
        deadline = time.monotonic() + timeout
        want = set(replicas if replicas is not None else self._replicas)
        while True:
            ready = {rid for rid in want
                     if self._replica_ready(self._replicas[rid])}
            live = {rid for rid in want
                    if self._replicas[rid].state != "failed"}
            if ready >= live and live:
                return sorted(ready)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "replicas not ready after %.0fs: %s"
                    % (timeout, {rid: self._replicas[rid].describe()
                                 for rid in want - ready}))
            time.sleep(0.05)

    # -- drills / control ----------------------------------------------------
    def kill(self, rid, sig=signal.SIGKILL):
        """Fault injection: kill one replica (the monitor respawns it)."""
        handle = self._replicas[rid]
        if handle.proc is not None and handle.proc.poll() is None:
            os.kill(handle.proc.pid, sig)

    def replica_ids(self):
        return sorted(self._replicas)

    def describe(self):
        return {rid: h.describe() for rid, h in self._replicas.items()}

    # -- session migration ---------------------------------------------------
    def _admin(self, handle, action, body, timeout=60.0):
        return get_json(self.host, handle.port,
                        "/admin/sessions/" + action, method="POST",
                        timeout=timeout, body=body)

    def _pick_target(self, source_rid):
        for rid in self.replica_ids():
            handle = self._replicas[rid]
            if rid != source_rid and handle.state == "up" \
                    and handle.port is not None:
                return rid
        return None

    def migrate_sessions(self, source_rid, target_rid=None):
        """Move every live decode session off ``source_rid`` to a peer.

        Three phases, each idempotent against a crash between them:
        export (the source frees the rows and PARKS the clients'
        futures — nothing is answered yet), import at the target (each
        session lands independently), release at the source (the
        parked clients get the 307 redirect the router follows to the
        new home).  Sessions the target rejected are re-imported at
        the source — a failed migrate degrades to "nothing moved",
        never to a lost session."""
        source = self._replicas[source_rid]
        if source.port is None:
            raise RuntimeError("replica %s has no address" % source_rid)
        status, body = self._admin(source, "export", {})
        if status != 200:
            raise RuntimeError("session export on %s answered %s: %s"
                               % (source_rid, status, body))
        sessions = (body or {}).get("sessions") or []
        summary = {"source": source_rid, "target": target_rid,
                   "moved": [], "restored": [], "errors": []}
        if not sessions:
            return summary
        if target_rid is None:
            target_rid = self._pick_target(source_rid)
            summary["target"] = target_rid
        target = self._replicas.get(target_rid) if target_rid else None
        imported = []
        if target is not None and target.port is not None:
            try:
                _, tbody = self._admin(target, "import",
                                       {"sessions": sessions})
            except _DISPATCH_ERRORS + (ValueError,):
                tbody = None
            if isinstance(tbody, dict):
                imported = [str(s) for s in tbody.get("imported") or []]
                summary["errors"] = list(tbody.get("errors") or [])
        if imported:
            self._admin(source, "release",
                        {"session_ids": imported,
                         "target": "%s:%d" % (self.host, target.port)},
                        timeout=30.0)
            if self.router is not None:
                for sid in imported:
                    self.router.note_session_home(sid, target_rid)
            summary["moved"] = imported
        # anything that did not land at the target goes back home —
        # its parked future is reused, the client never notices
        landed = set(imported)
        leftover = [s for s in sessions
                    if str(s.get("session_id")) not in landed]
        if leftover:
            self._admin(source, "import", {"sessions": leftover})
            summary["restored"] = [str(s.get("session_id"))
                                   for s in leftover]
        events.event("fleet.migrate", source=source_rid,
                     target=target_rid, moved=len(imported),
                     restored=len(leftover))
        return summary

    def drain(self, rid, drain_timeout=30.0):
        """Quiesce one replica: stop NEW dispatch at the router,
        migrate its live sessions to a peer (so the wait below is
        bounded by migration time, not by generation length), then
        wait out the remaining in-flight requests."""
        if self.router is not None:
            self.router.set_admitting(rid, False)
        summary = None
        try:
            summary = self.migrate_sessions(rid)
        except Exception:  # noqa: BLE001 — fall back to waiting it out
            events.event("fleet.migrate_failed", replica=rid)
        if self.router is not None:
            self._drain_router_inflight(rid, drain_timeout)
        return summary

    # -- rolling model updates -----------------------------------------------
    def rolling_update(self, name, spec, version=None,
                       drain_timeout=30.0, admin_timeout=300.0):
        """Zero-downtime version rollout: one replica at a time —
        quiesce at the router, drain in-flight, hot-load, re-admit.

        The replica itself keeps serving its OLD version until the new
        scheduler is fully warm (registry hot-swap semantics), so the
        only reason to quiesce is to keep tail latency flat while the
        replica pays the warmup CPU.  Raises on the first replica that
        fails to load, leaving it quiesced and the rest untouched."""
        t0 = time.monotonic()
        updated = []
        for rid in self.replica_ids():
            handle = self._replicas[rid]
            if handle.state == "failed":
                continue
            if not handle.announce.wait(self.spawn_timeout):
                raise RuntimeError("replica %s has no address" % rid)
            if self.router is not None:
                self.router.set_admitting(rid, False)
                try:
                    # live sessions move to a peer instead of pinning
                    # the drain to their generation length; on any
                    # migration failure the old behavior (wait out the
                    # generations) still holds
                    self.migrate_sessions(rid)
                except Exception:  # noqa: BLE001
                    events.event("fleet.migrate_failed", replica=rid)
                self._drain_router_inflight(rid, drain_timeout)
            try:
                status, body = get_json(
                    self.host, handle.port, "/admin/models",
                    method="POST", timeout=admin_timeout,
                    body={"name": name, "model": spec,
                          "version": version})
                if status != 200:
                    raise RuntimeError(
                        "hot-load on %s answered %s: %s"
                        % (rid, status, body))
                self.wait_ready(timeout=admin_timeout, replicas=[rid])
            except Exception:
                events.event("fleet.rollout_failed", replica=rid,
                             model=name, version=version)
                raise
            finally:
                # re-admit on success AND on failure of a LATER step —
                # the replica still serves (old or new version); only
                # an unreachable one stays out via the health poll
                if self.router is not None:
                    self.router.set_admitting(rid, True)
            updated.append(rid)
            events.event("fleet.rollout_step", replica=rid, model=name,
                         version=version)
        return {"model": name, "version": version, "updated": updated,
                "seconds": round(time.monotonic() - t0, 3)}

    def _drain_router_inflight(self, rid, timeout):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rep = self.router.replica(rid)
            if rep is None or rep.inflight <= 0:
                return
            time.sleep(0.01)

    # -- shutdown ------------------------------------------------------------
    def stop(self, drain=True, timeout=20.0):
        """SIGTERM every replica (graceful drain in the child), reap,
        SIGKILL stragglers."""
        self._stopping = True
        if self._monitor is not None:
            self._monitor.join(self.poll_interval * 4 + 1.0)
        with self._lock:
            handles = list(self._replicas.values())
        for handle in handles:
            handle.state = "stopped"
            if handle.proc is not None and handle.proc.poll() is None:
                handle.proc.send_signal(
                    signal.SIGTERM if drain else signal.SIGKILL)
        deadline = time.monotonic() + timeout
        for handle in handles:
            if handle.proc is None:
                continue
            try:
                handle.proc.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                handle.proc.kill()
                handle.proc.wait(5.0)
            if self.router is not None:
                self.router.mark_down(handle.id)


class Fleet:
    """Convenience composition: a router plus a supervised replica set.

    >>> fleet = Fleet({"mnist": "mnist_pkg.zip"}, replicas=3).start()
    >>> # POST fleet.url + "/api/mnist" ...
    >>> fleet.stop()
    """

    def __init__(self, models, replicas=3, router_port=0,
                 host="127.0.0.1", poll_interval=0.2,
                 request_timeout=60.0, **supervisor_kw):
        from .router import FleetRouter
        self.router = FleetRouter(port=router_port, host=host,
                                  poll_interval=poll_interval,
                                  request_timeout=request_timeout)
        self.supervisor = ReplicaSupervisor(
            models, replicas=replicas, router=self.router, host=host,
            **supervisor_kw)
        # restart budgets / crash-looper state ride the one merged
        # /metrics payload the router already serves
        self.router.supervisor_info = self.supervisor.describe

    @property
    def url(self):
        return self.router.url

    @property
    def port(self):
        return self.router.port

    def start(self, ready_timeout=300.0):
        self.supervisor.start()
        self.supervisor.wait_ready(ready_timeout)
        return self

    def rolling_update(self, name, spec, **kwargs):
        return self.supervisor.rolling_update(name, spec, **kwargs)

    def migrate_sessions(self, source_rid, target_rid=None):
        return self.supervisor.migrate_sessions(source_rid, target_rid)

    def drain(self, rid, **kwargs):
        return self.supervisor.drain(rid, **kwargs)

    def stop(self, drain=True):
        self.supervisor.stop(drain=drain)
        self.router.stop()
