"""veles_tpu.fleet — multi-host serving: router + replica lifecycle.

The composition layer over :mod:`veles_tpu.serving` (ROADMAP open item
2, the "millions of users" story): N single-process serving replicas —
subprocesses on one host, or processes across hosts — behind one front
router, with zero-downtime rolling model updates.

- :mod:`.router` — :class:`FleetRouter`: least-loaded dispatch on
  per-replica health/backpressure signals (queue depth, in-flight,
  KV occupancy), exactly-once retry of idempotent requests on a dead
  replica, merged ``/metrics`` ``/healthz`` ``/readyz`` ``/models``;
- :mod:`.supervisor` — :class:`ReplicaSupervisor`: warm replica spawn
  (compile-cache + warmup-manifest env inherited → zero XLA compiles
  before ready), crash respawn on the shared
  :class:`~veles_tpu.distributed.RestartBackoff` policy, and
  :meth:`~ReplicaSupervisor.rolling_update`; :class:`Fleet` composes
  both;
- :mod:`.replica` — the replica process entry
  (``python -m veles_tpu.fleet.replica``): a stock
  :class:`~veles_tpu.serving.InferenceServer` with the admin hot-load
  endpoint on;
- :mod:`.chaos` — :class:`FaultPlan`: deterministic, scripted fault
  injection (refuse / black-hole / truncate / latency / SIGKILL /
  SIGSTOP) installed inside replica subprocesses via
  ``VELES_FAULT_PLAN`` — what the failover guarantees are tested
  against.

Quickstart::

    from veles_tpu.fleet import Fleet
    fleet = Fleet({"mnist": "mnist_pkg.zip"}, replicas=3).start()
    # POST fleet.url + "/api/mnist" {"input": [[...]]}
    fleet.rolling_update("mnist", "mnist_pkg_v2.zip", version="v2")
    fleet.stop()

or from the CLI: ``python -m veles_tpu.fleet --model mnist=pkg.zip
--replicas 3``.
"""

from .chaos import FaultPlan
from .replica import resolve_model_spec
from .router import FleetRouter
from .supervisor import Fleet, ReplicaSupervisor

__all__ = ["FaultPlan", "Fleet", "FleetRouter", "ReplicaSupervisor",
           "resolve_model_spec"]
