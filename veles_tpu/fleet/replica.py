"""One serving replica: a single-process InferenceServer under fleet
supervision.

The replica is deliberately NOT a new server — it is exactly the
PR-1/PR-6 :class:`~veles_tpu.serving.server.InferenceServer` (bucket +
decode schedulers, registry, warmup manifests) started by
``python -m veles_tpu.fleet.replica`` with:

- the admin hot-load endpoint enabled (``POST /admin/models``), which is
  how the supervisor performs rolling model updates;
- an announce line on stdout — one JSON object
  ``{"fleet_replica": {"port": ..., "pid": ..., "replica": ...}}`` —
  printed as soon as the listener is bound, so the supervisor learns
  the (port-0-allocated) address immediately while readiness stays
  gated on ``GET /readyz``;
- trace context and compile-cache dirs adopted from the environment
  (the supervisor injects both), so a warm spawn deserializes its
  executable ladder instead of compiling and its spans join the
  fleet-wide trace.

Model specs (``--model NAME=SPEC``, repeatable):

- a path to an exported package zip (the production case);
- ``sleep:SECONDS[:DIM]`` — a deterministic device-bound STAND-IN
  model: it sleeps ``SECONDS`` per sample ROW, then returns the input
  batch.  Sleeping per row (not per call) means batching cannot
  amortize it — a replica's throughput is pinned at ``1/SECONDS``
  rows/s, exactly like a model whose cost is accelerator time.  Fleet
  tests and benches measure SCHEDULING (scaling, failover, rollout)
  against it without paying XLA compiles, and replica scaling stays
  measurable on a single-core CI host, where CPU-bound work cannot
  scale by construction (on real TPUs each replica owns its own
  chip, which this emulates).
"""

import argparse
import json
import os
import signal
import sys
import threading
import time


def resolve_model_spec(spec):
    """An admin/CLI model spec → something ``registry.add`` accepts."""
    if isinstance(spec, str) and spec.startswith("sleep:"):
        from ..serving.scheduler import OpaqueModel
        parts = spec.split(":")
        delay = float(parts[1])
        dim = int(parts[2]) if len(parts) > 2 else 4

        def fn(x, _delay=delay):
            time.sleep(_delay * x.shape[0])   # device-time-per-row twin
            return x

        return OpaqueModel(fn, sample_shape=(dim,))
    if isinstance(spec, str) and spec.startswith("toydecode"):
        # the decode-path stand-in: deterministic, KV-dependent, with
        # a host oracle — what migration/chaos drills generate against
        from ..serving.toydecode import from_spec
        return from_spec(spec)
    return spec


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles_tpu.fleet.replica",
        description="One fleet serving replica (an InferenceServer "
                    "with the admin hot-load endpoint on).")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=SPEC", dest="models",
                   help="package zip path, sleep:SECONDS[:DIM], or "
                        "toydecode:k=v,... (repeatable)")
    p.add_argument("--port", type=int, default=0,
                   help="0 = pick a free port (announced on stdout)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--queue-limit", type=int, default=256)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--replica-id", default=None,
                   help="stable id assigned by the supervisor")
    p.add_argument("--cache-dir", default=None,
                   help="persistent executable cache dir (usually "
                        "inherited via VELES_COMPILE_CACHE_DIR)")
    p.add_argument("--kvtier-dir", default=None,
                   help="disk tier directory for the tiered KV cache "
                        "(usually inherited via VELES_KVTIER_DIR)")
    args = p.parse_args(argv)

    from ..config import root
    if args.cache_dir:
        root.common.compile_cache.dir = args.cache_dir
    if args.kvtier_dir:
        # resolved by DecodeScheduler's kvtier disk_dir=True path
        from ..kvtier import DIR_ENV
        os.environ[DIR_ENV] = args.kvtier_dir
    from ..observability import trace as _trace
    _trace.adopt_env()
    # flight recorder: adopt the supervisor's per-replica persist dir
    # and stamp timelines with this replica's stable id
    from ..observability.flight import configure_from_env
    configure_from_env(replica=args.replica_id)

    from ..serving import InferenceServer
    server = InferenceServer(
        port=args.port, host=args.host, enable_admin=True,
        model_resolver=resolve_model_spec, max_batch=args.max_batch,
        queue_limit=args.queue_limit, workers=args.workers)
    # scripted fault injection (VELES_FAULT_PLAN, planted by the
    # supervisor's fault_plans= knob); clean env → no-op
    from .chaos import install_from_env
    install_from_env(server)
    # announce BEFORE warmup: the supervisor learns the address now and
    # gates traffic on /readyz, which stays 503 until every model below
    # finishes its ladder
    print(json.dumps({"fleet_replica": {
        "port": server.port, "pid": os.getpid(),
        "replica": args.replica_id}}), flush=True)

    for spec in args.models:
        name, _, model = spec.partition("=")
        if not model:
            model, name = name, os.path.splitext(
                os.path.basename(name))[0]
        server.registry.add(name, resolve_model_spec(model))

    done = threading.Event()
    # SIGTERM = graceful drain (the supervisor's stop path); SIGKILL is
    # the crash being drilled and never reaches python
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    server.stop(drain=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
