"""Shared JSON-over-HTTP plumbing for the serving endpoints.

The serving subsystem (serving/server.py), the compatibility facade
(restful_api.py) and the live-workflow input loader (loader/restful.py)
all speak the same protocol — ``POST /api {"input": ...}`` answered with
JSON — so the request parsing/validation and response writing live here
once.

Error taxonomy: everything wrong with the *request* raises
:class:`ClientError` (a ValueError), which handlers answer with HTTP
400; any other exception is a *server* fault and must surface as a 500
with a generic body — never the raw traceback string (the seed handler
conflated the two, restful_api.py:87-88).
"""

import json
from http.server import BaseHTTPRequestHandler

import numpy


class ClientError(ValueError):
    """The request itself is malformed — answer 400, not 500."""


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Quiet handler with JSON helpers and the /api input contract."""

    def log_message(self, *args):
        pass

    def send_json(self, code, payload, headers=None):
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def read_input_payload(self):
        """Parse the request body as {"input": ...} → float32 array.
        Raises ClientError with a client-presentable message."""
        length = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(length))
        except ValueError:
            raise ClientError("body is not valid JSON")
        if not isinstance(payload, dict) or "input" not in payload:
            raise ClientError("body must be {'input': [...]}")
        try:
            return numpy.asarray(payload["input"], numpy.float32)
        except (ValueError, TypeError):
            raise ClientError("'input' is not a numeric array "
                              "(ragged or non-numeric rows?)")
