"""Shared JSON-over-HTTP plumbing for the serving endpoints.

Both the standalone inference endpoint (restful_api.py) and the
live-workflow input loader (loader/restful.py) speak the same protocol —
``POST /api {"input": ...}`` answered with JSON — so the request
parsing/validation and response writing live here once.
"""

import json
from http.server import BaseHTTPRequestHandler

import numpy


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Quiet handler with JSON helpers and the /api input contract."""

    def log_message(self, *args):
        pass

    def send_json(self, code, payload):
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def read_input_payload(self):
        """Parse the request body as {"input": ...} → float32 array.
        Raises ValueError with a client-presentable message."""
        length = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(length))
        if not isinstance(payload, dict) or "input" not in payload:
            raise ValueError("body must be {'input': [...]}")
        return numpy.asarray(payload["input"], numpy.float32)
