"""Interface verification: fail fast on unimplemented unit contracts.

Re-creation of /root/reference/veles/verified.py (:45): the reference
verified zope.interface contracts (IUnit, ILoader, ...) at construction
so a half-implemented unit failed before training started.  Without
zope, the same guarantee comes from explicit contract lists: a base
class declares ``CONTRACT = ("method", ...)`` and
:func:`verify_contract` asserts each is overridden (not the base's
NotImplementedError stub) — called from the bases' ``initialize``.
``Unit.verify_demands`` (attribute-level) complements this
method-level check.
"""


def verify_contract(obj, base):
    """Raise TypeError when ``obj`` leaves a CONTRACT method of ``base``
    unimplemented."""
    contract = getattr(base, "CONTRACT", ())
    missing = []
    for name in contract:
        impl = getattr(type(obj), name, None)
        if impl is None or impl is getattr(base, name, None):
            missing.append(name)
    if missing:
        raise TypeError(
            "%s does not implement required %s methods: %s (reference "
            "verified.py contract check)" %
            (type(obj).__name__, base.__name__, ", ".join(missing)))
