"""Distributed argparse: classes contribute their own CLI options.

Re-creation of /root/reference/veles/cmdline.py
(CommandLineArgumentsRegistry): every registered class could add
argparse options via its metaclass (e.g. backends.py:351-370,
loader/base.py:561-566).  Here classes declare a ``CLI_ARGUMENTS``
mapping (flag → argparse kwargs + a ``config`` dotted path); the main
parser collects them all, and parsed values are written into the config
tree before the workflow builds — so ``--train-ratio 0.5`` works for
every loader without each sample wiring it."""

_contributors = []


def register_arguments(owner, arguments):
    """``arguments``: iterable of (flag, argparse_kwargs, config_path).
    ``config_path`` is where the parsed value lands in ``root``."""
    _contributors.append((owner, list(arguments)))


def contribute_arguments(parser):
    """Add every registered class's options to ``parser``; returns
    {dest: config_path} for :func:`apply_arguments`."""
    dest_to_path = {}
    for owner, arguments in _contributors:
        group = parser.add_argument_group("%s options" % owner)
        for flag, kwargs, config_path in arguments:
            action = group.add_argument(flag, **kwargs)
            dest_to_path[action.dest] = config_path
    return dest_to_path


def apply_arguments(args, dest_to_path, set_config_by_path, root):
    """Write parsed values into the config tree (None = not given)."""
    for dest, path in dest_to_path.items():
        value = getattr(args, dest, None)
        if value is not None:
            set_config_by_path(root, path, value)


# -- built-in contributions (the reference's own examples) -------------------
register_arguments("Loader", [
    ("--train-ratio",
     {"type": float, "default": None,
      "help": "use this fraction of the train set (ensembles/ablation; "
              "reference loader/base.py:561-566)"},
     "root.common.ensemble.train_ratio"),
])
register_arguments("Device", [
    ("--precision-level",
     {"type": int, "default": None, "choices": (0, 1, 2),
      "help": "matmul precision 0/1/2 = default/high/highest "
              "(reference GEMM PRECISION_LEVEL)"},
     "root.common.engine.precision_level"),
])
register_arguments("FusedTrainStep", [
    ("--compute-dtype",
     {"default": None, "choices": ("float32", "bfloat16"),
      "help": "mixed-precision compute dtype for the fused step"},
     "root.common.engine.dtype"),
])
