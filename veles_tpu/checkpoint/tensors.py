"""Tensor extraction, chunked serialization, and resharded restore.

The snapshotter's capture phase walks the workflow with
``copy.deepcopy`` through the ``Pickleable.__getstate__`` machinery.
Sharded checkpoints hook that walk: inside an :func:`extracting`
context every large tensor — a device-dirty ``memory.Array`` payload
(handled in ``Array.__getstate__``) or a plain host ``numpy.ndarray``
(solver state; handled by a deepcopy dispatch hook here) — is diverted
into a :class:`TensorSink` and replaced by a tiny :class:`TensorStub`.
The topology pickle that reaches the writer thread therefore carries no
tensor payload; the writer serializes the sink's tensors as
content-addressed chunks instead, each process writing only its
``addressable_shards`` (``replica_id == 0`` — every unique piece of
data is written exactly once globally, the discipline of distributed
checkpointing in arXiv 2112.09017).

Restore is the mirror: ``TensorStub.__reduce__`` resolves through the
:func:`restoring` context, so ordinary ``pickle.load`` of the topology
rebuilds every tensor in place — assembled on host from the manifest's
chunks, or (via :meth:`TensorReader.restore_array`) materialized
per-shard onto the *restoring* process's mesh, reading only the chunks
that overlap each local shard.
"""

import contextlib
import io
import pickle
import threading

import numpy

_TLS = threading.local()


def _payload_nbytes(value):
    try:
        return int(value.nbytes)
    except Exception:  # noqa: BLE001 — anything unsized is not a tensor
        return 0


class TensorSink:
    """Collects tensor payloads extracted during one capture walk.

    Host numpy values are copied at capture (training keeps mutating
    the original); jax Arrays are immutable and kept zero-copy — the
    device→host pull happens on the writer thread, not the step loop.
    """

    def __init__(self, min_bytes=65536):
        self.min_bytes = int(min_bytes)
        self.tensors = {}            # ref -> numpy copy | jax.Array
        self._n = 0
        self._by_id = {}             # id(value) -> ref (shared-array dedupe)

    def add(self, value, copy=False):
        ref = self._by_id.get(id(value))
        if ref is not None and self.tensors[ref] is value:
            return ref
        if copy:
            value = numpy.array(value)
        ref = "t%05d" % self._n
        self._n += 1
        self.tensors[ref] = value
        self._by_id[id(value)] = ref
        return ref

    @property
    def nbytes(self):
        return sum(_payload_nbytes(v) for v in self.tensors.values())


def active_sink():
    return getattr(_TLS, "sink", None)


def active_source():
    return getattr(_TLS, "source", None)


@contextlib.contextmanager
def extracting(sink):
    """Divert large ``memory.Array`` payloads seen by pickle/deepcopy
    into ``sink`` (consulted by ``Array.__getstate__``)."""
    prev = active_sink()
    _TLS.sink = sink
    try:
        yield sink
    finally:
        _TLS.sink = prev


@contextlib.contextmanager
def restoring(source):
    """Resolve :class:`TensorStub` references through ``source``
    (anything with a ``resolve(ref)`` method) during ``pickle.load``."""
    prev = active_source()
    _TLS.source = source
    try:
        yield source
    finally:
        _TLS.source = prev


def _resolve(ref):
    src = active_source()
    if src is None:
        raise RuntimeError(
            "TensorStub %r resolved outside a checkpoint restore "
            "context — load sharded checkpoints via "
            "checkpoint.import_dir()/snapshotter.restore(), not bare "
            "pickle.load" % ref)
    return src.resolve(ref)


class TensorStub:
    """Pickles as a call to ``_resolve(ref)`` — restore rebuilds the
    tensor in place, even inside tuples/dicts pickle reconstructs."""

    __slots__ = ("ref",)

    def __init__(self, ref):
        self.ref = ref

    def __reduce__(self):
        return (_resolve, (self.ref,))

    def __deepcopy__(self, memo):
        return self                  # immutable marker

    def __repr__(self):
        return "<TensorStub %s>" % self.ref


class ExtractingPickler(pickle.Pickler):
    """Pickler diverting the remaining large tensors — plain host
    ndarrays (solver state inside gd units) and bare jax Arrays — into
    the sink via pickle's persistent-id protocol.

    This runs on the WRITER thread over the frozen capture twin, which
    is why plain ndarrays are NOT hooked at deepcopy time: a deepcopy
    hook would hand :class:`TensorStub` markers to ``__setstate__``
    methods that interpret their state eagerly (numpy's RandomState
    rejects them), whereas at load time pickle resolves every reference
    before any ``__setstate__`` sees it.  The twin is frozen, so the
    sink takes the arrays zero-copy."""

    def __init__(self, file, sink, protocol=pickle.HIGHEST_PROTOCOL):
        super().__init__(file, protocol)
        self._sink = sink

    def persistent_id(self, obj):
        sink = self._sink
        if isinstance(obj, numpy.ndarray):
            if obj.dtype != numpy.object_ and \
                    obj.nbytes >= sink.min_bytes:
                return sink.add(obj)
            return None
        if hasattr(obj, "addressable_shards") and \
                _payload_nbytes(obj) >= sink.min_bytes:
            return sink.add(obj)
        return None


def dumps_extracting(obj, sink):
    buf = io.BytesIO()
    ExtractingPickler(buf, sink).dump(obj)
    return buf.getvalue()


class ResolvingUnpickler(pickle.Unpickler):
    """Mirror of :class:`ExtractingPickler`: persistent ids resolve
    through a :class:`TensorReader` (stub references resolve through
    the surrounding :func:`restoring` context)."""

    def __init__(self, file, reader):
        super().__init__(file)
        self._reader = reader

    def persistent_load(self, ref):
        return self._reader.resolve(ref)


# -- dtype naming (manifest is JSON; bf16 etc. are not stock numpy) ----------

def dtype_name(dt):
    return numpy.dtype(dt).name


def dtype_from(name):
    try:
        return numpy.dtype(name)
    except TypeError:
        import ml_dtypes
        return numpy.dtype(getattr(ml_dtypes, name))


# -- shard / chunk planning ---------------------------------------------------

def global_shape(value):
    return tuple(int(d) for d in value.shape)


def sharding_spec(value):
    """JSON-able description of a jax.Array's sharding (None for host
    tensors).  Informational: restore reshards onto whatever mesh the
    restoring process asks for; this records what the *saving* run had
    (surfaced by tools/ckpt_inspect.py)."""
    sharding = getattr(value, "sharding", None)
    if sharding is None:
        return None
    try:
        mesh = {str(name): int(size) for name, size in
                zip(sharding.mesh.axis_names, sharding.mesh.devices.shape)}
        parts = []
        for p in tuple(sharding.spec):
            if p is None:
                parts.append(None)
            elif isinstance(p, (list, tuple)):
                parts.append([str(q) for q in p])
            else:
                parts.append(str(p))
        return {"mesh": mesh, "spec": parts}
    except Exception:  # noqa: BLE001 — e.g. SingleDeviceSharding
        return {"repr": repr(sharding)}


def local_blocks(value):
    """Yield ``(global_offset, numpy_block)`` for the pieces THIS
    process must write.  jax Arrays: addressable shards with
    ``replica_id == 0`` (each unique piece written once globally; the
    device→host pull happens here, on the writer thread).  Host numpy:
    the whole array — the caller skips host tensors on processes != 0,
    where they are replicas of process 0's."""
    if hasattr(value, "addressable_shards"):
        for shard in value.addressable_shards:
            if shard.replica_id != 0:
                continue
            base = tuple(int(sl.start or 0) for sl in shard.index)
            yield base, numpy.asarray(shard.data)
    else:
        arr = numpy.asarray(value)
        yield (0,) * arr.ndim, arr


def iter_block_chunks(base, block, chunk_bytes):
    """Split one contiguous block (at global offset ``base``) into
    leading-axis bands of ~``chunk_bytes`` each."""
    if block.size == 0:
        return
    if block.ndim == 0:
        yield base, block
        return
    row_bytes = max(block.nbytes // max(len(block), 1), 1)
    rows = max(int(chunk_bytes // row_bytes), 1)
    for off in range(0, len(block), rows):
        piece = block[off:off + rows]
        yield (base[0] + off,) + tuple(base[1:]), piece


def write_tensors(store, sink, chunk_bytes, host_tensors=True):
    """Serialize every sink tensor into ``store``; returns
    ``(entries, stats)`` where ``entries`` maps ref -> manifest entry.
    ``host_tensors=False`` skips plain-numpy payloads (multi-host
    processes != 0: host state is a replica of process 0's)."""
    entries = {}
    stats = {"bytes_written": 0, "bytes_total": 0,
             "chunks_written": 0, "chunks_deduped": 0}
    for ref, value in sink.tensors.items():
        is_jax = hasattr(value, "addressable_shards")
        chunks = []
        if is_jax or host_tensors:
            for base, block in local_blocks(value):
                # NOT ascontiguousarray: it promotes 0-d to shape (1,)
                block = numpy.asarray(block)
                for off, piece in iter_block_chunks(
                        base, block, chunk_bytes):
                    if not piece.flags.c_contiguous:
                        piece = numpy.ascontiguousarray(piece)
                    digest, written = store.put(piece.data)
                    stats["bytes_total"] += piece.nbytes
                    if written:
                        stats["bytes_written"] += written
                        stats["chunks_written"] += 1
                    else:
                        stats["chunks_deduped"] += 1
                    chunks.append({"offset": list(off),
                                   "shape": list(piece.shape),
                                   "digest": digest,
                                   "bytes": piece.nbytes})
        entries[ref] = {"shape": list(global_shape(value)),
                        "dtype": dtype_name(value.dtype),
                        "sharding": sharding_spec(value),
                        "chunks": chunks}
    return entries, stats


# -- restore ------------------------------------------------------------------

def _norm_index(index, shape):
    """Normalize a shard index (tuple of slices, possibly open-ended)
    to concrete [start, stop) bounds."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _overlap(dst_bounds, chunk_off, chunk_shape):
    """(dst_slices, src_slices) of the intersection, or None."""
    dst_sl, src_sl = [], []
    for (a, b), o, s in zip(dst_bounds, chunk_off, chunk_shape):
        lo, hi = max(a, o), min(b, o + s)
        if hi <= lo:
            return None
        dst_sl.append(slice(lo - a, hi - a))
        src_sl.append(slice(lo - o, hi - o))
    return tuple(dst_sl), tuple(src_sl)


class TensorReader:
    """Resolves manifest tensors from a chunk store.

    ``resolve(ref)`` assembles the full tensor on host (the default
    restore path: peak memory is one tensor, not the whole model twice).
    ``restore_array(ref, sharding)`` builds a jax.Array directly onto
    the restoring process's mesh, reading ONLY the chunks overlapping
    each addressable shard — the beyond-host-RAM path.
    """

    def __init__(self, store, manifest):
        self.store = store
        self.manifest = manifest
        self.bytes_read = 0
        #: optional hard cap on a single host assembly (set by callers
        #: proving the beyond-RAM path; None = unlimited)
        self.max_resolve_bytes = None

    def entry(self, ref):
        try:
            return self.manifest.tensors[ref]
        except KeyError:
            raise KeyError("checkpoint manifest has no tensor %r" % ref)

    def _chunk_array(self, chunk, dtype):
        data = self.store.get(chunk["digest"])
        self.bytes_read += len(data)
        return numpy.frombuffer(data, dtype).reshape(chunk["shape"])

    def resolve(self, ref):
        e = self.entry(ref)
        dtype = dtype_from(e["dtype"])
        shape = tuple(e["shape"])
        nbytes = int(numpy.prod(shape, dtype=numpy.int64)) * dtype.itemsize
        if self.max_resolve_bytes is not None and \
                nbytes > self.max_resolve_bytes:
            raise MemoryError(
                "tensor %s (%d bytes) exceeds the per-process host "
                "assembly cap (%d); restore it shard-wise via "
                "restore_array(ref, sharding)" % (
                    ref, nbytes, self.max_resolve_bytes))
        out = numpy.empty(shape, dtype)
        for c in e["chunks"]:
            if not shape:
                out[...] = self._chunk_array(c, dtype)
                continue
            region = tuple(slice(o, o + s)
                           for o, s in zip(c["offset"], c["shape"]))
            out[region] = self._chunk_array(c, dtype)
        return out

    def restore_array(self, ref, sharding):
        import jax
        e = self.entry(ref)
        dtype = dtype_from(e["dtype"])
        shape = tuple(e["shape"])
        chunks = e["chunks"]

        def cb(index):
            if not shape:
                return self._chunk_array(chunks[0], dtype) \
                    if chunks else numpy.zeros((), dtype)
            bounds = _norm_index(index, shape)
            out = numpy.zeros(
                tuple(b - a for a, b in bounds), dtype)
            for c in chunks:
                ov = _overlap(bounds, c["offset"], c["shape"])
                if ov is None:
                    continue
                dst_sl, src_sl = ov
                out[dst_sl] = self._chunk_array(c, dtype)[src_sl]
            return out

        return jax.make_array_from_callback(shape, sharding, cb)
