"""Sharded, content-addressed tensor checkpoints (ISSUE 10).

Public surface:

- :class:`SnapshotterToShards` — drop-in snapshotter backend
  (``root.common.snapshot.format = "shards"``) writing per-process
  tensor shards as deduplicated chunks under a manifest;
- :func:`import_dir` / :func:`open_checkpoint` — restore a workflow /
  open a manifest for inspection or shard-wise tensor rebuilds;
- :func:`save_state` / :func:`load_state` — checkpoint arbitrary
  tensor pytrees (decode KV pools, tools);
- :class:`ChunkStore`, :class:`Manifest`, :class:`TensorReader` — the
  storage primitives, for tools and tests.
"""

from .manifest import (CHUNKS_DIR, CKPT_SUFFIX, MANIFEST, TOPOLOGY,
                       Manifest, list_checkpoints)
from .snapshot import (SnapshotterToShards, delete_checkpoint, import_dir,
                       is_shard_checkpoint,
                       load_state, open_checkpoint, quarantine_partials,
                       resolve_checkpoint, save_state)
from .store import ChunkStore, CorruptChunkError
from .tensors import (ExtractingPickler, ResolvingUnpickler,
                      TensorReader, TensorSink, TensorStub,
                      extracting, restoring)

__all__ = [
    "CHUNKS_DIR", "CKPT_SUFFIX", "MANIFEST", "TOPOLOGY",
    "Manifest", "list_checkpoints",
    "SnapshotterToShards", "delete_checkpoint", "import_dir",
    "is_shard_checkpoint",
    "load_state", "open_checkpoint", "quarantine_partials",
    "resolve_checkpoint", "save_state",
    "ChunkStore", "CorruptChunkError",
    "ExtractingPickler", "ResolvingUnpickler",
    "TensorReader", "TensorSink", "TensorStub",
    "extracting", "restoring",
]
