"""SnapshotterToShards: sharded, content-addressed workflow checkpoints.

Layout under one snapshot root (``directory``)::

    chunks/<sha256>.chunk            content-addressed tensor chunks,
                                     shared by ALL checkpoints (dedupe)
    <prefix>[_suffix].<n>.ckpt/      one complete checkpoint
        manifest.json                tensors -> chunk lists
        topology.pickle.gz           workflow pickle, tensors stubbed out
    <prefix>_current                 symlink to the newest complete dir

The capture/writer split of the PR-4 snapshotter is kept exactly: the
training thread deep-copies the workflow (inside an
:func:`~veles_tpu.checkpoint.tensors.extracting` context, so device
tensors are captured ZERO-COPY as immutable jax.Arrays and host numpy
is snapshotted once) and returns; the single
:class:`~veles_tpu.snapshotter.SnapshotWriter` thread pulls shards to
host, chunks, hashes and fsyncs them, writes the manifest + topology
into ``*.ckpt.tmp``, atomically renames the directory, and flips
``_current``.  A kill at ANY point leaves either the previous
checkpoint set intact or the new directory complete — never a torn
checkpoint at a listed name; leftover ``.tmp`` partials are quarantined
on the next snapshotter start.

Multi-host: EVERY process exports (unlike the pickle backends) — each
writes only its addressable shards (``replica_id == 0``) plus a
``part-<k>.json`` manifest fragment; process 0 also writes the topology,
waits for all fragments, merges them, and performs the atomic rename.
Restore happens wherever the checkpoint is opened: the topology unpickles
with every tensor resolved from chunks — assembled on host by default,
or shard-by-shard onto the restoring process's mesh via
:meth:`TensorReader.restore_array` for state that must never fully
materialize on one host.
"""

import gzip
import os

import shutil
import time

from ..config import root
from ..logger import events
from ..observability.registry import REGISTRY
from ..snapshotter import SnapshotterBase
from .manifest import (CHUNKS_DIR, CKPT_SUFFIX, MANIFEST, TOPOLOGY,
                       Manifest, list_checkpoints)
from .store import ChunkStore
from .tensors import (ResolvingUnpickler, TensorReader, TensorSink,
                      dumps_extracting, extracting, restoring,
                      write_tensors)

_PARTS_SUFFIX = ".parts"
_PART_WAIT_S = 120.0

_metrics = None


def _obs():
    global _metrics
    if _metrics is None:
        _metrics = {
            "bytes": REGISTRY.counter(
                "veles_checkpoint_bytes_written_total",
                "New (non-deduplicated) chunk bytes durably written"),
            "deduped": REGISTRY.counter(
                "veles_checkpoint_chunks_deduped_total",
                "Chunks skipped because identical content was already "
                "stored (cross-checkpoint dedupe hits)"),
            "seconds": REGISTRY.counter(
                "veles_checkpoint_seconds_total",
                "Wall seconds spent in checkpoint save/restore",
                ("op",)),
        }
    return _metrics


def _proc():
    try:
        import jax
        return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 — no backend ⇒ standalone
        return 0, 1


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def quarantine_partials(directory):
    """Rename leftover ``*.ckpt.tmp``/``*.ckpt.parts`` partials from a
    crashed save aside (``.quarantine``) so they can never shadow a
    complete checkpoint and the evidence survives.  Returns the new
    paths."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.endswith(CKPT_SUFFIX + ".tmp") or
                name.endswith(CKPT_SUFFIX + _PARTS_SUFFIX)):
            continue
        src = os.path.join(directory, name)
        dst = src + ".quarantine"
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = "%s.quarantine.%d" % (src, n)
        try:
            os.replace(src, dst)
            out.append(dst)
        except OSError:
            continue
    return out


def resolve_checkpoint(path):
    """Accepts a checkpoint dir, its ``manifest.json``, a ``_current``
    symlink, or a snapshot root (→ ``_current``, else the newest
    complete checkpoint); returns the checkpoint dir."""
    real = os.path.realpath(os.path.expanduser(path))
    if os.path.isfile(real):
        if os.path.basename(real) == MANIFEST:
            return os.path.dirname(real)
        raise ValueError("%s is not a sharded checkpoint" % path)
    if real.endswith(CKPT_SUFFIX) and \
            os.path.exists(os.path.join(real, MANIFEST)):
        return real
    try:
        names = os.listdir(real)
    except OSError:
        raise ValueError("no such checkpoint: %s" % path)
    for name in sorted(names):
        if name.endswith("_current"):
            target = os.path.realpath(os.path.join(real, name))
            if os.path.exists(os.path.join(target, MANIFEST)):
                return target
    ckpts = list_checkpoints(real)
    if ckpts:
        return ckpts[-1]
    raise ValueError("no complete sharded checkpoint under %s" % path)


def is_shard_checkpoint(path):
    """True when ``path`` can be resolved to a sharded checkpoint dir
    (used by ``snapshotter.restore`` to route dirs here)."""
    try:
        resolve_checkpoint(path)
        return True
    except (ValueError, OSError):
        return False


def open_checkpoint(path):
    """(ckpt_dir, Manifest, TensorReader) for inspection or shard-wise
    tensor restore."""
    ckpt = resolve_checkpoint(path)
    man = Manifest.load_dir(ckpt)
    store = ChunkStore(os.path.join(os.path.dirname(ckpt), CHUNKS_DIR))
    return ckpt, man, TensorReader(store, man)


def import_dir(path):
    """Load a sharded checkpoint back into its workflow object (the
    mirror of ``SnapshotterToFile.import_file``)."""
    ckpt, man, reader = open_checkpoint(path)
    t0 = time.perf_counter()
    with restoring(reader):
        with gzip.open(os.path.join(ckpt, TOPOLOGY), "rb") as f:
            wf = ResolvingUnpickler(f, reader).load()
    dt = time.perf_counter() - t0
    _obs()["seconds"].labels(op="restore").inc(dt)
    events.span("checkpoint.restore", dt, path=ckpt,
                tensors=len(man.tensors), bytes=reader.bytes_read)
    wf._restored_from_snapshot = True
    return wf


class SnapshotterToShards(SnapshotterBase):
    """Sharded content-addressed checkpoints behind the standard
    capture/writer split.  Opt-in via ``root.common.snapshot.format =
    "shards"`` (or ``snapshotter_config={"format": "shards"}``)."""

    MAPPING = "shards"
    #: every process writes its own addressable shards (the pickle
    #: backends gate the whole export to process 0)
    WRITES_ON_ALL_PROCESSES = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.directory = kwargs.get(
            "directory", os.path.expanduser(
                root.common.dirs.get("snapshots", ".")))
        # None = follow root.common.snapshot.* defaults
        self.chunk_bytes = kwargs.get("chunk_bytes")
        self.min_tensor_bytes = kwargs.get("min_tensor_bytes")
        quarantine_partials(self.directory)

    def _chunk_bytes(self):
        v = self.chunk_bytes
        if v is None:
            v = root.common.snapshot.get("chunk_bytes", 16 << 20)
        return max(int(v), 4096)

    def _min_tensor_bytes(self):
        v = self.min_tensor_bytes
        if v is None:
            v = root.common.snapshot.get("min_tensor_bytes", 65536)
        return max(int(v), 1)

    def export(self):
        os.makedirs(self.directory, exist_ok=True)
        target = self.workflow
        fused = getattr(target, "fused_step", None)
        if fused is not None:
            # the only part that must see a quiescent step: pull the
            # fused params/opt-state back into the units' host Arrays
            fused.sync_weights()
            fused.sync_solver_state()
        name = "%s%s.%d%s" % (
            self.prefix, ("_" + self.suffix) if self.suffix else "",
            self._counter, CKPT_SUFFIX)
        path = os.path.join(self.directory, name)
        sink = TensorSink(min_bytes=self._min_tensor_bytes())
        if self._async_enabled():
            with extracting(sink):
                payload = self._capture(target)
        else:
            payload = None
        if payload is None:
            # synchronous (or capture-failed) path: extract while
            # pickling the LIVE workflow on this thread — same hooks,
            # no twin copy
            self._write_ckpt(target, TensorSink(
                min_bytes=self._min_tensor_bytes()), path,
                extract_live=True)
        else:
            self._get_writer().submit(
                lambda: self._write_ckpt(payload, sink, path),
                improved=bool(getattr(self, "_exporting_improvement_",
                                      False)),
                label=name)
        self.destination = path
        return path

    # -- durable-write phase (writer thread; inline when synchronous) --------
    def _write_ckpt(self, obj, sink, path, extract_live=False):
        t0 = time.perf_counter()
        pidx, pcount = _proc()
        store = ChunkStore(os.path.join(self.directory, CHUNKS_DIR))
        # plain host ndarrays (solver state) divert here, at pickle
        # time on this thread; extract_live additionally arms the
        # Array.__getstate__ hook (live workflow, no twin)
        if extract_live:
            with extracting(sink):
                blob = dumps_extracting(obj, sink)
        else:
            blob = dumps_extracting(obj, sink)
        entries, stats = write_tensors(
            store, sink, self._chunk_bytes(), host_tensors=pidx == 0)
        store.fsync_dir()
        man = Manifest(tensors=entries, meta={
            "prefix": self.prefix, "suffix": self.suffix,
            "counter": self._counter, "created": time.time(),
            "process_count": pcount})
        if pcount > 1:
            parts = path + _PARTS_SUFFIX
            os.makedirs(parts, exist_ok=True)
            man.dump(os.path.join(parts, "part-%d.json" % pidx))
            if pidx != 0:
                return path
            man = self._merge_parts(parts, man, pcount)
        self._finalize(path, man, blob)
        dt = time.perf_counter() - t0
        obs = self._obs()
        obs["bytes"].inc(stats["bytes_written"])
        obs["written"].inc()
        ck = _obs()
        ck["bytes"].inc(stats["bytes_written"])
        ck["deduped"].inc(stats["chunks_deduped"])
        ck["seconds"].labels(op="save").inc(dt)
        events.span("checkpoint.save", dt, snapshotter=self.prefix,
                    path=path, bytes_written=stats["bytes_written"],
                    bytes_total=stats["bytes_total"],
                    chunks_deduped=stats["chunks_deduped"],
                    tensors=len(entries))
        self._report_tensor_sizes(path, man, stats)
        self._last_write_stats_ = stats
        return path

    def _merge_parts(self, parts, man, pcount):
        """Process 0: rendezvous on the shared filesystem — wait for
        every process's fragment, then union them."""
        deadline = time.monotonic() + _PART_WAIT_S
        want = {"part-%d.json" % k for k in range(pcount)}
        while time.monotonic() < deadline:
            try:
                have = set(os.listdir(parts))
            except OSError:
                have = set()
            if want <= have:
                break
            time.sleep(0.05)
        else:
            raise TimeoutError(
                "checkpoint parts missing after %.0fs: %s"
                % (_PART_WAIT_S, sorted(want - have)))
        for k in range(1, pcount):
            man.merge(Manifest.load(
                os.path.join(parts, "part-%d.json" % k)))
        return man

    def _finalize(self, path, man, blob):
        """Write manifest + topology into ``*.tmp``, fsync, atomically
        rename the directory, flip ``_current``, drop staging."""
        tmp = path + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        topo = os.path.join(tmp, TOPOLOGY)
        with open(topo, "wb") as raw:
            with gzip.GzipFile(
                    fileobj=raw, mode="wb",
                    compresslevel=self._compression_level()) as gz:
                gz.write(blob)
            raw.flush()
            os.fsync(raw.fileno())
        man.dump(os.path.join(tmp, MANIFEST))
        _fsync_dir(tmp)
        if os.path.isdir(path):
            shutil.rmtree(path)     # same-counter re-export (bench loops)
        os.rename(tmp, path)
        _fsync_dir(self.directory)
        self._flip_current(path)
        shutil.rmtree(path + _PARTS_SUFFIX, ignore_errors=True)

    def _flip_current(self, path):
        link = os.path.join(self.directory, "%s_current" % self.prefix)
        tmp_link = link + ".tmp"
        try:
            if os.path.lexists(tmp_link):
                os.remove(tmp_link)
            os.symlink(os.path.basename(path), tmp_link)
            os.replace(tmp_link, link)
        except OSError:
            pass

    def _report_tensor_sizes(self, path, man, stats, top=5):
        """The fattest-units diagnostic without the double-pickle: the
        manifest already measured every tensor, so just read it."""
        threshold = self.report_size_threshold
        if threshold is None:
            threshold = root.common.snapshot.get(
                "report_size_threshold", 64 << 20)
        threshold = int(threshold)
        if threshold <= 0 or stats["bytes_total"] < threshold:
            return
        sizes = sorted(((man.tensor_bytes(ref), ref,
                         tuple(man.tensors[ref]["shape"]))
                        for ref in man.tensors), reverse=True)
        lines = ["  %-12s %-20s %.1f MiB" % (ref, shape, sz / 1048576)
                 for sz, ref, shape in sizes[:top]]
        self.warning(
            "checkpoint %s is %.1f MiB (%.1f new after dedupe); "
            "fattest tensors:\n%s", path,
            stats["bytes_total"] / 1048576,
            stats["bytes_written"] / 1048576, "\n".join(lines))

    def gc(self, keep=None):
        """Drop chunks referenced by no retained checkpoint.  ``keep``
        limits which checkpoint dirs count as retained (default: all
        complete ones under the root)."""
        live = set()
        for ckpt in (keep if keep is not None
                     else list_checkpoints(self.directory)):
            live |= Manifest.load_dir(ckpt).digests()
        store = ChunkStore(os.path.join(self.directory, CHUNKS_DIR))
        return store.gc(live)

    @staticmethod
    def import_dir(path):
        return import_dir(path)


# -- generic object checkpoints (decode KV pools, tools) ----------------------

def save_state(directory, name, obj, min_tensor_bytes=1,
               chunk_bytes=None, meta=None, compresslevel=6):
    """Checkpoint an arbitrary picklable object whose tensor pytree
    leaves (numpy / jax Arrays) are sharded into the content-addressed
    store under ``directory``.  Returns the checkpoint dir path.
    An existing checkpoint of the same name is replaced."""
    os.makedirs(directory, exist_ok=True)
    t0 = time.perf_counter()
    store = ChunkStore(os.path.join(directory, CHUNKS_DIR))
    sink = TensorSink(min_bytes=max(int(min_tensor_bytes), 1))
    with extracting(sink):
        blob = dumps_extracting(obj, sink)
    if chunk_bytes is None:
        chunk_bytes = root.common.snapshot.get("chunk_bytes", 16 << 20)
    entries, stats = write_tensors(store, sink, int(chunk_bytes))
    store.fsync_dir()
    man = Manifest(tensors=entries, meta=dict(
        meta or {}, name=name, created=time.time()))
    path = os.path.join(directory, name + CKPT_SUFFIX)
    tmp = path + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    with open(os.path.join(tmp, TOPOLOGY), "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb",
                           compresslevel=compresslevel) as gz:
            gz.write(blob)
        raw.flush()
        os.fsync(raw.fileno())
    man.dump(os.path.join(tmp, MANIFEST))
    _fsync_dir(tmp)
    if os.path.isdir(path):
        shutil.rmtree(path)
    os.rename(tmp, path)
    _fsync_dir(directory)
    dt = time.perf_counter() - t0
    ck = _obs()
    ck["bytes"].inc(stats["bytes_written"])
    ck["deduped"].inc(stats["chunks_deduped"])
    ck["seconds"].labels(op="save").inc(dt)
    events.span("checkpoint.save", dt, path=path,
                bytes_written=stats["bytes_written"],
                chunks_deduped=stats["chunks_deduped"],
                tensors=len(entries))
    return path


def delete_checkpoint(path):
    """Remove one ``*.ckpt`` directory (a consumed session spill, a
    superseded state checkpoint).  Only the checkpoint's manifest +
    topology go away — its chunks are content-addressed and possibly
    shared, so reclaiming their bytes is the store GC's job
    (:meth:`SnapshotterToShards.gc_chunks`).  Returns True when
    something was deleted."""
    path = resolve_checkpoint(path)
    if not is_shard_checkpoint(path):
        raise ValueError("%r is not a shard checkpoint" % path)
    existed = os.path.isdir(path)
    shutil.rmtree(path, ignore_errors=True)
    return existed


def load_state(path):
    """Mirror of :func:`save_state`: the object with every tensor leaf
    resolved (host numpy by default)."""
    ckpt, man, reader = open_checkpoint(path)
    t0 = time.perf_counter()
    with restoring(reader):
        with gzip.open(os.path.join(ckpt, TOPOLOGY), "rb") as f:
            obj = ResolvingUnpickler(f, reader).load()
    dt = time.perf_counter() - t0
    _obs()["seconds"].labels(op="restore").inc(dt)
    events.span("checkpoint.restore", dt, path=ckpt,
                tensors=len(man.tensors), bytes=reader.bytes_read)
    return obj
