"""Content-addressed chunk store for sharded tensor checkpoints.

Durability follows ``compilecache/store.py``: every blob lands as
``*.tmp.<pid>`` + flush + fsync + atomic ``os.rename`` — a kill at any
point leaves either no chunk or a complete one, never a truncated file
at its final name.  Chunks are named by the sha256 of their content, so

- a chunk is written at most once no matter how many tensors (or how
  many consecutive checkpoints) contain the same bytes — that is the
  whole cross-checkpoint dedupe story; and
- a read can always verify itself; a mismatching chunk is *quarantined*
  (renamed aside with ``.corrupt``) so the evidence survives and the
  caller gets a hard error instead of silently wrong weights.

Unlike the compile cache, a failed WRITE raises: an executable cache
entry is an optimization, a checkpoint chunk is the data.
"""

import hashlib
import os

SUFFIX = ".chunk"


class CorruptChunkError(Exception):
    """A stored chunk no longer hashes to its name."""


def digest_of(data):
    return hashlib.sha256(data).hexdigest()


class ChunkStore:
    """sha256-hex -> bytes blobs under one flat directory."""

    def __init__(self, directory):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    def path_for(self, digest):
        return os.path.join(self.directory, digest + SUFFIX)

    def has(self, digest):
        return os.path.exists(self.path_for(digest))

    # -- write ---------------------------------------------------------------
    def put(self, data):
        """Persist one chunk; returns ``(digest, written_bytes)`` where
        ``written_bytes`` is 0 when the content was already stored (the
        dedupe hit).  ``data`` is any buffer (bytes/memoryview)."""
        data = memoryview(data)
        if data.ndim != 1 or data.format != "B":
            data = data.cast("B")   # byte view: len() must mean bytes
        digest = digest_of(data)
        path = self.path_for(digest)
        if os.path.exists(path):
            return digest, 0
        tmp = path + ".tmp.%d" % os.getpid()
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return digest, len(data)

    # -- read ----------------------------------------------------------------
    def get(self, digest):
        """The chunk bytes; verifies the content hash on every read and
        quarantines + raises on mismatch (bit rot, torn write that
        somehow reached its final name, operator error)."""
        path = self.path_for(digest)
        with open(path, "rb") as f:
            data = f.read()
        if digest_of(data) != digest:
            self.quarantine(digest)
            raise CorruptChunkError(
                "chunk %s... failed content verification (quarantined)"
                % digest[:16])
        return data

    def quarantine(self, digest):
        """Rename a bad chunk aside (``.corrupt``); idempotent."""
        path = self.path_for(digest)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            return False
        return True

    # -- accounting / gc -----------------------------------------------------
    def digests(self):
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return [n[:-len(SUFFIX)] for n in names if n.endswith(SUFFIX)]

    def total_bytes(self):
        total = 0
        for digest in self.digests():
            try:
                total += os.path.getsize(self.path_for(digest))
            except OSError:
                continue
        return total

    def gc(self, live_digests):
        """Drop every chunk not in ``live_digests`` (the union over all
        retained manifests).  Returns (chunks_removed, bytes_removed)."""
        live = set(live_digests)
        removed = freed = 0
        for digest in self.digests():
            if digest in live:
                continue
            path = self.path_for(digest)
            try:
                size = os.path.getsize(path)
                os.unlink(path)
            except OSError:
                continue
            removed += 1
            freed += size
        return removed, freed

    def fsync_dir(self):
        try:
            fd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass
