"""Checkpoint manifests: what tensors a checkpoint holds, as which chunks.

One checkpoint is a directory ``<prefix>[_suffix].<counter>.ckpt/``
containing

- ``manifest.json`` — per-tensor global shape/dtype/sharding plus the
  chunk list (global-coordinate offsets/shapes, sha256 digests, sizes);
- ``topology.pickle.gz`` — the workflow pickle with every large tensor
  replaced by a :class:`~veles_tpu.checkpoint.tensors.TensorStub`;
- ``part-<k>.json`` staging fragments while a multi-process save is in
  flight (merged into ``manifest.json`` by process 0).

Chunks themselves live in a SIBLING ``chunks/`` directory shared by all
checkpoints under one snapshot root — that sharing is what makes
unchanged tensors dedupe across consecutive checkpoints.  The directory
is written as ``*.ckpt.tmp`` and atomically renamed; a torn save can
only ever leave a ``.tmp`` partial (quarantined by the next writer) and
orphan chunks (garbage-collectable), never a listed-but-incomplete
checkpoint.
"""

import json
import os

FORMAT = 1
MANIFEST = "manifest.json"
TOPOLOGY = "topology.pickle.gz"
CHUNKS_DIR = "chunks"
CKPT_SUFFIX = ".ckpt"


class Manifest:
    def __init__(self, tensors=None, meta=None):
        self.tensors = dict(tensors or {})
        self.meta = dict(meta or {})

    def add(self, ref, entry):
        self.tensors[ref] = entry

    def digests(self):
        out = set()
        for e in self.tensors.values():
            for c in e["chunks"]:
                out.add(c["digest"])
        return out

    def tensor_bytes(self, ref):
        return sum(c["bytes"] for c in self.tensors[ref]["chunks"])

    def total_bytes(self):
        return sum(self.tensor_bytes(ref) for ref in self.tensors)

    def to_json(self):
        return {"format": FORMAT, "meta": self.meta,
                "tensors": self.tensors}

    def dump(self, path):
        """Plain write + fsync: atomicity comes from the enclosing
        ``*.ckpt.tmp`` directory rename, not per-file renames."""
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())

    @classmethod
    def load(cls, path):
        with open(path) as f:
            doc = json.load(f)
        if doc.get("format") != FORMAT:
            raise ValueError("unsupported checkpoint manifest format %r "
                             "in %s" % (doc.get("format"), path))
        return cls(tensors=doc.get("tensors", {}),
                   meta=doc.get("meta", {}))

    @classmethod
    def load_dir(cls, ckpt_dir):
        return cls.load(os.path.join(ckpt_dir, MANIFEST))

    def merge(self, other):
        """Union another process's part into this one (refs are
        process-disjoint except replicated jax tensors, where every
        process planned identical chunk lists — last wins)."""
        for ref, entry in other.tensors.items():
            mine = self.tensors.get(ref)
            if mine is None or not mine["chunks"]:
                self.tensors[ref] = entry
            elif entry["chunks"] and mine["chunks"] != entry["chunks"]:
                # disjoint shards of the same tensor: concatenate
                seen = {tuple(c["offset"]) for c in mine["chunks"]}
                mine["chunks"].extend(
                    c for c in entry["chunks"]
                    if tuple(c["offset"]) not in seen)
        return self


def list_checkpoints(directory):
    """Complete checkpoint dirs under a snapshot root, oldest first by
    counter (``*.ckpt`` containing a manifest; ``.tmp``/quarantined
    partials never listed)."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not name.endswith(CKPT_SUFFIX):
            continue
        path = os.path.join(directory, name)
        if not os.path.isdir(path) or \
                not os.path.exists(os.path.join(path, MANIFEST)):
            continue
        try:
            counter = int(name[:-len(CKPT_SUFFIX)].rsplit(".", 1)[1])
        except (IndexError, ValueError):
            counter = -1
        out.append((counter, path))
    return [path for _, path in sorted(out)]
