"""Lazily autovivifying configuration tree.

TPU-native re-design of the reference config system
(/root/reference/veles/config.py:60-152): a ``root`` singleton of attribute
nodes that spring into existence on first access, ``update()`` from nested
dicts, protected keys, per-workflow namespaces, and callable values resolved
at read time via ``get()``.  Values may also be :class:`Range` placeholders
consumed by the genetic optimizer (reference: veles/genetics/config.py);
``fix_config`` collapses them to their plain default for non-optimize runs
(reference: veles/__main__.py:721-723).
"""

import os


class Range:
    """A tuneable config value: a default plus an allowed range/choices.

    The genetic optimizer treats every ``Range`` found in the config tree as
    one gene; everyone else sees ``value``.
    """

    def __init__(self, value, *bounds):
        self.value = value
        if len(bounds) == 2 and not isinstance(bounds[0], (list, tuple)):
            self.min_value, self.max_value = bounds
            self.choices = None
        elif len(bounds) == 1 and isinstance(bounds[0], (list, tuple)):
            self.choices = list(bounds[0])
            self.min_value = self.max_value = None
        elif not bounds:
            self.min_value = self.max_value = value
            self.choices = None
        else:
            raise ValueError("Range(value, min, max) or Range(value, [choices])")

    def __repr__(self):
        if self.choices is not None:
            return "Range(%r, %r)" % (self.value, self.choices)
        return "Range(%r, %r, %r)" % (self.value, self.min_value, self.max_value)

    def __eq__(self, other):
        if isinstance(other, Range):
            return self.value == other.value
        return self.value == other


class Config:
    """One node of the config tree.  Attribute access autovivifies children."""

    _protected = frozenset(("update", "get", "keys", "items", "print_", "path"))

    def __init__(self, path):
        self.__dict__["_path"] = path

    # -- tree construction ---------------------------------------------------
    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        child = Config("%s.%s" % (self.__dict__["_path"], name))
        self.__dict__[name] = child
        return child

    def __setattr__(self, name, value):
        if name in Config._protected:
            raise AttributeError("'%s' is a protected Config key" % name)
        # NOTE: plain-dict assignment stays a plain dict on purpose
        # (data dicts may have non-string keys, and users compare the
        # value back with ==); tree consumers must accept either form
        # — see znicz/samples/__init__.py _cfg_dict
        self.__dict__[name] = value

    def __delattr__(self, name):
        self.__dict__.pop(name, None)

    # -- mapping-ish API -----------------------------------------------------
    def update(self, tree=None, **kwargs):
        """Recursively merge a nested dict (or kwargs) into this node."""
        if tree is None:
            tree = {}
        if not isinstance(tree, dict):
            raise TypeError("Config.update() takes a dict, got %r" % (tree,))
        tree = dict(tree)
        tree.update(kwargs)
        for key, value in tree.items():
            if key in Config._protected or key.startswith("_"):
                raise AttributeError(
                    "%r is a protected Config key" % key)
            if isinstance(value, dict):
                node = self.__dict__.get(key)
                if not isinstance(node, Config):
                    node = Config("%s.%s" % (self.__dict__["_path"], key))
                    self.__dict__[key] = node
                node.update(value)
            else:
                self.__dict__[key] = value
        return self

    def get(self, name, default=None):
        """Read a leaf; callables are invoked, Ranges collapse to .value."""
        value = self.__dict__.get(name, default)
        if isinstance(value, Config):
            return value
        if isinstance(value, Range):
            return value.value
        if callable(value):
            return value()
        return value

    def keys(self):
        return [k for k in self.__dict__ if not k.startswith("_")]

    def items(self):
        return [(k, self.__dict__[k]) for k in self.keys()]

    def __getitem__(self, name):
        try:
            return self.__dict__[name]
        except KeyError:
            raise KeyError("%s.%s" % (self.__dict__["_path"], name))

    def __contains__(self, name):
        return name in self.__dict__

    def __iter__(self):
        return iter(self.keys())

    @property
    def path(self):
        return self.__dict__["_path"]

    def todict(self):
        out = {}
        for k, v in self.items():
            out[k] = v.todict() if isinstance(v, Config) else v
        return out

    def print_(self, indent=0, file=None):
        import sys
        file = file or sys.stdout
        for k, v in sorted(self.items()):
            if isinstance(v, Config):
                print("%s%s:" % ("  " * indent, k), file=file)
                v.print_(indent + 1, file=file)
            else:
                print("%s%s: %r" % ("  " * indent, k, v), file=file)

    def __repr__(self):
        return "<Config %s: %s>" % (self.__dict__["_path"],
                                    ", ".join(self.keys()) or "(empty)")


def _fix_container(obj):
    """Collapse Ranges inside plain dict/list containers (layer configs
    are dicts in a list — the reference's process_config walked them too,
    genetics/config.py)."""
    if isinstance(obj, Range):
        return obj.value
    if isinstance(obj, dict):
        return {k: _fix_container(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_fix_container(v) for v in obj]
    return obj


def fix_config(cfg):
    """Collapse every Range in the tree to its plain default value."""
    for key, value in list(cfg.__dict__.items()):
        if key.startswith("_"):
            continue
        if isinstance(value, Config):
            fix_config(value)
        elif isinstance(value, (Range, dict, list)):
            cfg.__dict__[key] = _fix_container(value)


def _ranges_in_container(obj, prefix, out):
    if isinstance(obj, Range):
        out.append((prefix, obj))
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _ranges_in_container(v, "%s.%s" % (prefix, k), out)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _ranges_in_container(v, "%s.%d" % (prefix, i), out)


def get_config_ranges(cfg, prefix=None, out=None):
    """Collect (path, Range) pairs for the genetic optimizer, including
    Ranges nested in dict/list values (layer config lists)."""
    if out is None:
        out = []
    prefix = prefix if prefix is not None else cfg.path
    for key, value in cfg.__dict__.items():
        if key.startswith("_"):
            continue
        if isinstance(value, Config):
            get_config_ranges(value, "%s.%s" % (prefix, key), out)
        else:
            _ranges_in_container(value, "%s.%s" % (prefix, key), out)
    return out


def set_config_by_path(cfg, dotted, value):
    """Assign ``root.a.b.c = value`` given the dotted path string.
    Numeric segments index into lists; dict keys are traversed too, so
    GA paths like ``root.mnist.layers.0.<-.learning_rate`` resolve."""
    parts = dotted.split(".")
    if parts and parts[0] == "root":
        parts = parts[1:]
    node = cfg
    for p in parts[:-1]:
        if isinstance(node, list):
            node = node[int(p)]
        elif isinstance(node, dict):
            node = node[p]
        else:
            node = getattr(node, p)
    last = parts[-1]
    if isinstance(node, list):
        node[int(last)] = value
    elif isinstance(node, dict):
        node[last] = value
    elif isinstance(value, dict):
        # dict override merges as a Config subtree (so CLI overrides like
        # root.x.snapshotter={...} behave like config-file declarations)
        child = getattr(node, last)
        if isinstance(child, Config):
            child.update(value)
        else:
            setattr(node, last, value)
    else:
        setattr(node, last, value)


#: The global configuration tree (reference: veles/config.py:152).
root = Config("root")

_cache_dir = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
    "veles_tpu")

root.update({
    "common": {
        "dirs": {
            "cache": _cache_dir,
            "datasets": os.path.join(_cache_dir, "datasets"),
            "snapshots": os.path.join(_cache_dir, "snapshots"),
            "events": os.path.join(_cache_dir, "events"),
        },
        "engine": {
            # "tpu" | "cpu" | "auto"
            "backend": "auto",
            # matmul precision: 0 = default, 1 = float32 accumulation,
            # 2 = highest (mirrors the reference's GEMM PRECISION_LEVEL
            # 0/1/2 = plain/Kahan/multipartial, veles/config.py:245-248).
            "precision_level": 0,
            # preferred compute dtype on TPU
            "dtype": "float32",
            # whole-workflow compilation (veles_tpu/graphcomp/): trace
            # any link_from unit DAG into single compiled, donated XLA
            # programs; host units (loaders, deciders, plotters) stay
            # interpreted at region boundaries.  Default off: interpreted
            # dispatch is exactly unchanged until the knob is flipped.
            "graph_compile": False,
            # JAX's built-in persistent compilation cache, applied at
            # backend init (backends.py): one knob covers every jit the
            # executable cache (compilecache/) doesn't own.  None = off.
            "compilation_cache_dir": None,
            # don't persist XLA cache entries smaller than this
            "compilation_cache_min_entry_bytes": 0,
        },
        "compile_cache": {
            # persistent AOT executable cache + warmup manifests
            # (veles_tpu/compilecache/): serving bucket executables and
            # the fused train step deserialize instead of recompiling
            # on restart.  None = off (exact pre-cache behavior);
            # $VELES_COMPILE_CACHE_DIR overrides for child processes.
            "dir": None,
            "enabled": True,
            # size-budget LRU sweep over the store directory
            "max_bytes": 4 << 30,
            # serving warmup: compile the first manifest bucket
            # synchronously, the rest of the ladder on a background
            # thread (the server answers before the tail finishes)
            "background_warmup": False,
        },
        "autotune": {
            # persistent kernel/serving config tuning (veles_tpu/
            # autotune/): measured winners keyed by (site, shape class,
            # device kind, jax/jaxlib versions) live under ``dir`` and
            # kernel call sites resolve through them.  None = no store
            # configured — every site uses its hand-picked default,
            # byte-for-byte the pre-autotune behavior;
            # $VELES_AUTOTUNE_DIR overrides for child processes.
            "dir": None,
            "enabled": True,
        },
        "loader": {
            # background minibatch prefetch lookahead on the per-step
            # training path (loader/prefetch.py): how many minibatches a
            # worker thread prepares + device_puts ahead of the consumer.
            # 0 = exactly today's synchronous serving.
            "prefetch_depth": 2,
        },
        "snapshot": {
            # zero-stall checkpointing (snapshotter.py): capture on the
            # training thread, pickle+compress+fsync+rename on a writer
            # thread.  False = the exact old synchronous path (still
            # atomic: tmp-write + rename).
            "async_write": True,
            # gz/bz2/xz codec level: 9 buys ~nothing on float weights
            # and costs multiples in CPU time (bench.py snapshot stage)
            "compression_level": 6,
            # _report_size fattest-units diagnostic threshold, bytes
            # (0 disables)
            "report_size_threshold": 64 << 20,
            # snapshot backend: "pickle" (SnapshotterToFile, the
            # default — whole-workflow pickle, one host holds it all)
            # or "shards" (checkpoint/SnapshotterToShards — every
            # process writes its addressable shards as content-
            # addressed chunks; restores onto any mesh shape)
            "format": "pickle",
            # sharded backend: target chunk size for tensor bands
            "chunk_bytes": 16 << 20,
            # tensors smaller than this stay inline in the topology
            # pickle instead of becoming chunked shards
            "min_tensor_bytes": 65536,
        },
        "trace": {"enabled": False, "file": None},
        "timings": set(),
        "random_seed": 1234,
    },
})


def apply_site_config(cfg=None, paths=None):
    """Apply per-machine overrides: import ``site_config.py`` from each
    existing path (default: $VELES_TPU_SITE_CONFIG, the XDG config dir)
    and call its ``update(root)``.

    The reference loaded the same hook from its dist-config dir, the
    user dir, and the cwd at import time
    (/root/reference/veles/config.py:294-308); here it is an explicit
    call (the CLI runs it before workflow-module import) so library
    users and tests control when machine-local state enters the tree.
    The cwd is deliberately NOT searched (unlike the reference): a
    ``site_config.py`` in an untrusted working directory would execute
    arbitrary code on every CLI run — point $VELES_TPU_SITE_CONFIG or
    ``paths=`` at one explicitly instead.
    Returns the list of files applied."""
    import importlib.util
    cfg = cfg if cfg is not None else root
    if paths is None:
        paths = []
        env = os.environ.get("VELES_TPU_SITE_CONFIG")
        if env:
            paths.append(env)
        paths.append(os.path.join(
            os.environ.get("XDG_CONFIG_HOME",
                           os.path.expanduser("~/.config")),
            "veles_tpu"))
    env_explicit = os.environ.get("VELES_TPU_SITE_CONFIG")
    applied = []
    for path in paths:
        fname = path if path.endswith(".py") else os.path.join(
            path, "site_config.py")
        if not os.path.exists(fname):
            if env_explicit and path == env_explicit:
                # the optional search dirs skip silently, but a typo'd
                # explicit pointer must not silently drop site overrides
                raise FileNotFoundError(
                    "VELES_TPU_SITE_CONFIG=%r does not exist" % path)
            continue
        spec = importlib.util.spec_from_file_location(
            "veles_tpu_site_config_%d" % len(applied), fname)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        update = getattr(module, "update", None)
        if update is None:
            raise AttributeError(
                "%s must define update(root)" % fname)
        update(cfg)
        applied.append(fname)
    return applied
