"""Web status: live training status over HTTP.

TPU-native re-design of /root/reference/veles/web_status.py (:113-244):
the reference ran a separate Tornado server that masters POSTed
heartbeats to (``/update``) and browsers polled, garbage-collecting dead
masters.  Here a stdlib ``ThreadingHTTPServer`` runs in-process on a
daemon thread:

- ``GET /status``  → JSON of every registered workflow (name, epoch,
  metrics, per-unit timing, age);
- ``POST /update`` → external masters may still push heartbeats (kept
  for protocol parity — a multi-host launcher posts here);
- ``GET /``        → minimal HTML auto-refreshing view.

The ``StatusReporter`` unit updates the in-process registry once per
epoch; dead entries age out after ``gc_timeout`` like the reference's
garbage collection.
"""

import html as html_mod
import json
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .config import root
from .units import Unit


class StatusRegistry:
    """Thread-safe workflow-status store with age-out."""

    def __init__(self, gc_timeout=3600.0):
        # generous by default: reporters heartbeat once per EPOCH, and a
        # real epoch can take many minutes — aging out a live workflow
        # would invert the reference's dead-master GC intent
        self._lock = threading.Lock()
        self._entries = {}
        self.gc_timeout = gc_timeout

    def update(self, key, payload):
        payload = {k: v for k, v in payload.items()
                   if k not in ("t", "age")}  # reserved bookkeeping keys
        with self._lock:
            self._entries[key] = {**payload, "t": time.time()}

    def snapshot(self):
        now = time.time()
        with self._lock:
            self._entries = {k: v for k, v in self._entries.items()
                             if now - v["t"] < self.gc_timeout}
            return {k: {**v, "age": round(now - v["t"], 1)}
                    for k, v in self._entries.items()}


class _Handler(BaseHTTPRequestHandler):
    registry = None

    def log_message(self, *args):
        pass  # silent; the event log is the observability channel

    def _send(self, code, body, ctype="application/json"):
        data = body if isinstance(body, bytes) else body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        route = urllib.parse.urlparse(self.path).path
        if route == "/status":
            self._send(200, json.dumps(self.registry.snapshot(), indent=2))
        elif route == "/plots" or route.startswith("/plots/"):
            self._serve_plots(route)
        elif route == "/":
            rows = []
            for key, e in sorted(self.registry.snapshot().items()):
                rows.append(
                    "<tr><td>%s</td><td>%s</td><td>%s</td><td>%ss</td>"
                    "</tr>" % (key, e.get("epoch", "-"),
                               json.dumps(e.get("metrics", {})),
                               e.get("age", 0)))
            self._send(200, (
                "<html><head><meta http-equiv=refresh content=5>"
                "<title>veles_tpu status</title></head><body>"
                "<h2>Workflows</h2><table border=1>"
                "<tr><th>workflow</th><th>epoch</th><th>metrics</th>"
                "<th>age</th></tr>%s</table>"
                "<p><a href=\"/plots\">plots</a> · "
                "<a href=\"/status\">status JSON</a></p></body></html>"
                % "".join(rows)), "text/html")
        else:
            self._send(404, '{"error": "not found"}')

    def _serve_plots(self, route):
        """Minimal plots browser (the reference web/ dashboard role):
        /plots lists the plot artifacts in the plots directory; /plots/
        <name> serves the JSONL series or PNG render."""
        directory = root.common.dirs.get("plots", None)
        if not directory:
            # never fall back to CWD: that would serve arbitrary files
            # from the server process's working directory
            self._send(404, '{"error": "plots directory not configured '
                            '(set root.common.dirs.plots)"}')
            return
        rel = urllib.parse.unquote(route[len("/plots"):].lstrip("/"))
        if not rel:
            entries = []
            if os.path.isdir(directory):
                entries = sorted(os.listdir(directory))
            rows = "".join(
                '<li><a href="/plots/%s">%s</a></li>' %
                (urllib.parse.quote(name), html_mod.escape(name))
                for name in entries)
            self._send(200, ("<html><body><h2>Plots (%s)</h2><ul>%s</ul>"
                             "</body></html>") %
                       (html_mod.escape(directory), rows), "text/html")
            return
        safe = os.path.basename(rel)  # no traversal
        path = os.path.join(directory, safe)
        if not os.path.isfile(path):
            self._send(404, '{"error": "no such plot"}')
            return
        with open(path, "rb") as f:
            data = f.read()
        ctype = "image/png" if safe.endswith(".png") else "text/plain"
        self._send(200, data, ctype)

    def do_POST(self):
        if self.path != "/update":
            self._send(404, '{"error": "not found"}')
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("update body must be a JSON object")
            key = payload.pop("id", self.client_address[0])
            self.registry.update(key, payload)
            self._send(200, '{"ok": true}')
        except (ValueError, json.JSONDecodeError):
            self._send(400, '{"error": "bad json"}')


#: process-default registry: reporters publish here, servers serve it
default_registry = StatusRegistry()


class StatusServer:
    """In-process HTTP status server on a daemon thread."""

    def __init__(self, port=0, registry=None):
        self.registry = registry or default_registry
        handler = type("Handler", (_Handler,), {"registry": self.registry})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="veles-tpu-web-status")
        self._thread.start()

    def stop(self):
        """Release one reference; the socket closes when the last owner
        (shared via :func:`serve`) lets go."""
        self._refs = max(getattr(self, "_refs", 1) - 1, 0)
        if self._refs:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        _servers.pop(self.port, None)


_servers = {}


def serve(port=0, registry=None):
    """Start (or reuse, refcounted) the status server on ``port`` — a
    second Launcher in the same process must neither crash with
    EADDRINUSE nor have its endpoint killed by the first one's stop()."""
    if port and port in _servers:
        server = _servers[port]
        server._refs = getattr(server, "_refs", 1) + 1
        return server
    server = StatusServer(port, registry)
    server._refs = 1
    _servers[server.port] = server
    return server


class StatusReporter(Unit):
    """Per-epoch heartbeat into a StatusRegistry (reference masters
    POSTing /update, web_status.py:113)."""

    MAPPING = "status_reporter"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.runs_after_stop = True  # report the final epoch too
        self.registry = kwargs.get("registry") or default_registry
        self.epoch_ended = None      # linked
        self.epoch_number = None

    def link_loader(self, loader):
        self.link_attrs(loader, "epoch_ended", "epoch_number")
        self.gate_skip = ~loader.epoch_ended
        return self

    def run(self):
        wf = self._workflow
        metrics = {}
        try:
            metrics = wf.gather_results()
        except Exception:
            pass
        self.registry.update(wf.name, {
            "epoch": self.epoch_number,
            "metrics": {k: v for k, v in metrics.items()
                        if isinstance(v, (int, float, str)) and
                        not isinstance(v, bool)},
            "units": len(list(wf)),
        })
