"""Web status: live training status over HTTP.

TPU-native re-design of /root/reference/veles/web_status.py (:113-244):
the reference ran a separate Tornado server that masters POSTed
heartbeats to (``/update``) and browsers polled, garbage-collecting dead
masters.  Here a stdlib ``ThreadingHTTPServer`` runs in-process on a
daemon thread:

- ``GET /status``  → JSON of every registered workflow (name, epoch,
  metrics, per-unit timing, age);
- ``POST /update`` → external masters may still push heartbeats (kept
  for protocol parity — a multi-host launcher posts here);
- ``GET /``        → minimal HTML auto-refreshing view.

The ``StatusReporter`` unit updates the in-process registry once per
epoch; dead entries age out after ``gc_timeout`` like the reference's
garbage collection.
"""

import html as html_mod
import json
import math
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .config import root
from .units import Unit


class StatusRegistry:
    """Thread-safe workflow-status store with age-out and a bounded
    per-metric history (the dashboard's chart source)."""

    HISTORY = 200  # points kept per (workflow, metric)

    def __init__(self, gc_timeout=3600.0):
        # generous by default: reporters heartbeat once per EPOCH, and a
        # real epoch can take many minutes — aging out a live workflow
        # would invert the reference's dead-master GC intent
        self._lock = threading.Lock()
        self._entries = {}
        self._history = {}
        self.gc_timeout = gc_timeout

    def update(self, key, payload):
        payload = {k: v for k, v in payload.items()
                   if k not in ("t", "age")}  # reserved bookkeeping keys
        with self._lock:
            self._entries[key] = {**payload, "t": time.time()}
            hist = self._history.setdefault(key, {})
            for name, value in payload.get("metrics", {}).items():
                if isinstance(value, bool) or \
                        not isinstance(value, (int, float)) or \
                        not math.isfinite(value):
                    # a NaN/inf point would make /history invalid strict
                    # JSON and poison the sparkline's min/max
                    continue
                series = hist.setdefault(name, [])
                series.append(float(value))
                del series[:-self.HISTORY]

    def snapshot(self):
        now = time.time()
        with self._lock:
            self._entries = {k: v for k, v in self._entries.items()
                             if now - v["t"] < self.gc_timeout}
            self._history = {k: v for k, v in self._history.items()
                             if k in self._entries}
            return {k: {**v, "age": round(now - v["t"], 1)}
                    for k, v in self._entries.items()}

    def history(self):
        with self._lock:
            return {k: {m: list(s) for m, s in hist.items()}
                    for k, hist in self._history.items()}


class _Handler(BaseHTTPRequestHandler):
    registry = None

    def log_message(self, *args):
        pass  # silent; the event log is the observability channel

    def _send(self, code, body, ctype="application/json"):
        data = body if isinstance(body, bytes) else body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        route = urllib.parse.urlparse(self.path).path
        if route == "/status":
            # workflow heartbeats merged with the process-global metrics
            # registry under the reserved "metrics" key — one JSON view
            # of everything this process measures
            from .observability.registry import REGISTRY
            payload = dict(self.registry.snapshot())
            payload["metrics"] = REGISTRY.snapshot()
            self._send(200, json.dumps(payload, indent=2))
        elif route == "/metrics":
            # Prometheus text exposition 0.0.4: training (step profiler,
            # unit timings) and serving (request/batch counters,
            # latency histograms) from the SAME registry
            from .observability.registry import REGISTRY
            self._send(200, REGISTRY.render_prometheus(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif route == "/history":
            self._send(200, json.dumps(self.registry.history(), indent=2))
        elif route == "/plots" or route.startswith("/plots/"):
            self._serve_plots(route)
        elif route == "/logs":
            self._serve_logs()
        elif route == "/forge" or route.startswith("/forge/"):
            self._serve_forge(route)
        elif route == "/bboxer" or route.startswith("/bboxer/"):
            self._serve_bboxer(route)
        elif route == "/":
            self._send(200, self._dashboard(), "text/html")
        else:
            self._send(404, '{"error": "not found"}')

    def _serve_logs(self, tail=300):
        """The reference's ``/logs.html`` Mongo browser, over the JSONL
        event log: last ``tail`` trace records as an HTML table."""
        from .logger import events
        path = getattr(events, "path", None)
        if not path or not os.path.isfile(path):
            self._send(404, json.dumps(
                {"error": "no event log yet (tracing writes %s)"
                          % (path or "events dir")}))
            return
        # bounded tail read: a long run's event log is huge — never
        # materialize the whole file in the request thread
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 512 * 1024))
            chunk = f.read().decode("utf-8", "replace")
        lines = chunk.splitlines()
        if size > 512 * 1024 and lines:
            lines = lines[1:]  # drop the partial first line
        lines = lines[-tail:]
        esc = html_mod.escape
        rows = []
        for ln in lines:
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue  # foreign JSONL line; skip, don't 500
            # Chrome-trace fields (logger.EventLog): ts/dur in us;
            # foreign dicts may carry non-numeric values — skip, as
            # above, rather than 500 the whole page
            ts, dur = rec.get("ts", 0), rec.get("dur")
            if not isinstance(ts, (int, float)) or \
                    not isinstance(dur, (int, float, type(None))):
                continue
            rows.append(
                "<tr><td>%.3fs</td><td>%s</td><td>%s</td><td>%s</td>"
                "<td><code>%s</code></td></tr>"
                % (ts / 1e6, esc(str(rec.get("name"))),
                   esc(str(rec.get("ph", ""))),
                   esc("" if dur is None else "%.4fs" % (dur / 1e6)),
                   esc(json.dumps(rec.get("args", {}), default=str))
                   if rec.get("args") else ""))
        self._send(200, (
            "<!DOCTYPE html><html><head><title>veles_tpu logs</title>"
            "<style>body{font-family:sans-serif;margin:1.5em}"
            "table{border-collapse:collapse}td,th{border:1px solid "
            "#ccc;padding:.2em .5em;font-size:.85em}</style></head>"
            "<body><h2>Event log (last %d of %s)</h2>"
            "<table><tr><th>t</th><th>name</th><th>ph</th>"
            "<th>duration</th><th>args</th></tr>%s</table>"
            "</body></html>" % (len(rows), esc(path), "".join(rows))),
            "text/html")

    _IMG_EXT = {".png": "image/png", ".jpg": "image/jpeg",
                ".jpeg": "image/jpeg", ".bmp": "image/bmp",
                ".gif": "image/gif"}

    @staticmethod
    def _bboxer_dir():
        from .config import root
        return root.common.bboxer.get("image_dir", None)

    @classmethod
    def _bboxer_store(cls, image_dir):
        return os.path.join(image_dir, "bboxes.json")

    def _serve_bboxer(self, route):
        """Bounding-box annotation tool (the role of the reference's
        node bboxer app, /root/reference/web/projects/bboxer/src/js,
        rebuilt server-rendered and dependency-free): ``/bboxer`` is a
        canvas UI over the images in ``root.common.bboxer.image_dir``;
        drag to draw, boxes persist per image to ``bboxes.json`` in
        the same directory via POST /bboxer/save.  ``/bboxer/data``
        returns {images, boxes}; ``/bboxer/img/<name>`` serves one
        image (basenames only — no path traversal)."""
        image_dir = self._bboxer_dir()
        if not image_dir or not os.path.isdir(image_dir):
            self._send(404, json.dumps(
                {"error": "set root.common.bboxer.image_dir to an "
                          "image directory to annotate"}))
            return
        if route == "/bboxer":
            self._send(200, _BBOXER_HTML, "text/html")
            return
        if route == "/bboxer/data":
            images = sorted(
                f for f in os.listdir(image_dir)
                if os.path.splitext(f)[1].lower() in self._IMG_EXT)
            boxes = {}
            store = self._bboxer_store(image_dir)
            if os.path.isfile(store):
                try:
                    with open(store) as f:
                        boxes = json.load(f)
                except ValueError:
                    boxes = {}
            self._send(200, json.dumps(
                {"images": images, "boxes": boxes}))
            return
        if route.startswith("/bboxer/img/"):
            name = os.path.basename(
                urllib.parse.unquote(route[len("/bboxer/img/"):]))
            ext = os.path.splitext(name)[1].lower()
            path = os.path.join(image_dir, name)
            if ext not in self._IMG_EXT or not os.path.isfile(path):
                self._send(404, '{"error": "no such image"}')
                return
            with open(path, "rb") as f:
                self._send(200, f.read(), self._IMG_EXT[ext])
            return
        self._send(404, '{"error": "not found"}')

    def _serve_forge(self, route):
        """Forge model-marketplace browser (the role of the reference's
        node/gulp forge app, /root/reference/web/projects/forge/src/js,
        rebuilt server-rendered and dependency-free): ``/forge`` lists
        every model/version in the configured registry with download
        links; ``/forge/<name>/<ver>/package.zip`` serves the package,
        ``.../manifest.json`` the manifest.  The registry directory is
        ``root.common.dirs.forge`` (a ForgeStore layout — the same one
        ``python -m veles_tpu.forge serve`` publishes)."""
        from .forge import ForgeStore
        esc = html_mod.escape
        directory = root.common.dirs.get("forge", None)
        if not directory or not os.path.isdir(directory):
            self._send(404, '{"error": "forge directory not configured '
                            '(set root.common.dirs.forge)"}')
            return
        store = ForgeStore(directory)
        parts = [p for p in route[len("/forge"):].split("/") if p]
        if parts:
            try:
                if len(parts) != 3 or parts[2] not in ("package.zip",
                                                       "manifest.json"):
                    raise KeyError("bad forge path")
                name, version, leaf = parts
                if leaf == "manifest.json":
                    self._send(200, json.dumps(
                        store.manifest(name, version), indent=2))
                    return
                with open(store.package_path(name, version), "rb") as f:
                    self._send(200, f.read(), "application/zip")
            except (KeyError, OSError, ValueError) as e:
                # ValueError: a corrupt manifest.json must 404 its own
                # entry, not 500 the connection
                self._send(404, json.dumps({"error": str(e)}))
            return
        rows = []
        for mf in store.list():
            name = str(mf.get("name", "?"))
            version = str(mf.get("version", "?"))
            quoted = "%s/%s" % (urllib.parse.quote(name),
                                urllib.parse.quote(version))
            extra = {k: v for k, v in mf.items()
                     if k not in ("name", "version", "uploaded", "size")}
            rows.append(
                "<tr><td><b>%s</b></td><td>%s</td><td>%s</td>"
                "<td>%.1f&nbsp;KiB</td><td><code>%s</code></td>"
                '<td><a href="/forge/%s/package.zip">fetch</a> · '
                '<a href="/forge/%s/manifest.json">manifest</a></td></tr>'
                % (esc(name), esc(version),
                   esc(time.strftime(
                       "%Y-%m-%d %H:%M",
                       time.localtime(float(mf.get("uploaded", 0))))),
                   float(mf.get("size", 0)) / 1024.0,
                   esc(json.dumps(extra, default=str)) if extra else "",
                   quoted, quoted))
        self._send(200, (
            "<!DOCTYPE html><html><head><title>veles_tpu forge</title>"
            "<style>body{font-family:sans-serif;margin:1.5em}"
            "table{border-collapse:collapse}td,th{border:1px solid "
            "#ccc;padding:.25em .6em;font-size:.9em}</style></head>"
            "<body><h2>Forge registry (%s)</h2>"
            "<table><tr><th>model</th><th>version</th><th>uploaded</th>"
            "<th>size</th><th>metadata</th><th></th></tr>%s</table>"
            "<p>%d package(s) · <a href=\"/\">dashboard</a></p>"
            "</body></html>"
            % (esc(directory), "".join(rows), len(rows))), "text/html")

    @staticmethod
    def _sparkline(series, w=160, h=36):
        """Inline-SVG polyline of a metric series (no JS, no deps)."""
        if len(series) < 2:
            return '<svg width="%d" height="%d"></svg>' % (w, h)
        lo, hi = min(series), max(series)
        span = (hi - lo) or 1.0
        pts = " ".join(
            "%.1f,%.1f" % (i * (w - 4) / (len(series) - 1) + 2,
                           h - 3 - (v - lo) / span * (h - 6))
            for i, v in enumerate(series))
        return ('<svg width="%d" height="%d"><polyline points="%s" '
                'fill="none" stroke="#26c" stroke-width="1.5"/></svg>'
                % (w, h, pts))

    def _dashboard(self):
        """The live view: per workflow a status row plus one sparkline
        per numeric metric across its heartbeat history (the reference
        web/ dashboard's chart role, dependency-free)."""
        esc = html_mod.escape
        history = self.registry.history()
        sections = []
        for key, e in sorted(self.registry.snapshot().items()):
            charts = "".join(
                "<figure><figcaption>%s<br><small>last %s</small>"
                "</figcaption>%s</figure>"
                % (esc(name), esc("%.6g" % series[-1]),
                   self._sparkline(series))
                for name, series in sorted(
                    history.get(key, {}).items()))
            graph = e.get("graph")
            graph_html = (
                "<details><summary>unit graph (dot)</summary>"
                "<pre>%s</pre></details>" % esc(str(graph))
                if graph else "")
            sections.append(
                "<section><h3>%s</h3><p>epoch %s · %ss ago · %s units"
                "</p><p><code>%s</code></p><div class=row>%s</div>%s"
                "</section>"
                % (esc(str(key)), esc(str(e.get("epoch", "-"))),
                   esc(str(e.get("age", 0))),
                   esc(str(e.get("units", "-"))),
                   # CURRENT metrics verbatim — string metrics and
                   # history-less externals must stay visible here
                   esc(json.dumps(e.get("metrics", {}), default=str)),
                   charts, graph_html))
        return (
            "<!DOCTYPE html><html><head>"
            "<meta http-equiv=refresh content=5>"
            "<title>veles_tpu status</title><style>"
            "body{font-family:sans-serif;margin:1.5em}"
            "figure{display:inline-block;margin:.4em;text-align:center}"
            "figcaption{font-size:.75em}section{border-bottom:1px solid "
            "#ddd;padding:.5em 0}.row{display:flex;flex-wrap:wrap}"
            "</style></head><body><h2>Workflows</h2>%s"
            "<p><a href=\"/plots\">plots</a> · "
            "<a href=\"/logs\">logs</a> · "
            "<a href=\"/forge\">forge</a> · "
            "<a href=\"/bboxer\">bboxer</a> · "
            "<a href=\"/status\">status JSON</a> · "
            "<a href=\"/history\">history JSON</a> · "
            "<a href=\"/metrics\">metrics (prometheus)</a></p>"
            "</body></html>"
            % ("".join(sections) or "<p>no workflows reporting</p>"))

    def _serve_plots(self, route):
        """Minimal plots browser (the reference web/ dashboard role):
        /plots lists the plot artifacts in the plots directory; /plots/
        <name> serves the JSONL series or PNG render."""
        directory = root.common.dirs.get("plots", None)
        if not directory:
            # never fall back to CWD: that would serve arbitrary files
            # from the server process's working directory
            self._send(404, '{"error": "plots directory not configured '
                            '(set root.common.dirs.plots)"}')
            return
        rel = urllib.parse.unquote(route[len("/plots"):].lstrip("/"))
        if not rel:
            entries = []
            if os.path.isdir(directory):
                entries = sorted(os.listdir(directory))
            rows = "".join(
                '<li><a href="/plots/%s">%s</a></li>' %
                (urllib.parse.quote(name), html_mod.escape(name))
                for name in entries)
            self._send(200, ("<html><body><h2>Plots (%s)</h2><ul>%s</ul>"
                             "</body></html>") %
                       (html_mod.escape(directory), rows), "text/html")
            return
        safe = os.path.basename(rel)  # no traversal
        path = os.path.join(directory, safe)
        if not os.path.isfile(path):
            self._send(404, '{"error": "no such plot"}')
            return
        with open(path, "rb") as f:
            data = f.read()
        ctype = "image/png" if safe.endswith(".png") else "text/plain"
        self._send(200, data, ctype)

    def do_POST(self):
        if self.path == "/bboxer/save":
            self._bboxer_save()
            return
        if self.path != "/update":
            self._send(404, '{"error": "not found"}')
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("update body must be a JSON object")
            key = payload.pop("id", self.client_address[0])
            self.registry.update(key, payload)
            self._send(200, '{"ok": true}')
        except (ValueError, json.JSONDecodeError):
            self._send(400, '{"error": "bad json"}')

    def _bboxer_save(self):
        """POST {image, boxes: [[x, y, w, h, label], ...]} — replaces
        that image's box list in bboxes.json (atomic rewrite)."""
        image_dir = self._bboxer_dir()
        if not image_dir or not os.path.isdir(image_dir):
            self._send(404, '{"error": "bboxer not configured"}')
            return
        length = int(self.headers.get("Content-Length", 0))
        if length > _BBOXER_MAX_BODY:
            # bboxes.json is rewritten whole on every save: an oversized
            # body would balloon the store (and buffer in RAM) — no
            # legitimate box list comes anywhere near this
            self._send(413, '{"error": "bbox payload too large"}')
            return
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            name = os.path.basename(str(payload["image"]))
            boxes = payload["boxes"]
            if not isinstance(boxes, list) or not all(
                    isinstance(b, list) and len(b) == 5 and
                    all(isinstance(c, (int, float)) for c in b[:4]) and
                    isinstance(b[4], str) and
                    len(b[4]) <= _BBOXER_MAX_LABEL
                    for b in boxes):
                raise ValueError("boxes must be [x, y, w, h, label:str]")
        except (KeyError, ValueError, TypeError):
            self._send(400, '{"error": "bad bbox payload"}')
            return
        store = self._bboxer_store(image_dir)
        # the UI fires an async save per mouseup and the server is
        # threaded: serialize the read-modify-write or a concurrent
        # save of another image silently vanishes from disk
        with _bboxer_lock:
            data = {}
            if os.path.isfile(store):
                try:
                    with open(store) as f:
                        data = json.load(f)
                except ValueError:
                    data = {}
            data[name] = boxes
            tmp = store + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1)
            os.replace(tmp, store)
        self._send(200, '{"ok": true}')


#: /bboxer/save hardening: labels are persisted verbatim into
#: bboxes.json and echoed back into the canvas UI — cap them, and bound
#: the whole body (the server is threaded; each request buffers its
#: body in RAM before parsing)
_BBOXER_MAX_LABEL = 256
_BBOXER_MAX_BODY = 1 << 20

#: the bboxer canvas UI (single self-contained page, no toolchain —
#: the reference built this as a node/gulp app)
#: serializes /bboxer/save read-modify-writes (threaded server)
_bboxer_lock = threading.Lock()

_BBOXER_HTML = """<!doctype html><html><head><title>bboxer</title>
<style>body{font-family:sans-serif;margin:1em}#list{float:left;
width:14em;overflow:auto;max-height:80vh}#list a{display:block;
padding:.15em .4em;text-decoration:none;color:#036}#list a.cur
{background:#def}#work{margin-left:15em}canvas{border:1px solid #999;
cursor:crosshair;max-width:100%}#boxes td{border:1px solid #ccc;
padding:.1em .4em;font-size:.85em}</style></head><body>
<h2>bboxer</h2><div id=list></div><div id=work>
<label>label: <input id=label value=object size=12></label>
<button id=undo>undo box</button> <span id=msg></span><br>
<canvas id=cv></canvas><table id=boxes></table></div><script>
let images=[], boxesAll={}, cur=null, img=new Image(), drag=null;
const cv=document.getElementById('cv'), ctx=cv.getContext('2d');
function boxes(){ return boxesAll[cur] = boxesAll[cur] || []; }
function draw(){ if(!img.complete) return;
 cv.width=img.naturalWidth; cv.height=img.naturalHeight;
 ctx.drawImage(img,0,0); ctx.lineWidth=2; ctx.font='13px sans-serif';
 for(const b of boxes()){ ctx.strokeStyle='#e33';
  ctx.strokeRect(b[0],b[1],b[2],b[3]); ctx.fillStyle='#e33';
  ctx.fillText(b[4],b[0]+3,b[1]+13); }
 if(drag){ ctx.strokeStyle='#39e';
  ctx.strokeRect(drag[0],drag[1],drag[2]-drag[0],drag[3]-drag[1]); }
 const t=document.getElementById('boxes');
 t.textContent='';  /* rebuild via textContent: labels are user data */
 for(const b of boxes()){ const tr=t.insertRow();
  for(const x of b){ tr.insertCell().textContent =
    typeof x=='number' ? Math.round(x) : x; } } }
function pos(e){ const r=cv.getBoundingClientRect();
 return [ (e.clientX-r.left)*cv.width/r.width,
          (e.clientY-r.top)*cv.height/r.height ]; }
cv.onmousedown=e=>{ const p=pos(e); drag=[p[0],p[1],p[0],p[1]]; };
cv.onmousemove=e=>{ if(!drag) return; const p=pos(e);
 drag[2]=p[0]; drag[3]=p[1]; draw(); };
cv.onmouseup=e=>{ if(!drag) return;
 const x=Math.min(drag[0],drag[2]), y=Math.min(drag[1],drag[3]),
       w=Math.abs(drag[2]-drag[0]), h=Math.abs(drag[3]-drag[1]);
 drag=null; if(w>3&&h>3){ boxes().push([x,y,w,h,
  document.getElementById('label').value||'object']); save(); }
 draw(); };
document.getElementById('undo').onclick=()=>{ boxes().pop(); save();
 draw(); };
function save(){ fetch('/bboxer/save',{method:'POST',
 body:JSON.stringify({image:cur,boxes:boxes()})}).then(r=>
 document.getElementById('msg').textContent =
   r.ok ? 'saved' : 'save failed'); }
function show(name){ cur=name; img=new Image();
 img.onload=draw; img.src='/bboxer/img/'+encodeURIComponent(name);
 for(const a of document.querySelectorAll('#list a'))
   a.className = a.textContent==name ? 'cur' : ''; }
fetch('/bboxer/data').then(r=>r.json()).then(d=>{
 images=d.images; boxesAll=d.boxes||{};
 const l=document.getElementById('list');
 for(const n of images){ const a=document.createElement('a');
  a.href='#'; a.textContent=n;  /* filenames are untrusted: no HTML */
  a.onclick=e=>{ e.preventDefault(); show(n); };
  l.appendChild(a); }
 if(images.length) show(images[0]); });
</script></body></html>"""

#: process-default registry: reporters publish here, servers serve it
default_registry = StatusRegistry()


class StatusServer:
    """In-process HTTP status server on a daemon thread."""

    def __init__(self, port=0, registry=None):
        self.registry = registry or default_registry
        handler = type("Handler", (_Handler,), {"registry": self.registry})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="veles-tpu-web-status")
        self._thread.start()

    def stop(self):
        """Release one reference; the socket closes when the last owner
        (shared via :func:`serve`) lets go."""
        self._refs = max(getattr(self, "_refs", 1) - 1, 0)
        if self._refs:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        _servers.pop(self.port, None)


_servers = {}


def serve(port=0, registry=None):
    """Start (or reuse, refcounted) the status server on ``port`` — a
    second Launcher in the same process must neither crash with
    EADDRINUSE nor have its endpoint killed by the first one's stop()."""
    if port and port in _servers:
        server = _servers[port]
        server._refs = getattr(server, "_refs", 1) + 1
        return server
    server = StatusServer(port, registry)
    server._refs = 1
    _servers[server.port] = server
    return server


class StatusReporter(Unit):
    """Per-epoch heartbeat into a StatusRegistry (reference masters
    POSTing /update, web_status.py:113)."""

    MAPPING = "status_reporter"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.runs_after_stop = True  # report the final epoch too
        self.registry = kwargs.get("registry") or default_registry
        self.epoch_ended = None      # linked
        self.epoch_number = None
        self._graph_ = None          # computed once at first heartbeat

    def link_loader(self, loader):
        self.link_attrs(loader, "epoch_ended", "epoch_number")
        self.gate_skip = ~loader.epoch_ended
        return self

    def run(self):
        wf = self._workflow
        metrics = {}
        try:
            metrics = wf.gather_results()
        except Exception:
            pass
        if self._graph_ is None:
            # the reference heartbeat carried the workflow graph
            # (web_status.py:113); static after build — compute once,
            # and NEVER let a cosmetic failure kill the training run
            try:
                self._graph_ = wf.generate_graph()
            except Exception:
                self._graph_ = ""
        self.registry.update(wf.name, {
            "epoch": self.epoch_number,
            "metrics": {k: v for k, v in metrics.items()
                        if isinstance(v, (int, float, str)) and
                        not isinstance(v, bool)},
            "units": len(list(wf)),
            "graph": self._graph_,
        })
