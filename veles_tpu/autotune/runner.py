"""Measurement runner: fresh subprocess per candidate, hard caps.

Every candidate is measured by :mod:`veles_tpu.autotune.probe` in a
FRESH subprocess (the tools/cold_start.py / tools/graph_bench.py
pattern): a Mosaic compile that wedges, an OOM, or a crash kills one
candidate, never the tuning run — and each candidate compiles in a
pristine process so no warm JAX state flatters late candidates.

Isolation is a full PROCESS GROUP: children start in their own session
(``start_new_session=True``) and a timeout kills the whole group with
SIGKILL — a hung Pallas compile, a SIGSTOP'd child, or a grandchild the
probe spawned can never leak past the runner's hard cap.

Ranking is drift-robust: every probe measures its candidate AND the
site's hand-picked default config in the same process with interleaved
min-of-windows timing, and candidates are ranked by that in-process
ratio — machine-load drift between probes cancels instead of picking
the winner.  A candidate whose correctness gate fails is discarded no
matter how fast it ran: a fast-but-wrong config can never win.
"""

import json
import os
import signal
import subprocess
import sys
import time

from ..logger import events
from ..observability.registry import REGISTRY
from . import space as _space
from .dispatch import default_store

_c_tunes = REGISTRY.counter(
    "veles_autotune_tunes_total", "Completed tune_site runs")
_c_candidates = REGISTRY.counter(
    "veles_autotune_candidates_total", "Candidate measurements launched")
_c_gate_failures = REGISTRY.counter(
    "veles_autotune_gate_failures_total",
    "Candidates discarded because their correctness gate failed")
_c_timeouts = REGISTRY.counter(
    "veles_autotune_timeouts_total",
    "Candidate probes killed at the wall-clock cap (whole process "
    "group)")


def run_isolated(argv, timeout, env=None, cwd=None):
    """Run ``argv`` in its own process group under a hard wall-clock
    cap.  On timeout the WHOLE group gets SIGKILL — a stopped child or
    a spawned grandchild dies with it.  Returns
    ``(returncode, stdout, stderr, timed_out)`` (text, never raises
    for timeouts)."""
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=env, cwd=cwd, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout)
        return proc.returncode, out.decode(errors="replace"), \
            err.decode(errors="replace"), False
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        out, err = proc.communicate()
        return proc.returncode, out.decode(errors="replace"), \
            err.decode(errors="replace"), True


def _kill_group(proc):
    """SIGKILL the child's whole process group (it is its own session
    leader), then the child directly as a belt-and-braces fallback."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (OSError, ProcessLookupError):
        pass
    try:
        proc.kill()
    except OSError:
        pass


def _last_json_line(text):
    for raw in reversed(text.strip().splitlines()):
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            return json.loads(raw)
        except ValueError:
            continue
    return None


def measure_candidate(site, config, ctx=None, *, timeout=120.0,
                      env=None):
    """One candidate in one fresh isolated subprocess -> the probe's
    JSON dict, or ``{"ok": False, "error": ...}``."""
    argv = [sys.executable, "-m", "veles_tpu.autotune.probe",
            "--site", site, "--config", json.dumps(config)]
    if ctx:
        argv += ["--ctx", json.dumps(ctx)]
    env = dict(os.environ if env is None else env)
    # the probe imports veles_tpu relative to the repo, like the tools
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    rc, out, err, timed_out = run_isolated(argv, timeout, env=env)
    if timed_out:
        _c_timeouts.inc()
        return {"ok": False, "config": config,
                "error": "timeout after %.0fs (process group killed)"
                         % timeout}
    line = _last_json_line(out)
    if line is None:
        return {"ok": False, "config": config,
                "error": "probe exit %d, no JSON: %s"
                         % (rc, err.strip()[-300:])}
    return line


def tune_site(site, ctx=None, *, store=None, timeout=120.0, env=None,
              measure=None, log_fn=None):
    """Measure every candidate of ``site`` for ``ctx``, persist the
    gated winner, and return the stored record (None when nothing
    measured successfully — dispatch then keeps the default).

    ``measure(site, config, ctx)`` is injectable for tests; the real
    one is a fresh-subprocess probe per candidate.
    """
    sp = _space.site(site)
    ctx = dict(ctx or {})
    shape_class = sp.shape_class(ctx)
    candidates = sp.candidates(ctx)
    say = log_fn or (lambda msg: None)
    if measure is None:
        measure = lambda s, c, x: measure_candidate(  # noqa: E731
            s, c, x, timeout=timeout, env=env)
    t_start = time.perf_counter()
    results = []
    for config in candidates:
        _c_candidates.inc()
        t0 = time.perf_counter()
        res = measure(site, config, ctx)
        dt = time.perf_counter() - t0
        res = dict(res or {})
        res.setdefault("config", config)
        ok = bool(res.get("ok"))
        gate = res.get("gate", "unmeasured")
        if ok and gate != "passed":
            _c_gate_failures.inc()
        events.span("autotune.candidate", dt, site=site,
                    shape_class=shape_class, config=json.dumps(config),
                    ok=ok, gate=gate)
        say("%s %s: %s%s" % (
            site, json.dumps(config, sort_keys=True),
            "score %.3f" % res["score"]
            if ok and gate == "passed" and "score" in res
            else res.get("error", gate),
            " (gate %s)" % gate if ok and gate != "passed" else ""))
        results.append(res)
    # only gated, successfully measured candidates can win
    viable = [r for r in results
              if r.get("ok") and r.get("gate") == "passed"
              and "score" in r]
    total_dt = time.perf_counter() - t_start
    if not viable:
        events.span("autotune.tune", total_dt, site=site,
                    shape_class=shape_class,
                    candidates=len(candidates), winner="none")
        say("%s: no viable candidate (of %d) — keeping the default"
            % (site, len(candidates)))
        return None
    # score = candidate seconds / reference seconds, both measured
    # interleaved in the SAME process — lower is better.  The reference
    # workload is fixed per site (the default config for lrn/serving,
    # the dense oracle for the attention kernels), so cross-probe
    # machine drift divides out and scores compare across subprocesses.
    winner = min(viable, key=lambda r: r["score"])
    # speedup vs HAND-PICKED = default candidate's score / winner's
    # (each normalized by its own in-process reference).  candidates[0]
    # is always the declared default; if its probe failed, fall back to
    # 1/score, exact whenever the reference IS the default config.
    default_score = next(
        (r["score"] for r in viable if r["config"] == candidates[0]),
        None)
    if default_score is not None and winner["score"] > 0:
        speedup = default_score / winner["score"]
    else:
        speedup = 1.0 / winner["score"] if winner["score"] > 0 else 0.0
    if store is None:
        store = default_store()
    record = None
    if store is not None:
        record = store.put(
            site, shape_class, winner["config"], default=sp.default,
            speedup=speedup, gate="passed",
            baseline_s=winner.get("ref_s"),
            best_s=winner.get("cand_s"),
            candidates_tried=len(results),
            extra={"viable": len(viable),
                   "gate_failures": sum(
                       1 for r in results
                       if r.get("ok") and r.get("gate") != "passed")})
    _c_tunes.inc()
    events.span("autotune.tune", total_dt, site=site,
                shape_class=shape_class, candidates=len(candidates),
                winner=json.dumps(winner["config"], sort_keys=True),
                speedup=round(speedup, 3))
    say("%s/%s: winner %s, %.2fx vs hand-picked (%d/%d candidates "
        "viable)" % (site, shape_class,
                     json.dumps(winner["config"], sort_keys=True),
                     speedup, len(viable), len(results)))
    return record
