"""One candidate, one fresh process: measure + correctness-gate it.

``python -m veles_tpu.autotune.probe --site S --config JSON [--ctx
JSON]`` builds the site's op with the candidate configuration, checks
its output against the site's *oracle* (the dense/numpy reference the
tests already trust — NOT the default config, so a systematically
wrong pair can't vouch for itself), then times the candidate AND the
site's hand-picked default config with interleaved min-of-windows
timing in this same process.  Emits ONE JSON line::

    {"ok": true, "site": ..., "config": {...}, "gate": "passed",
     "cand_s": ..., "ref_s": ..., "score": cand_s / ref_s}

``score`` is the in-process candidate/default time ratio — the runner
ranks by it so machine-load drift between probe processes cancels.  A
gate other than ``"passed"`` disqualifies the candidate regardless of
its score.  Any exception still prints a parseable ``{"ok": false}``
line (the runner treats it as a discarded candidate).
"""

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _timed_pair(cand_fn, ref_fn, reps, windows):
    """Interleaved min-of-windows seconds for (candidate, reference):
    alternating window order cancels monotone load drift, the min
    discards contended windows (the bench.py discipline)."""
    cand_fn()
    ref_fn()                    # both warm (compiles outside timing)
    cand_times, ref_times = [], []
    for w in range(max(int(windows), 1)):
        pairs = [(cand_fn, cand_times), (ref_fn, ref_times)]
        if w % 2:
            pairs.reverse()
        for fn, acc in pairs:
            t0 = time.perf_counter()
            for _ in range(max(int(reps), 1)):
                fn()
            acc.append((time.perf_counter() - t0) / max(int(reps), 1))
    return min(cand_times), min(ref_times)


def _gate(ok, detail=""):
    return "passed" if ok else "failed:%s" % (detail or "mismatch")


# -- kernel sites -------------------------------------------------------------

def probe_lrn(config, ctx, reps, windows):
    import jax
    import jax.numpy as jnp
    import numpy
    from veles_tpu.znicz import lrn as lrn_mod
    rows = int(ctx.get("rows", 2048))
    c = int(ctx.get("c", 96))
    n = int(ctx.get("n", 5))
    alpha, beta, k = 1e-4, 0.75, 2.0
    rng = numpy.random.RandomState(0)
    x = jnp.asarray(rng.standard_normal((rows, c)), jnp.float32)

    def make(cfg):
        if cfg["impl"] == "mxu":
            return jax.jit(
                lambda v: lrn_mod.lrn_mxu(v, n, alpha, beta, k))
        rows_blk = int(cfg["block_rows"])
        return jax.jit(
            lambda v: lrn_mod.pallas_lrn(v, n, alpha, beta, k,
                                         rows_blk))

    from veles_tpu.autotune.space import site
    f_cand, f_ref = make(config), make(site("lrn").default)
    out = numpy.asarray(f_cand(x))
    xs = numpy.asarray(x)
    want = xs / (k + (alpha / n)
                 * lrn_mod._window_sum(xs * xs, n, numpy)) ** beta
    err = float(numpy.max(numpy.abs(out - want)))
    cand_s, ref_s = _timed_pair(
        lambda: jax.block_until_ready(f_cand(x)),
        lambda: jax.block_until_ready(f_ref(x)), reps, windows)
    return {"gate": _gate(err <= 2e-4, "max_err=%.3g" % err),
            "cand_s": cand_s, "ref_s": ref_s}


def _probe_attention(site_name, config, ctx, reps, windows):
    import jax
    import jax.numpy as jnp
    import numpy
    from veles_tpu.autotune.space import site
    from veles_tpu.parallel.ring import attention_reference
    from veles_tpu.znicz.flash_attention import flash_attention
    b = int(ctx.get("b", 1))
    t = int(ctx.get("t", 256))
    h = int(ctx.get("h", 2))
    d = int(ctx.get("d", 64))
    causal = bool(ctx.get("causal", True))
    window = ctx.get("window") if site_name == "window_attention" \
        else None
    rng = numpy.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.standard_normal((b, t, h, d)) * 0.5,
                           jnp.float32) for _ in range(3))

    def make(cfg):
        return jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal, None, cfg["block_q"], cfg["block_k"],
            window))

    f_cand, f_ref = make(config), make(site(site_name).default)
    out = numpy.asarray(f_cand(q, k, v))
    want = numpy.asarray(attention_reference(
        q, k, v, causal=causal, scale=1.0 / (d ** 0.5), window=window))
    err = float(numpy.max(numpy.abs(out - want)))
    cand_s, ref_s = _timed_pair(
        lambda: jax.block_until_ready(f_cand(q, k, v)),
        lambda: jax.block_until_ready(f_ref(q, k, v)), reps, windows)
    return {"gate": _gate(err <= 2e-3, "max_err=%.3g" % err),
            "cand_s": cand_s, "ref_s": ref_s}


def probe_flash_attention(config, ctx, reps, windows):
    return _probe_attention("flash_attention", config, ctx, reps,
                            windows)


def probe_window_attention(config, ctx, reps, windows):
    ctx = dict(ctx or {})
    ctx.setdefault("window", max(int(ctx.get("t", 256)) // 4, 32))
    return _probe_attention("window_attention", config, ctx, reps,
                            windows)


def probe_precise_gemm(config, ctx, reps, windows):
    import jax
    import jax.numpy as jnp
    import numpy
    from veles_tpu.autotune.space import site
    from veles_tpu.znicz.gemm import _matmul_impl
    m = int(ctx.get("m", 512))
    kk = int(ctx.get("k", 512))
    n = int(ctx.get("n", 512))
    level = int(ctx.get("level", 1))
    rng = numpy.random.RandomState(0)
    a = jnp.asarray(rng.standard_normal((m, kk)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((kk, n)), jnp.float32)

    def make(cfg):
        return jax.jit(lambda a, b: _matmul_impl(
            a, b, level, None, cfg["block_m"], cfg["block_n"],
            cfg["block_k"]))

    f_cand, f_ref = make(config), make(site("precise_gemm").default)
    out = numpy.asarray(f_cand(a, b))
    want = numpy.asarray(a, numpy.float64) @ numpy.asarray(
        b, numpy.float64)
    scale = float(numpy.max(numpy.abs(want))) or 1.0
    err = float(numpy.max(numpy.abs(out - want))) / scale
    cand_s, ref_s = _timed_pair(
        lambda: jax.block_until_ready(f_cand(a, b)),
        lambda: jax.block_until_ready(f_ref(a, b)), reps, windows)
    return {"gate": _gate(err <= 1e-4, "rel_err=%.3g" % err),
            "cand_s": cand_s, "ref_s": ref_s}


def probe_paged_attention(config, ctx, reps, windows):
    import jax
    import jax.numpy as jnp
    import numpy
    from veles_tpu.znicz.paged_attention import (
        paged_attention, paged_attention_reference, required_blocks)
    batch = int(ctx.get("batch", 2))
    heads = int(ctx.get("heads", 2))
    d = int(ctx.get("d", 16))
    length = int(ctx.get("length", 48))
    bs = int(config["block_size"])
    max_blocks = required_blocks(length, bs)
    num_blocks = batch * max_blocks + 1      # + reserved trash block 0
    rng = numpy.random.RandomState(0)
    k_pool, v_pool = (jnp.asarray(
        rng.standard_normal((num_blocks, bs, heads, d)) * 0.5,
        jnp.float32) for _ in range(2))
    table = numpy.zeros((batch, max_blocks), numpy.int32)
    blk = 1
    lengths = numpy.asarray(
        [length if i % 2 == 0 else max(length // 2, 1)
         for i in range(batch)], numpy.int32)
    for i in range(batch):
        used = required_blocks(int(lengths[i]), bs)
        for j in range(used):
            table[i, j] = blk
            blk += 1
    table = jnp.asarray(table)
    lengths = jnp.asarray(lengths)
    q = jnp.asarray(rng.standard_normal((batch, heads, d)) * 0.5,
                    jnp.float32)
    f_cand = jax.jit(paged_attention)
    f_ref = jax.jit(paged_attention_reference)
    out = numpy.asarray(f_cand(q, k_pool, v_pool, table, lengths))
    want = numpy.asarray(f_ref(q, k_pool, v_pool, table, lengths))
    # the kernel's contract with its dense reference is BITWISE
    bitwise = bool(numpy.array_equal(out, want))
    cand_s, ref_s = _timed_pair(
        lambda: jax.block_until_ready(
            f_cand(q, k_pool, v_pool, table, lengths)),
        lambda: jax.block_until_ready(
            f_ref(q, k_pool, v_pool, table, lengths)), reps, windows)
    return {"gate": _gate(bitwise, "not bitwise-equal to the dense "
                                   "reference"),
            "cand_s": cand_s, "ref_s": ref_s}


# -- serving sites ------------------------------------------------------------

def probe_bucket_ladder(config, ctx, reps, windows):
    """Steady-state drain time of a seeded ragged request mix.  Compile
    count differences are a one-time cost the compile cache + warmup
    manifests amortize away; what a ladder shape pays FOREVER is
    padding waste — that is what this measures."""
    import jax
    import jax.numpy as jnp
    import numpy
    from veles_tpu.autotune.space import ladder
    from veles_tpu.serving.scheduler import BucketScheduler
    mb = int(ctx.get("max_batch", 16))
    dim = int(ctx.get("dim", 64))
    n_requests = int(ctx.get("requests", 48))
    rng = numpy.random.RandomState(int(ctx.get("seed", 0)))
    w = jnp.asarray(rng.standard_normal((dim, dim)) * 0.1, jnp.float32)
    fn = jax.jit(lambda x: jnp.tanh(x @ w))
    mix = [rng.standard_normal(
        (int(rng.randint(1, mb + 1)), dim)).astype(numpy.float32)
        for _ in range(n_requests)]

    def build(shape):
        return BucketScheduler(
            fn, max_batch=mb, queue_limit=4 * n_requests * mb,
            warmup=True, name="autotune-%s" % shape,
            sample_shape=(dim,), cache=False,
            buckets=ladder(shape, mb))

    cand = build(config["shape"])
    ref = build("pow2")
    try:
        def drain(s):
            futs = [s.submit(x) for x in mix]
            return [f.result(60) for f in futs]

        outs = drain(cand)
        want = [numpy.asarray(fn(jnp.asarray(x))) for x in mix[:8]]
        ok = all(numpy.allclose(numpy.asarray(o), wv, atol=1e-5)
                 for o, wv in zip(outs[:8], want))
        cand_s, ref_s = _timed_pair(lambda: drain(cand),
                                    lambda: drain(ref), reps, windows)
    finally:
        cand.close(drain=False)
        ref.close(drain=False)
    return {"gate": _gate(ok), "cand_s": cand_s, "ref_s": ref_s,
            "ladder": ladder(config["shape"], mb)}


def probe_serving_decode(config, ctx, reps, windows):
    """Decode throughput (tokens/s over a seeded ragged prompt mix)
    under candidate geometry, gated on token-exactness vs the
    cache-free oracle."""
    import numpy
    from veles_tpu.serving import DecodeScheduler
    from veles_tpu.znicz.samples.flagship import (FlagshipDecodeModel,
                                                  generate_reference)
    max_prompt = int(ctx.get("max_prompt_len", 8))
    max_new = int(ctx.get("max_new_tokens", 8))
    n_requests = int(ctx.get("requests", 12))
    model = FlagshipDecodeModel(stages=2, experts=2, d=16, heads=2,
                                hidden=32, vocab=32, seed=0)
    rng = numpy.random.RandomState(int(ctx.get("seed", 0)))
    prompts = [[int(t) for t in rng.randint(
        0, 32, size=rng.randint(1, max_prompt + 1))]
        for _ in range(n_requests)]

    def build(cfg):
        return DecodeScheduler(
            model, max_batch=int(cfg["max_batch"]),
            block_size=int(cfg["block_size"]),
            max_prompt_len=max_prompt, max_new_tokens=max_new,
            queue_limit=4 * n_requests, warmup=True,
            name="autotune-%d-%d" % (cfg["max_batch"],
                                     cfg["block_size"]),
            cache=False)

    from veles_tpu.autotune.space import site
    cand = build(config)
    ref = build(site("serving.decode").default)
    try:
        def drain(s):
            futs = [s.submit(p, max_new) for p in prompts]
            return [f.result(120) for f in futs]

        outs = drain(cand)
        ok = all(
            outs[i]["tokens"] == generate_reference(
                model.params, prompts[i], max_new)
            for i in range(min(4, n_requests)))
        cand_s, ref_s = _timed_pair(lambda: drain(cand),
                                    lambda: drain(ref), reps, windows)
    finally:
        cand.close(drain=False)
        ref.close(drain=False)
    return {"gate": _gate(ok, "tokens diverge from the cache-free "
                              "oracle"),
            "cand_s": cand_s, "ref_s": ref_s}


def probe_prefill_chunk(config, ctx, reps, windows):
    """Short-request TTFT behind long chunked prefills — the quantity
    the chunk size actually trades (smaller chunks interleave sooner,
    but each chunk pays a dispatch) — gated on token-exactness vs the
    cache-free oracle.  Runs on the toydecode stand-in with a pinned
    per-prompt-token prefill cost so scheduling, not XLA, is what's
    measured."""
    import numpy
    from veles_tpu.serving import DecodeScheduler
    from veles_tpu.serving.toydecode import ToyDecodeModel
    max_prompt = int(ctx.get("max_prompt_len", 64))
    longs = int(ctx.get("long_prompts", 2))
    pdelay = float(ctx.get("prefill_delay", 0.001))
    model = ToyDecodeModel(vocab=97, prefill_delay=pdelay)
    rng = numpy.random.RandomState(int(ctx.get("seed", 0)))
    long_prompts = [rng.randint(1, 90, max_prompt).tolist()
                    for _ in range(longs)]
    short = [3, 1, 4, 1]

    def build(chunk):
        return DecodeScheduler(
            model, max_batch=longs + 1, block_size=4,
            max_prompt_len=max_prompt, max_new_tokens=4,
            queue_limit=64, warmup=True, cache=False,
            prefill_chunk_tokens=int(chunk),
            name="autotune-chunk%d" % chunk)

    def wave(s):
        futs = [s.submit(p, 4) for p in long_prompts]
        got = s.submit(short, 4).result(120)
        for f in futs:
            f.result(120)
        return got

    from veles_tpu.autotune.space import site
    cand = build(config["chunk_tokens"])
    ref = build(site("serving.prefill_chunk").default["chunk_tokens"])
    try:
        ok = wave(cand)["tokens"] == model.generate_reference(short, 4)
        # the _timed_pair discipline (interleaved min-of-windows)
        # applied to the short request's TTFT rather than wall time
        cand_t, ref_t = [], []
        for w in range(max(int(windows), 1)):
            pairs = [(cand, cand_t), (ref, ref_t)]
            if w % 2:
                pairs.reverse()
            for s, acc in pairs:
                vals = [wave(s)["ttft_s"]
                        for _ in range(max(int(reps), 1))]
                acc.append(sum(vals) / len(vals))
        cand_s, ref_s = min(cand_t), min(ref_t)
    finally:
        cand.close(drain=False)
        ref.close(drain=False)
    return {"gate": _gate(ok, "tokens diverge from the cache-free "
                              "oracle"),
            "cand_s": cand_s, "ref_s": ref_s}


def probe_spec_depth(config, ctx, reps, windows):
    """Decode drain time with the draft-and-verify loop at the
    candidate depth — what the depth trades is accepted tokens per
    verify pass vs wasted draft/verify work on rejections — gated on
    token-exactness vs the pure-host oracle.  Runs on the toydecode
    stand-in with a pinned per-step host delay and a pinned drafter
    agreement rate so scheduling, not XLA, is what's measured."""
    import numpy
    from veles_tpu.serving import DecodeScheduler
    from veles_tpu.serving.toydecode import ToyDecodeModel
    max_prompt = int(ctx.get("max_prompt_len", 8))
    max_new = int(ctx.get("max_new_tokens", 16))
    n_requests = int(ctx.get("requests", 8))
    agree = float(ctx.get("agreement", 0.8))
    sdelay = float(ctx.get("step_delay", 0.002))
    model = ToyDecodeModel(vocab=31, step_delay=sdelay,
                           draft_agreement=agree)
    rng = numpy.random.RandomState(int(ctx.get("seed", 0)))
    prompts = [[int(t) for t in rng.randint(
        0, 31, size=rng.randint(1, max_prompt + 1))]
        for _ in range(n_requests)]

    def build(depth):
        return DecodeScheduler(
            model, max_batch=4, block_size=4,
            max_prompt_len=max_prompt, max_new_tokens=max_new,
            queue_limit=4 * n_requests, warmup=True, cache=False,
            spec_depth=int(depth),
            name="autotune-spec%d" % depth)

    from veles_tpu.autotune.space import site
    cand = build(config["spec_depth"])
    ref = build(site("serving.spec_depth").default["spec_depth"])
    try:
        def drain(s):
            futs = [s.submit(p, max_new) for p in prompts]
            return [f.result(120) for f in futs]

        outs = drain(cand)
        ok = all(outs[i]["tokens"] == model.generate_reference(
                     prompts[i], max_new)
                 for i in range(n_requests))
        cand_s, ref_s = _timed_pair(lambda: drain(cand),
                                    lambda: drain(ref), reps, windows)
    finally:
        cand.close(drain=False)
        ref.close(drain=False)
    return {"gate": _gate(ok, "tokens diverge from the pure-host "
                              "oracle"),
            "cand_s": cand_s, "ref_s": ref_s}


def _decode_logit_rmse(model, kv_dtype, prompt, n_new):
    """Greedy-rollout logit RMSE of ``kv_dtype`` pools vs f32 pools —
    same params, same geometry, token-by-token through the model's
    ``logits_fn`` decode hook.  The error-bound gate's measurement."""
    import jax.numpy as jnp
    import numpy
    bs = 4
    # the fixed geometry below holds 4 blocks x 4 tokens per row —
    # cap the rollout so no position ever lands past the page table
    n_new = min(int(n_new), 4 * bs - len(prompt))
    per = {}
    for kvd in dict.fromkeys(("f32", kv_dtype)):
        kp, vp = model.make_pools(8, bs, kv_dtype=kvd)
        toks = jnp.zeros(8, jnp.int32).at[:len(prompt)].set(
            jnp.asarray(prompt, jnp.int32))
        block_row = jnp.asarray([1, 2, 3, 4], jnp.int32)
        tok, kp, vp = model.prefill_fn(bs, kv_dtype=kvd)(
            toks, len(prompt), kp, vp, block_row)
        table = jnp.zeros((2, 4), jnp.int32).at[0].set(block_row)
        lengths = jnp.asarray([len(prompt), 0], jnp.int32)
        logits = model.logits_fn(bs, kv_dtype=kvd)
        cur = jnp.asarray([int(tok), 0], jnp.int32)
        rows = []
        for _ in range(n_new):
            nxt, kp, vp, lg = logits(kp, vp, table, lengths, cur)
            rows.append(numpy.asarray(lg[0]))
            lengths = lengths.at[0].add(1)
            cur = cur.at[0].set(nxt[0])
        per[kvd] = numpy.stack(rows)
    if kv_dtype == "f32":
        return 0.0
    diff = per[kv_dtype] - per["f32"]
    return float(numpy.sqrt(numpy.mean(diff * diff)))


def probe_kv_dtype(config, ctx, reps, windows):
    """Decode drain time with the candidate KV-pool precision — what
    quantized pools buy is HBM (more resident blocks per byte) and
    memory-bound step time — gated on the site's DECLARED error bound:
    a lossy candidate cannot be bitwise vs the f32 oracle, so the gate
    is greedy-rollout logit RMSE <= error_bound, measured through the
    model's ``logits_fn`` hook before any timing."""
    import numpy
    from veles_tpu.autotune.space import site
    from veles_tpu.serving import DecodeScheduler
    from veles_tpu.znicz.samples.flagship import FlagshipDecodeModel
    sp = site("serving.kv_dtype")
    bound = float(ctx.get("error_bound", sp.error_bound))
    max_prompt = int(ctx.get("max_prompt_len", 8))
    max_new = int(ctx.get("max_new_tokens", 8))
    n_requests = int(ctx.get("requests", 8))
    kvd = str(config["kv_dtype"])
    model = FlagshipDecodeModel(stages=2, experts=2, d=16, heads=2,
                                hidden=32, vocab=32, seed=0)
    rng = numpy.random.RandomState(int(ctx.get("seed", 0)))
    prompts = [[int(t) for t in rng.randint(
        0, 32, size=rng.randint(1, max_prompt + 1))]
        for _ in range(n_requests)]
    rmse = _decode_logit_rmse(model, kvd, prompts[0][:3] or [1],
                              max_new)

    def build(kv_dtype, tag):
        return DecodeScheduler(
            model, max_batch=4, block_size=4,
            max_prompt_len=max_prompt, max_new_tokens=max_new,
            queue_limit=4 * n_requests, warmup=True, cache=False,
            kv_dtype=kv_dtype, name="autotune-kv-%s" % tag)

    cand = build(kvd, kvd)
    ref = build(sp.default["kv_dtype"], "ref")
    try:
        def drain(s):
            futs = [s.submit(p, max_new) for p in prompts]
            return [f.result(120) for f in futs]

        drain(cand)
        cand_s, ref_s = _timed_pair(lambda: drain(cand),
                                    lambda: drain(ref), reps, windows)
    finally:
        cand.close(drain=False)
        ref.close(drain=False)
    return {"gate": _gate(rmse <= bound,
                          "logit_rmse=%.3g > bound=%.3g"
                          % (rmse, bound)),
            "logit_rmse": round(rmse, 6), "error_bound": bound,
            "cand_s": cand_s, "ref_s": ref_s}


_IMPLS = {
    "lrn": probe_lrn,
    "flash_attention": probe_flash_attention,
    "window_attention": probe_window_attention,
    "precise_gemm": probe_precise_gemm,
    "paged_attention": probe_paged_attention,
    "serving.bucket_ladder": probe_bucket_ladder,
    "serving.decode": probe_serving_decode,
    "serving.prefill_chunk": probe_prefill_chunk,
    "serving.spec_depth": probe_spec_depth,
    "serving.kv_dtype": probe_kv_dtype,
}

#: cheap serving probes need fewer reps than μs-scale kernels
_DEFAULT_REPS = {"serving.bucket_ladder": 1, "serving.decode": 1,
                 "serving.prefill_chunk": 1, "serving.spec_depth": 1,
                 "serving.kv_dtype": 1}
_DEFAULT_WINDOWS = {"serving.bucket_ladder": 2, "serving.decode": 2,
                    "serving.prefill_chunk": 2, "serving.spec_depth": 2,
                    "serving.kv_dtype": 2}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--site", required=True, choices=sorted(_IMPLS))
    p.add_argument("--config", required=True,
                   help="candidate configuration (JSON object)")
    p.add_argument("--ctx", default="{}",
                   help="call context: shapes/seed (JSON object)")
    p.add_argument("--reps", type=int, default=None)
    p.add_argument("--windows", type=int, default=None)
    args = p.parse_args(argv)
    config = json.loads(args.config)
    ctx = json.loads(args.ctx)
    reps = args.reps if args.reps is not None \
        else _DEFAULT_REPS.get(args.site, 3)
    windows = args.windows if args.windows is not None \
        else _DEFAULT_WINDOWS.get(args.site, 3)
    out = {"ok": True, "site": args.site, "config": config}
    try:
        out.update(_IMPLS[args.site](config, ctx, reps, windows))
        if out.get("ref_s", 0) > 0 and "cand_s" in out:
            out["score"] = round(out["cand_s"] / out["ref_s"], 4)
        out["cand_s"] = round(out.get("cand_s", 0.0), 6)
        out["ref_s"] = round(out.get("ref_s", 0.0), 6)
    except Exception:  # noqa: BLE001 — the line must always print
        out = {"ok": False, "site": args.site, "config": config,
               "error": traceback.format_exc(limit=3).strip()[-500:]}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
