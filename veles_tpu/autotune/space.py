"""Search spaces: what each tunable site is allowed to try.

TVM framed kernel tuning as search over a declared schedule space
(arXiv 1802.04799); our spaces are far smaller — a handful of block
sizes, layouts, or ladder shapes per site — but the contract is the
same: the site declares *every* candidate up front with validity
constraints, the runner measures, and only a measured, correctness-
gated winner is ever persisted.

Ten builtin sites cover the tree's tunables:

==================== ======================================== ===========
site                 parameters                               dispatch at
==================== ======================================== ===========
lrn                  impl (pallas|mxu), block_rows            znicz/lrn.py
flash_attention      block_q, block_k                         znicz/flash_attention.py
window_attention     block_q, block_k                         znicz/flash_attention.py
precise_gemm         block_m, block_n, block_k                znicz/gemm.py
paged_attention      block_size                               serving/decode.py
serving.bucket_ladder shape (pow2|coarse|dense)               serving/scheduler.py
serving.decode       max_batch, block_size                    serving/decode.py
serving.prefill_chunk chunk_tokens                            serving/decode.py
serving.spec_depth   spec_depth                               serving/decode.py
serving.kv_dtype     kv_dtype (f32|int8)                      serving/decode.py
==================== ======================================== ===========

Every site's ``default`` is the exact hand-picked configuration the
kernel shipped with (cross-checked against the kernel constants in
tests/test_autotune.py), so a resolve with no tuning record — or with
the tuner off — reproduces current behavior byte for byte.

This module imports no JAX: config-time code (CLI ``list``, dispatch
with the tuner off) must stay light.
"""

import itertools

__all__ = ["SearchSpace", "SITES", "site", "ladder", "pow2_bucket"]


def pow2_bucket(n):
    """The next power of two >= n — the shape-class bucket for dims
    that vary continuously (GEMM sizes), so one tuning record covers a
    band of shapes the same blocking serves."""
    n = max(int(n), 1)
    b = 1
    while b < n:
        b <<= 1
    return b


def ladder(shape, max_batch):
    """Materialize a bucket-ladder shape into sizes, largest = max_batch.

    ``pow2`` reproduces ``serving.scheduler.bucket_sizes`` exactly
    (test-enforced — that equality is what makes the tuner-off path
    byte-identical); ``coarse`` trades padding for fewer compiles,
    ``dense`` the reverse (pow2 + 3*2^k midpoints).
    """
    mb = int(max_batch)
    if mb < 1:
        raise ValueError("max_batch must be >= 1")
    if shape == "pow2":
        sizes, b = [], 1
        while b < mb:
            sizes.append(b)
            b <<= 1
        sizes.append(mb)
        return sizes
    if shape == "coarse":
        return sorted({1, max(mb // 4, 1), max(mb // 2, 1), mb})
    if shape == "dense":
        sizes, b = {mb}, 1
        while b < mb:
            sizes.add(b)
            if 3 * b // 2 < mb and b > 1:
                sizes.add(3 * b // 2)
            b <<= 1
        return sorted(sizes)
    raise ValueError("unknown ladder shape %r" % (shape,))


class SearchSpace:
    """One tunable site: parameter grid + validity constraint.

    ``params`` maps parameter name -> tuple of candidate values;
    ``default`` is the hand-picked config (always a valid candidate and
    always measured first — it is the baseline every speedup is
    reported against).  ``constraint(config, ctx)`` filters the cross
    product; ``classify(ctx)`` maps a concrete call context to the
    shape-class string the tuning store keys on.

    ``error_bound`` declares the numeric tolerance a LOSSY candidate
    must stay within to pass the probe's gate (e.g. logit RMSE for
    quantized KV pools).  ``None`` — every site that searches exact
    reformulations — keeps the gate bitwise/exact: an error bound is a
    property of the site's contract, declared here, never improvised
    per probe run.
    """

    def __init__(self, name, params, default, constraint=None,
                 classify=None, description="", error_bound=None):
        self.name = name
        self.params = {k: tuple(v) for k, v in params.items()}
        self.default = dict(default)
        self._constraint = constraint
        self._classify = classify
        self.description = description
        self.error_bound = (None if error_bound is None
                            else float(error_bound))

    def valid(self, config, ctx=None):
        if set(config) != set(self.params):
            return False
        if any(config[k] not in self.params[k] for k in config):
            return False
        return self._constraint(config, ctx or {}) \
            if self._constraint else True

    def candidates(self, ctx=None):
        """Every valid config, hand-picked default FIRST (the runner
        measures it as the baseline even when invalid-by-constraint —
        it is what ships, so it is always comparable)."""
        ctx = ctx or {}
        out = [dict(self.default)]
        names = sorted(self.params)
        for values in itertools.product(*(self.params[n] for n in names)):
            cfg = dict(zip(names, values))
            if cfg == self.default or not self.valid(cfg, ctx):
                continue
            out.append(cfg)
        return out

    def shape_class(self, ctx):
        """The store key's shape-class string for a call context."""
        if self._classify is None:
            return "any"
        return self._classify(ctx or {})


def _lrn_constraint(cfg, ctx):
    # block_rows only means something to the pallas layout; pin it to
    # the default for the mxu band so the grid has no duplicate points
    if cfg["impl"] == "mxu":
        return cfg["block_rows"] == 1024
    rows = ctx.get("rows")
    return rows is None or cfg["block_rows"] <= max(int(rows), 8)


def _attention_constraint(cfg, ctx):
    # the kernel fits blocks down to a divisor itself; restricting the
    # grid to exact divisors of T keeps every candidate DISTINCT
    t = ctx.get("t")
    if t is None:
        return True
    return t % cfg["block_q"] == 0 and t % cfg["block_k"] == 0


def _gemm_constraint(cfg, ctx):
    # VMEM estimate: one A tile + one B tile + out/acc/carry scratch
    # (4 [bm, bn] f32 buffers) must fit comfortably (~12 MB of ~16)
    bm, bn, bk = cfg["block_m"], cfg["block_n"], cfg["block_k"]
    return (bm * bk + bk * bn + 4 * bm * bn) * 4 <= 12 << 20


def _decode_constraint(cfg, ctx):
    ctx_len = ctx.get("max_context")
    return ctx_len is None or cfg["block_size"] <= int(ctx_len)


#: the builtin sites; tools/autotune.py ``tune --site`` names these
SITES = {}


def _register(s):
    SITES[s.name] = s
    return s


_register(SearchSpace(
    "lrn",
    params={"impl": ("pallas", "mxu"),
            "block_rows": (256, 512, 1024, 2048, 4096)},
    # the hand-picked pallas config (lrn._LRN_BLOCK_ROWS); "mxu" is the
    # banded-matmul LAYOUT as a searchable candidate — the measured
    # answer to BENCH_r05's 0.6x: on device classes where the
    # pallas_call fusion boundary loses, the tuner picks the band
    default={"impl": "pallas", "block_rows": 1024},
    constraint=_lrn_constraint,
    classify=lambda ctx: "c%d_n%d" % (ctx["c"], ctx.get("n", 5)),
    description="cross-channel LRN: pallas row-tile size, or the "
                "banded-matmul layout"))

_register(SearchSpace(
    "flash_attention",
    params={"block_q": (128, 256, 512), "block_k": (128, 256, 512)},
    default={"block_q": 256, "block_k": 256},   # DEFAULT_BLOCK_Q/K
    constraint=_attention_constraint,
    classify=lambda ctx: "t%d_d%d%s" % (
        pow2_bucket(ctx["t"]), ctx["d"],
        "_causal" if ctx.get("causal") else ""),
    description="flash attention Q/K tile sizes"))

_register(SearchSpace(
    "window_attention",
    params={"block_q": (128, 256, 512), "block_k": (128, 256, 512)},
    default={"block_q": 256, "block_k": 256},
    constraint=_attention_constraint,
    classify=lambda ctx: "t%d_d%d_w%d" % (
        pow2_bucket(ctx["t"]), ctx["d"], ctx.get("window", 0)),
    description="sliding-window attention Q/K tile sizes"))

_register(SearchSpace(
    "precise_gemm",
    params={"block_m": (128, 256, 512), "block_n": (128, 256, 512),
            "block_k": (128, 256, 512)},
    default={"block_m": 128, "block_n": 128, "block_k": 256},
    constraint=_gemm_constraint,
    classify=lambda ctx: "m%d_k%d_n%d_l%d" % (
        pow2_bucket(ctx["m"]), pow2_bucket(ctx["k"]),
        pow2_bucket(ctx["n"]), ctx.get("level", 1)),
    description="compensated-GEMM M/N/K tile sizes"))

_register(SearchSpace(
    "paged_attention",
    params={"block_size": (4, 8, 16, 32)},
    default={"block_size": 8},       # paged_attention.DEFAULT_BLOCK_SIZE
    constraint=_decode_constraint,
    classify=lambda ctx: "h%d_d%d_len%d" % (
        ctx["heads"], ctx["d"], pow2_bucket(ctx.get("max_context", 64))),
    description="KV page size of the ragged paged-attention kernel"))

_register(SearchSpace(
    "serving.bucket_ladder",
    params={"shape": ("pow2", "coarse", "dense")},
    default={"shape": "pow2"},       # scheduler.bucket_sizes
    classify=lambda ctx: "mb%d" % ctx["max_batch"],
    description="bucket-ladder shape: padding waste vs compile count"))

_register(SearchSpace(
    "serving.decode",
    params={"max_batch": (4, 8, 16, 32), "block_size": (4, 8, 16, 32)},
    default={"max_batch": 8, "block_size": 8},
    constraint=_decode_constraint,
    classify=lambda ctx: "ctx%d" % pow2_bucket(ctx.get("max_context", 64)),
    description="decode scheduler geometry: concurrent rows + KV page "
                "size"))


def _chunk_constraint(cfg, ctx):
    # a chunk larger than the prompt ceiling degenerates to monolithic
    # prefill with extra padding — keep candidates distinct
    mp = ctx.get("max_prompt_len")
    return mp is None or cfg["chunk_tokens"] <= pow2_bucket(mp)


_register(SearchSpace(
    "serving.prefill_chunk",
    params={"chunk_tokens": (8, 16, 32, 64)},
    default={"chunk_tokens": 32},    # decode.DEFAULT_PREFILL_CHUNK
    constraint=_chunk_constraint,
    classify=lambda ctx: "mp%d" % pow2_bucket(
        ctx.get("max_prompt_len", 64)),
    description="prefill chunk size: short-request TTFT under "
                "head-of-line long prefills vs per-chunk dispatch "
                "overhead"))


def _spec_constraint(cfg, ctx):
    # speculating past the per-request token budget only writes
    # positions the accept step must discard — keep candidates distinct
    mn = ctx.get("max_new_tokens")
    return mn is None or cfg["spec_depth"] < max(int(mn), 2)


_register(SearchSpace(
    "serving.spec_depth",
    params={"spec_depth": (1, 2, 3, 4, 6, 8)},
    default={"spec_depth": 2},       # decode.DEFAULT_SPEC_DEPTH
    constraint=_spec_constraint,
    classify=lambda ctx: "mn%d" % pow2_bucket(
        ctx.get("max_new_tokens", 32)),
    description="speculative decoding depth: draft tokens per "
                "iteration — measured acceptance rate vs the "
                "multi-token verify pass's cost"))


_register(SearchSpace(
    "serving.kv_dtype",
    params={"kv_dtype": ("f32", "int8")},
    default={"kv_dtype": "f32"},     # decode pools exactly as shipped
    classify=lambda ctx: "ctx%d" % pow2_bucket(
        ctx.get("max_context", 64)),
    error_bound=1e-2,
    description="KV-pool precision: f32 pools exactly as shipped, or "
                "int8 blocks dequantized in-kernel — the first lossy "
                "site, gated on the declared logit-RMSE bound instead "
                "of bitwise equality"))


def site(name):
    try:
        return SITES[name]
    except KeyError:
        raise KeyError("unknown autotune site %r (known: %s)"
                       % (name, ", ".join(sorted(SITES))))
