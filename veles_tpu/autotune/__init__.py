"""veles_tpu.autotune — persistent search over kernel/serving configs.

TVM-style autotuning (arXiv 1802.04799) scaled to this tree: every
Pallas kernel and the serving-geometry knobs declare a small candidate
grid (:mod:`.space`), a runner measures candidates in isolated fresh
subprocesses with hard wall-clock caps and correctness gating
(:mod:`.runner` / :mod:`.probe`), and measured winners persist in a
store keyed by (site, shape-class, device kind, jax/jaxlib versions)
(:mod:`.store`) so tuning is paid once per device generation.  Kernel
call sites resolve through :func:`resolve` with their hand-picked
config as the fallback — with the tuner off (no
``root.common.autotune.dir`` / ``$VELES_AUTOTUNE_DIR``) behavior is
byte-for-byte unchanged.

Drive it with ``tools/autotune.py tune|list|show|verify``.
"""

from .dispatch import (AUTOTUNE_DIR_ENV, default_store, describe,  # noqa: F401
                       reset_default_stores, resolve, resolve_config)
from .runner import measure_candidate, run_isolated, tune_site  # noqa: F401
from .space import SITES, SearchSpace, ladder, site  # noqa: F401
from .store import SCHEMA, SUFFIX, TuningStore, record_key  # noqa: F401
