"""Persistent tuning store: measured winners, durable on disk.

One JSON record per ``(site, shape-class, environment)`` under one
directory, riding the compilecache store's durability discipline
(compilecache/store.py): every write is ``*.tmp`` + fsync + atomic
``os.rename``; records that fail to parse or validate are QUARANTINED
(renamed aside with ``.corrupt``) so the next lookup is a clean miss
and the caller falls back to the hand-picked default — a bad record
must never crash a kernel call or poison a second process.

The key hashes the site name, the shape class, and the compilation
environment fingerprint (compilecache/keys.py: jax/jaxlib versions,
platform, device kind/count).  jax upgrades or a different device
generation therefore produce a *different key* — a clean miss and a
re-tune, never a misload of stale block sizes.
"""

import hashlib
import json
import logging
import os
import time

from ..observability.registry import REGISTRY

log = logging.getLogger("veles_tpu.autotune")

#: record suffix; quarantined records get SUFFIX + ".corrupt"
SUFFIX = ".vtune"

#: record layout version — bump on schema change (old records then
#: quarantine-and-retune once, which is the upgrade path)
SCHEMA = 1

_REQUIRED = ("schema", "site", "shape_class", "fingerprint", "config",
             "default", "speedup", "gate", "measured_at")

_c_corrupt = REGISTRY.counter(
    "veles_autotune_corrupt_total",
    "Tuning records quarantined as unreadable or invalid")


def environment_fingerprint():
    """The tuning environment string — compilecache's fingerprint
    verbatim (monkeypatch THAT module in tests to simulate drift)."""
    from ..compilecache import keys
    return keys.environment_fingerprint()


def record_key(site, shape_class, fingerprint=None):
    """SHA-256 key (hex) for one ``(site, shape-class, environment)``."""
    if fingerprint is None:
        fingerprint = environment_fingerprint()
    h = hashlib.sha256()
    for part in (site, shape_class, fingerprint):
        h.update(str(part).encode())
        h.update(b"\x00")
    return h.hexdigest()


class TuningStore:
    """site + shape-class -> measured-winner records under one dir."""

    def __init__(self, directory):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._warned = set()            # keys already logged (log-once)

    def path_for(self, key):
        return os.path.join(self.directory, key + SUFFIX)

    # -- read ----------------------------------------------------------------
    def get(self, site, shape_class):
        """The validated record for the CURRENT environment, or None
        (miss / corrupt — corrupt records are quarantined and warned
        about once; the caller falls back to its default config)."""
        fingerprint = environment_fingerprint()
        key = record_key(site, shape_class, fingerprint)
        path = self.path_for(key)
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            return None
        record, reason = self._validate(text, site, shape_class,
                                        fingerprint)
        if record is None:
            self.quarantine(key, reason)
            if key not in self._warned:
                self._warned.add(key)
                log.warning(
                    "autotune: record for %s/%s is %s; quarantined "
                    "(%s.corrupt) and falling back to the hand-picked "
                    "default config", site, shape_class, reason,
                    os.path.basename(path))
            return None
        return record

    @staticmethod
    def _validate(text, site=None, shape_class=None, fingerprint=None):
        """(record, None) or (None, reason).  Beyond JSON parseability
        the stored identity fields must MATCH the key that found the
        record — a renamed/copied file can never smuggle a config onto
        the wrong site, shape, or environment."""
        try:
            record = json.loads(text)
        except ValueError as exc:
            return None, "unparseable (%s)" % exc
        if not isinstance(record, dict):
            return None, "not an object"
        missing = [k for k in _REQUIRED if k not in record]
        if missing:
            return None, "missing fields %s" % ",".join(missing)
        if record["schema"] != SCHEMA:
            return None, "schema %r != %d" % (record["schema"], SCHEMA)
        if not isinstance(record["config"], dict):
            return None, "config is not an object"
        for field, want in (("site", site), ("shape_class", shape_class),
                            ("fingerprint", fingerprint)):
            if want is not None and record[field] != want:
                return None, "%s mismatch" % field
        return record, None

    # -- write ---------------------------------------------------------------
    def put(self, site, shape_class, config, *, default, speedup,
            gate="passed", baseline_s=None, best_s=None,
            candidates_tried=None, extra=None):
        """Atomically persist a measured winner; returns the record."""
        fingerprint = environment_fingerprint()
        env = dict(kv.split("=", 1) for kv in fingerprint.split(";")
                   if "=" in kv)
        record = {
            "schema": SCHEMA,
            "site": site,
            "shape_class": shape_class,
            "fingerprint": fingerprint,
            "config": dict(config),
            "default": dict(default),
            "speedup": round(float(speedup), 4),
            "gate": gate,
            "measured_at": time.time(),
            # provenance the CLI surfaces per record
            "device_kind": env.get("device_kind", "?"),
            "platform": env.get("platform", "?"),
            "jax": env.get("jax", "?"),
            "jaxlib": env.get("jaxlib", "?"),
        }
        if baseline_s is not None:
            record["baseline_s"] = baseline_s
        if best_s is not None:
            record["best_s"] = best_s
        if candidates_tried is not None:
            record["candidates_tried"] = int(candidates_tried)
        if extra:
            record["extra"] = extra
        key = record_key(site, shape_class, fingerprint)
        path = self.path_for(key)
        tmp = path + ".tmp.%d" % os.getpid()
        try:
            with open(tmp, "w") as f:
                json.dump(record, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, path)
        except OSError:
            # a full/read-only disk must never fail the tuner — the
            # measurement already happened; the winner is just not saved
            log.warning("autotune: could not persist record %s under %s",
                        key[:16], self.directory, exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self._warned.discard(key)       # a rewrite clears the log-once
        return record

    def quarantine(self, key, reason=""):
        """Rename a bad record aside (``.corrupt``).  Idempotent."""
        path = self.path_for(key)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            return False
        _c_corrupt.inc()
        log.debug("autotune: quarantined record %s (%s)", key[:16],
                  reason or "invalid")
        return True

    # -- listing (CLI) -------------------------------------------------------
    def records(self):
        """[(key, record_or_None, reason_or_None)] for every on-disk
        record, corrupt ones included (record None + reason) — the
        ``list``/``verify`` surface.  Read-only: nothing is quarantined
        here."""
        out = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return out
        for name in names:
            if not name.endswith(SUFFIX):
                continue
            key = name[:-len(SUFFIX)]
            try:
                with open(os.path.join(self.directory, name)) as f:
                    text = f.read()
            except OSError:
                continue            # raced with a concurrent quarantine
            record, reason = self._validate(text)
            out.append((key, record, reason))
        return out
