"""Dispatch: kernels resolve their configs here, cheaply and safely.

``resolve(site, shape_class, default)`` is the one call every tunable
site makes (lrn.py, flash_attention.py, gemm.py, decode.py,
scheduler.py).  Resolution order:

1. tuner off (no ``root.common.autotune.dir`` and no
   ``$VELES_AUTOTUNE_DIR``, or ``enabled`` false) -> the hand-picked
   ``default``, with NO disk access — byte-for-byte the pre-tuner
   behavior;
2. store hit for the current environment -> the measured winner
   (``veles_autotune_tuned_hits_total``);
3. miss / corrupt / version drift -> the default again
   (``veles_autotune_fallbacks_total``).

Results are memoized per ``(dir, site, shape-class)`` so kernel trace
paths pay one disk read per shape class per process, not one per call.
"""

import os

from ..config import root
from ..observability.registry import REGISTRY
from . import space as _space
from .store import TuningStore

#: env var a supervisor/bench parent uses to hand the tuning dir to
#: child processes that don't re-read its programmatic config
AUTOTUNE_DIR_ENV = "VELES_AUTOTUNE_DIR"

_c_hits = REGISTRY.counter(
    "veles_autotune_tuned_hits_total",
    "Site resolutions served a measured tuning record")
_c_fallbacks = REGISTRY.counter(
    "veles_autotune_fallbacks_total",
    "Site resolutions that fell back to the hand-picked default "
    "(store configured but no valid record for this environment)")


def resolve_config():
    """The tuning-store directory, or None (tuner off) — from
    ``root.common.autotune.{enabled, dir}`` with the
    :data:`AUTOTUNE_DIR_ENV` env fallback."""
    cfg = root.common.autotune
    if not cfg.get("enabled", True):
        return None
    directory = cfg.get("dir", None) or os.environ.get(AUTOTUNE_DIR_ENV)
    return str(directory) if directory else None


_instances = {}
_memo = {}


def default_store():
    """The process-wide :class:`TuningStore` for the configured dir,
    or None when the tuner is off."""
    directory = resolve_config()
    if not directory:
        return None
    key = os.path.abspath(directory)
    store = _instances.get(key)
    if store is None:
        store = _instances[key] = TuningStore(directory)
    return store


def reset_default_stores():
    """Drop memoized stores AND resolutions (tests that switch dirs or
    rewrite records mid-process)."""
    _instances.clear()
    _memo.clear()


def resolve(site, shape_class, default=None):
    """-> ``(config, source)`` where source is ``"tuned"`` or
    ``"default"``.  ``default`` falls back to the site's declared
    hand-picked config; the returned dict is a copy (mutation-safe)."""
    if default is None:
        default = _space.site(site).default
    store = default_store()
    if store is None:
        return dict(default), "default"
    memo_key = (store.directory, site, shape_class)
    hit = _memo.get(memo_key)
    if hit is None:
        record = store.get(site, shape_class)
        if record is not None:
            hit = (record["config"], "tuned")
            _c_hits.inc()
        else:
            hit = (dict(default), "default")
            _c_fallbacks.inc()
        _memo[memo_key] = hit
    config, source = hit
    # tolerate records written by a space that has since GROWN params:
    # missing keys take the default, so dispatch never KeyErrors
    merged = dict(default)
    merged.update(config)
    return merged, source


def describe(site, shape_class, default=None):
    """Bench/JSON provenance helper: the resolved config flattened with
    its ``config_source`` tag (satellite: every kernel metric in
    bench.py carries which config produced it)."""
    config, source = resolve(site, shape_class, default)
    out = dict(config)
    out["config_source"] = source
    return out
