"""Distributed control plane: elastic checkpoint-restart + multi-host.

The reference's elasticity was slave-granular: dropped slaves had their
minibatches requeued and were respawned over SSH with backoff
(/root/reference/veles/server.py:315-338,637-655; fault injection via
--slave-death-probability, client.py:303-307).  On TPU, ICI collectives
are gang-scheduled — a participant cannot leave mid-step — so recovery
moves to CHECKPOINT-RESTART granularity (SURVEY.md §7 hard parts): the
:class:`ElasticRunner` supervises a training process and, when it dies,
relaunches it from the newest snapshot with exponential backoff.  The
in-process loader keeps the reference's minibatch requeue contract for
job-level accounting (loader/base.py); this module is the out-of-band
driver above it.

Fault injection for tests/drills mirrors the reference: the CLI's
``--death-probability`` (random per-epoch crash) and the deterministic
``--die-at-epoch`` hook.

Multi-host: :func:`init_multihost` wraps ``jax.distributed.initialize``
— processes coordinate over DCN, and every host's local chips join one
global mesh; combined with parallel/mesh.py shardings the same jitted
step then spans slices (collectives ride ICI within a slice, DCN
across).
"""

import glob
import os
import random
import subprocess
import sys
import time

from .compilecache import inject_env as _cache_inject_env
from .observability import trace as _trace
from .units import Unit


class RestartBudgetExhausted(RuntimeError):
    """A supervised process crashed more times than its budget allows."""


class RestartBackoff:
    """Exponential backoff with jitter and a max-restart budget.

    The respawn policy shared by :class:`ElasticRunner` (training
    checkpoint-restart) and :class:`veles_tpu.fleet.supervisor
    .ReplicaSupervisor` (serving replicas): a crash-looping child must
    not hot-spin the host, and many children restarting after a common
    cause must not stampede in lockstep — so the delay grows
    ``base * factor^streak`` (capped at ``cap``) with a ±``jitter``
    fraction of multiplicative noise.

    ``restarts`` counts every restart ever granted (the budget);
    ``streak`` counts consecutive crashes and is what the exponent
    uses — :meth:`note_uptime` resets the streak after a healthy run of
    ``reset_after`` seconds WITHOUT refunding the budget, so a process
    that crashes once a day restarts fast forever while one that
    crashes every second walks up to ``cap`` and eventually exhausts.

    Deterministic for tests: inject ``rng`` (a ``random.random``-like
    callable) and read delays from :meth:`next_delay` — no wall clock
    inside.
    """

    def __init__(self, base=1.0, factor=2.0, cap=60.0, jitter=0.1,
                 max_restarts=5, reset_after=None, rng=None):
        self.base = float(base)
        self.factor = float(factor)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self.max_restarts = (None if max_restarts is None
                             else int(max_restarts))
        self.reset_after = reset_after
        self._rng = rng or random.random
        self.restarts = 0
        self.streak = 0

    @property
    def exhausted(self):
        return (self.max_restarts is not None
                and self.restarts >= self.max_restarts)

    @property
    def remaining(self):
        """Restarts left in the budget (``None`` when unlimited) — the
        supervisor surfaces this in /metrics so an operator sees a
        crash-looper approaching ``failed`` before it parks."""
        if self.max_restarts is None:
            return None
        return max(self.max_restarts - self.restarts, 0)

    def next_delay(self):
        """Grant one restart: seconds to wait before it, or ``None``
        when the budget is exhausted (the caller gives up)."""
        if self.exhausted:
            return None
        delay = min(self.base * self.factor ** self.streak, self.cap)
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * self._rng() - 1.0)
        self.restarts += 1
        self.streak += 1
        return delay

    def note_uptime(self, seconds):
        """The child just ran healthily for ``seconds`` before dying;
        a long-enough run resets the exponent (not the budget)."""
        if self.reset_after is not None and seconds >= self.reset_after:
            self.streak = 0


class Reaper(Unit):
    """Fault injection: crash the process at epoch boundaries.

    (reference client.py:303-307 --slave-death-probability.)"""

    MAPPING = "reaper"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.death_probability = float(kwargs.get("death_probability", 0.0))
        self.die_at_epoch = kwargs.get("die_at_epoch")
        self.epoch_number = None     # linked
        self.prng = kwargs.get("prng")

    def link_loader(self, loader):
        self.link_attrs(loader, "epoch_number", "epoch_ended")
        self.gate_skip = ~loader.epoch_ended
        return self

    def run(self):
        epoch = int(self.epoch_number)
        if self.die_at_epoch is not None and epoch == int(self.die_at_epoch):
            os._exit(66)
        if self.death_probability > 0:
            if self.prng is not None:
                draw = float(self.prng.uniform(0, 1))  # reproducible
            else:
                import random
                draw = random.random()
            if draw < self.death_probability:
                os._exit(66)


def latest_snapshot(directory, prefix="wf"):
    """Newest snapshot path in ``directory`` (prefers the ``_current``
    symlink the snapshotter maintains)."""
    link = os.path.join(directory, "%s_current" % prefix)
    if os.path.islink(link) and os.path.exists(link):
        return os.path.realpath(link)
    candidates = glob.glob(os.path.join(directory, "%s*.pickle*" % prefix))
    candidates = [c for c in candidates if not c.endswith("_current")]
    if not candidates:
        return None
    return max(candidates, key=os.path.getmtime)


class ElasticRunner:
    """Supervise a CLI training run; restart from the newest snapshot on
    crash (reference server.py:637-655 respawn-with-backoff, moved to
    checkpoint granularity)."""

    def __init__(self, model, argv=(), snapshot_dir=".", prefix="wf",
                 max_respawns=5, backoff=1.0, backoff_factor=2.0,
                 backoff_cap=60.0, jitter=0.1, reset_after=None,
                 python=None, env=None, silent=False, rng=None):
        self.model = model
        self.argv = list(argv)
        self.snapshot_dir = snapshot_dir
        self.prefix = prefix
        self.max_respawns = max_respawns
        self.backoff = backoff
        self.backoff_factor = backoff_factor
        self._policy = RestartBackoff(
            base=backoff, factor=backoff_factor, cap=backoff_cap,
            jitter=jitter, max_restarts=max_respawns,
            reset_after=reset_after, rng=rng)
        self.python = python or sys.executable
        self.env = env
        self.silent = silent
        self.respawns = 0
        self.history = []

    def run(self):
        """Returns the final returncode (0 = the run completed)."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # every (re)launch joins the supervisor's trace: crash-restart
        # chains then read as one causal timeline in the merged trace
        env = _trace.inject_env(self.env)
        # ...and inherits the compile caches (VELES_COMPILE_CACHE_DIR /
        # JAX_COMPILATION_CACHE_DIR): a respawn then deserializes its
        # fused-step executables instead of re-paying XLA compilation
        env = _cache_inject_env(env)
        while True:
            argv = [self.python, "-m", "veles_tpu", self.model] + self.argv
            snapshot = latest_snapshot(self.snapshot_dir, self.prefix)
            if snapshot:
                argv += ["--snapshot", snapshot]
            t0 = time.monotonic()
            proc = subprocess.run(argv, cwd=repo, env=env,
                                  capture_output=self.silent)
            self.history.append({"rc": proc.returncode,
                                 "resumed_from": snapshot})
            if proc.returncode == 0:
                return 0
            self._policy.note_uptime(time.monotonic() - t0)
            delay = self._policy.next_delay()
            if delay is None:
                return proc.returncode
            self.respawns = self._policy.restarts
            if not self.silent:
                print("elastic: run died rc=%d; respawn %d/%d in %.1fs"
                      % (proc.returncode, self.respawns,
                         self.max_respawns, delay), file=sys.stderr)
            time.sleep(delay)


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None):
    """Join this process to a multi-host JAX cluster (DCN control plane).

    Thin wrapper over ``jax.distributed.initialize``: on TPU pods the
    arguments come from the environment automatically; elsewhere pass the
    coordinator's host:port and this process's rank.  After this, the
    global device set spans all hosts and parallel/mesh.make_mesh can lay
    a dp×tp mesh over it — the same fused step then trains multi-host
    with no further code changes."""
    import jax
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)
    jax.distributed.initialize(**kwargs)
    return jax.process_index(), jax.process_count()
