"""Shell unit: drop into an interactive console mid-workflow.

Re-creation of /root/reference/veles/interaction.py (:49): the reference
embedded an IPython kernel; here the unit prefers IPython when present
and falls back to the stdlib ``code.interact``, with the workflow and
unit namespace exposed.  ``interactive=False`` (the default under tests
and batch runs) makes it a no-op so graphs can keep the unit wired
permanently.
"""

from .units import Unit


class Shell(Unit):
    MAPPING = "shell"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.interactive = bool(kwargs.get("interactive", False))
        self.banner = kwargs.get(
            "banner", "veles_tpu shell — `workflow` and `shell` are in "
                      "scope; exit to resume the graph")

    def run(self):
        if not self.interactive:
            return
        ns = {"workflow": self._workflow, "shell": self}
        try:
            import IPython
            IPython.embed(user_ns=ns, banner1=self.banner)
        except ImportError:
            import code
            code.interact(banner=self.banner, local=ns)
