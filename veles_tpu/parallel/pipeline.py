"""Pipeline parallelism: microbatched stages over a mesh axis.

Beyond the reference's master–slave data parallelism, but part of the
platform's "scale past one device" contract: a stack of IDENTICAL
blocks (the transformer/MLP regime — SPMD requires every device to run
the same program, so heterogeneous stages are out of scope and
documented as such) is split over the ``pipe`` mesh axis, the batch is
split into microbatches, and activations flow stage→stage over ICI via
``ppermute`` in a ``lax.scan`` over pipeline ticks.

Two schedules:

- :func:`gpipe_apply` — the classic GPipe schedule: M microbatches
  drain through S stages in M + S - 1 ticks, bubble fraction
  (S-1)/(M+S-1).  Because the schedule is expressed as a scan of
  ppermutes, ``jax.grad`` differentiates straight through it — the
  reverse pipeline falls out of autodiff — at the cost of autodiff
  stashing residuals for EVERY tick: activation memory grows O(M).
- :func:`gpipe_train_1f1b` — the 1F1B (PipeDream-flush) schedule,
  hand-scheduled forward AND backward in ONE interleaved scan: stage
  ``i`` runs the forward of microbatch j at tick i + j and its
  backward at tick 2(S-1) - i + j, so a microbatch's backward starts
  as soon as its forward drains — a stage holds at most 2(S-1-i)+1
  stashed block inputs (a circular O(S) buffer, **independent of M**)
  and recomputes the block under ``jax.vjp`` at backward ticks
  (rematerialization).  The trade, measured on the 8-device CPU mesh
  (tests/test_pipeline.py): wall-clock M + 2(S-1) ticks each costing
  fwd+bwd (vs GPipe's 2(M+S-1) ticks costing one of them) — i.e. an
  extra (S-1) op-slots of bubble — in exchange for O(S) instead of
  O(M) activation memory.  Use it when long microbatch trains blow
  HBM; use GPipe when M is small.

Both compose with the ``data`` axis (dp x pp meshes): batch on
``data``, stages on ``pipe``.  Parity with the sequential stack is
exact for values AND gradients (tests/test_pipeline.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def sequential_blocks(block_apply, stacked_params, x):
    """The parity oracle: apply the S stacked blocks in order on one
    device.  ``stacked_params``: pytree with leading dim S."""
    s = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

    def body(h, i):
        params_i = jax.tree.map(lambda p: p[i], stacked_params)
        return block_apply(params_i, h), None

    out, _ = lax.scan(body, x, jnp.arange(s))
    return out


def _gpipe_local(params_stage, x, *, block_apply, n_stages, microbatches,
                 axis_name):
    """Per-device schedule: stage ``idx`` runs microbatch ``t - idx`` at
    tick ``t``; activations hop idx→idx+1 between ticks.

    Also reused (inside a caller-owned shard_map binding more axes) by
    znicz.samples.flagship — keep the signature and the
    leading-local-stage-dim-1 params convention in sync with it."""
    idx = lax.axis_index(axis_name)
    params_stage = jax.tree.map(lambda p: p[0], params_stage)  # [1,...]→
    m = microbatches
    b = x.shape[0]
    mb = x.reshape((m, b // m) + x.shape[1:])
    # zeros derived from x already vary over the data axis (when any);
    # only the pipe axis needs marking for the scan-carry types to agree
    act0 = jnp.zeros_like(mb[0])
    out0 = jnp.zeros_like(mb)
    act0, out0 = lax.pcast((act0, out0), (axis_name,), to="varying")
    perm = [(s, s + 1) for s in range(n_stages - 1)]

    def tick(carry, t):
        act_in, outputs = carry
        mb_idx = t - idx
        valid = jnp.logical_and(mb_idx >= 0, mb_idx < m)
        safe_idx = jnp.clip(mb_idx, 0, m - 1)
        # stage 0 reads a fresh microbatch; later stages read the hop
        x_in = jnp.where(idx == 0, mb[safe_idx], act_in)
        y = block_apply(params_stage, x_in)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        # the LAST stage banks its finished microbatch
        done = jnp.logical_and(idx == n_stages - 1, valid)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(done, y, outputs[safe_idx]),
            safe_idx, 0)
        act_next = lax.ppermute(y, axis_name, perm)
        return (act_next, outputs), None

    (_, outputs), _ = lax.scan(
        tick, (act0, out0), jnp.arange(m + n_stages - 1))
    # results live on the last stage only; a masked psum replicates them
    outputs = lax.psum(
        jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape((b,) + outputs.shape[2:])


def gpipe_apply(block_apply, stacked_params, x, mesh, pipe_axis="pipe",
                data_axis=None, microbatches=None):
    """Pipelined ``block_S-1(...block_0(x))`` over ``mesh[pipe_axis]``.

    block_apply(params_i, h) -> h' must preserve h's shape (identical
    blocks); ``stacked_params`` leading dim = the pipe axis size and is
    sharded over it; ``x`` [B, ...] (B split over ``data_axis`` when
    given).  ``microbatches`` defaults to 2 x stages (bubble ~1/3)."""
    from jax.sharding import PartitionSpec as P
    n_stages = mesh.shape[pipe_axis]
    stacked_s = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if stacked_s != n_stages:
        # a larger multiple would shard "evenly" and silently run only
        # every (stacked_s/n_stages)-th block
        raise ValueError("params stack %d blocks but the %r axis has %d "
                         "stages" % (stacked_s, pipe_axis, n_stages))
    m = microbatches if microbatches is not None else 2 * n_stages
    local_b = x.shape[0] // (mesh.shape[data_axis] if data_axis else 1)
    if local_b % m:
        # validated HERE with the caller's numbers: inside shard_map the
        # batch is already the data shard, which the caller never typed
        raise ValueError(
            "per-shard batch %d (global %d%s) not divisible by %d "
            "microbatches"
            % (local_b, x.shape[0],
               " over %s=%d" % (data_axis, mesh.shape[data_axis])
               if data_axis else "", m))
    param_spec = jax.tree.map(
        lambda _: P(pipe_axis), stacked_params)
    x_spec = P(data_axis)
    fn = jax.shard_map(
        functools.partial(_gpipe_local, block_apply=block_apply,
                          n_stages=n_stages, microbatches=m,
                          axis_name=pipe_axis),
        mesh=mesh, in_specs=(param_spec, x_spec), out_specs=x_spec)
    return fn(stacked_params, x)


def _1f1b_local(params_stage, x, *, block_apply, out_grad, n_stages,
                microbatches, axis_name):
    """Per-device 1F1B: fwd of mb j at tick idx + j, bwd of mb j at tick
    2(S-1) - idx + j; block inputs stash in a circular O(S) buffer and
    the block is recomputed under jax.vjp at backward ticks."""
    idx = lax.axis_index(axis_name)
    params_stage = jax.tree.map(lambda p: p[0], params_stage)
    s, m = n_stages, microbatches
    b = x.shape[0]
    mb = x.reshape((m, b // m) + x.shape[1:])
    cap = min(m, 2 * s - 1)          # max in-flight stash + 1
    act0 = jnp.zeros_like(mb[0])
    # derived from mb so it inherits data-axis vma when composed dp x pp
    stash0 = jnp.broadcast_to(jnp.zeros_like(mb[0]),
                              (cap,) + mb.shape[1:])
    outs0 = jnp.zeros_like(mb)
    dxs0 = jnp.zeros_like(mb)
    # dp0 derives from the pipe-sharded params and is already varying
    # over the axis; the x-derived zeros are invariant and need marking
    dp0 = jax.tree.map(jnp.zeros_like, params_stage)
    grad0 = jnp.zeros_like(mb[0])
    act0, stash0, outs0, dxs0, grad0 = lax.pcast(
        (act0, stash0, outs0, dxs0, grad0), (axis_name,), to="varying")
    fwd_perm = [(st, st + 1) for st in range(s - 1)]
    bwd_perm = [(st + 1, st) for st in range(s - 1)]

    def tick(carry, t):
        act_in, grad_in, stash, outs, dxs, dp = carry
        # ---- forward half ------------------------------------------------
        jf = t - idx
        valid_f = jnp.logical_and(jf >= 0, jf < m)
        jf_safe = jnp.clip(jf, 0, m - 1)
        x_in = jnp.where(idx == 0, mb[jf_safe], act_in)
        stash = lax.dynamic_update_index_in_dim(
            stash,
            jnp.where(valid_f, x_in, stash[jf_safe % cap]),
            jf_safe % cap, 0)
        y = block_apply(params_stage, x_in)
        y = jnp.where(valid_f, y, jnp.zeros_like(y))
        done = jnp.logical_and(idx == s - 1, valid_f)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(done, y, outs[jf_safe]), jf_safe, 0)
        # ---- backward half -----------------------------------------------
        jb = t - (2 * (s - 1) - idx)
        valid_b = jnp.logical_and(jb >= 0, jb < m)
        jb_safe = jnp.clip(jb, 0, m - 1)
        # the last stage seeds its own backward from THIS tick's forward
        # output (jb == jf there); other stages consume the hop
        g_in = jnp.where(idx == s - 1, out_grad(y, jb_safe), grad_in)
        x_saved = stash[jb_safe % cap]
        _, pullback = jax.vjp(block_apply, params_stage, x_saved)
        dparams_mb, dx_mb = pullback(g_in)
        dx_mb = jnp.where(valid_b, dx_mb, jnp.zeros_like(dx_mb))
        dp = jax.tree.map(
            lambda acc, g: acc + jnp.where(valid_b, g,
                                           jnp.zeros_like(g)),
            dp, dparams_mb)
        dxs = lax.dynamic_update_index_in_dim(
            dxs,
            jnp.where(jnp.logical_and(idx == 0, valid_b), dx_mb,
                      dxs[jb_safe]),
            jb_safe, 0)
        # ---- hops --------------------------------------------------------
        act_next = lax.ppermute(y, axis_name, fwd_perm)
        grad_next = lax.ppermute(dx_mb, axis_name, bwd_perm)
        return (act_next, grad_next, stash, outs, dxs, dp), None

    (_, _, _, outs, dxs, dp), _ = lax.scan(
        tick, (act0, grad0, stash0, outs0, dxs0, dp0),
        jnp.arange(m + 2 * (s - 1)))
    outs = lax.psum(
        jnp.where(idx == s - 1, outs, jnp.zeros_like(outs)), axis_name)
    dxs = lax.psum(
        jnp.where(idx == 0, dxs, jnp.zeros_like(dxs)), axis_name)
    y = outs.reshape((b,) + outs.shape[2:])
    dx = dxs.reshape((b,) + dxs.shape[2:])
    dp = jax.tree.map(lambda g: g[None], dp)   # back to a [1,...] stack
    return y, dp, dx


def gpipe_train_1f1b(block_apply, stacked_params, x, out_grad, mesh,
                     pipe_axis="pipe", data_axis=None,
                     microbatches=None):
    """One pipelined forward+backward under the 1F1B schedule.

    Same layout contract as :func:`gpipe_apply`; additionally
    ``out_grad(y_mb, mb_index) -> dy_mb`` supplies the loss gradient of
    each finished microbatch — 1F1B needs it the moment a microbatch
    drains, which is why this is a train-step primitive rather than an
    autodiff-transparent forward.  ``out_grad`` runs INSIDE the
    shard_map: with ``data_axis=None`` close it over targets reshaped
    to [microbatches, mb, ...] and index with ``mb_index``; with a
    ``data_axis`` set, ``y_mb`` is the PER-DATA-SHARD microbatch, so
    the closure must first select its shard's targets via
    ``lax.axis_index(data_axis)`` (e.g. ``lax.dynamic_index_in_dim`` on
    targets reshaped to [shards, microbatches, mb_local, ...]) before
    indexing with ``mb_index`` — see
    ``tests/test_pipeline.py::test_1f1b_composes_with_data_axis`` for
    the exact pattern.  Returns ``(y, param_grads, dx)`` with
    ``param_grads`` stacked [S, ...] like ``stacked_params``.
    See the module docstring for the memory/bubble trade vs GPipe."""
    from jax.sharding import PartitionSpec as P
    n_stages = mesh.shape[pipe_axis]
    stacked_s = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if stacked_s != n_stages:
        raise ValueError("params stack %d blocks but the %r axis has %d "
                         "stages" % (stacked_s, pipe_axis, n_stages))
    m = microbatches if microbatches is not None else 2 * n_stages
    local_b = x.shape[0] // (mesh.shape[data_axis] if data_axis else 1)
    if local_b % m:
        raise ValueError(
            "per-shard batch %d (global %d%s) not divisible by %d "
            "microbatches"
            % (local_b, x.shape[0],
               " over %s=%d" % (data_axis, mesh.shape[data_axis])
               if data_axis else "", m))
    param_spec = jax.tree.map(lambda _: P(pipe_axis), stacked_params)
    x_spec = P(data_axis)
    fn = jax.shard_map(
        functools.partial(_1f1b_local, block_apply=block_apply,
                          out_grad=out_grad, n_stages=n_stages,
                          microbatches=m, axis_name=pipe_axis),
        mesh=mesh, in_specs=(param_spec, x_spec),
        out_specs=(x_spec, param_spec, x_spec))
    return fn(stacked_params, x)
