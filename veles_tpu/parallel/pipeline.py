"""Pipeline parallelism: GPipe-style microbatched stages over a mesh axis.

Beyond the reference's master–slave data parallelism, but part of the
platform's "scale past one device" contract: a stack of IDENTICAL
blocks (the transformer/MLP regime — SPMD requires every device to run
the same program, so heterogeneous stages are out of scope and
documented as such) is split over the ``pipe`` mesh axis, the batch is
split into microbatches, and activations flow stage→stage over ICI via
``ppermute`` in a ``lax.scan`` over pipeline ticks.  The classic GPipe
schedule: M microbatches drain through S stages in M + S - 1 ticks,
bubble fraction (S-1)/(M+S-1).

Because the schedule is expressed as a scan of ppermutes, ``jax.grad``
differentiates straight through it — the reverse pipeline (activation
grads flowing backwards over the ring) falls out of autodiff rather
than being hand-scheduled, and parity with the sequential stack is
exact (asserted in tests/test_pipeline.py, values AND gradients).

Composes with the ``data`` axis (dp x pp meshes): batch on ``data``,
stages on ``pipe``.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def sequential_blocks(block_apply, stacked_params, x):
    """The parity oracle: apply the S stacked blocks in order on one
    device.  ``stacked_params``: pytree with leading dim S."""
    s = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

    def body(h, i):
        params_i = jax.tree.map(lambda p: p[i], stacked_params)
        return block_apply(params_i, h), None

    out, _ = lax.scan(body, x, jnp.arange(s))
    return out


def _gpipe_local(params_stage, x, *, block_apply, n_stages, microbatches,
                 axis_name):
    """Per-device schedule: stage ``idx`` runs microbatch ``t - idx`` at
    tick ``t``; activations hop idx→idx+1 between ticks."""
    idx = lax.axis_index(axis_name)
    params_stage = jax.tree.map(lambda p: p[0], params_stage)  # [1,...]→
    m = microbatches
    b = x.shape[0]
    mb = x.reshape((m, b // m) + x.shape[1:])
    # zeros derived from x already vary over the data axis (when any);
    # only the pipe axis needs marking for the scan-carry types to agree
    act0 = jnp.zeros_like(mb[0])
    out0 = jnp.zeros_like(mb)
    act0, out0 = lax.pcast((act0, out0), (axis_name,), to="varying")
    perm = [(s, s + 1) for s in range(n_stages - 1)]

    def tick(carry, t):
        act_in, outputs = carry
        mb_idx = t - idx
        valid = jnp.logical_and(mb_idx >= 0, mb_idx < m)
        safe_idx = jnp.clip(mb_idx, 0, m - 1)
        # stage 0 reads a fresh microbatch; later stages read the hop
        x_in = jnp.where(idx == 0, mb[safe_idx], act_in)
        y = block_apply(params_stage, x_in)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        # the LAST stage banks its finished microbatch
        done = jnp.logical_and(idx == n_stages - 1, valid)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(done, y, outputs[safe_idx]),
            safe_idx, 0)
        act_next = lax.ppermute(y, axis_name, perm)
        return (act_next, outputs), None

    (_, outputs), _ = lax.scan(
        tick, (act0, out0), jnp.arange(m + n_stages - 1))
    # results live on the last stage only; a masked psum replicates them
    outputs = lax.psum(
        jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape((b,) + outputs.shape[2:])


def gpipe_apply(block_apply, stacked_params, x, mesh, pipe_axis="pipe",
                data_axis=None, microbatches=None):
    """Pipelined ``block_S-1(...block_0(x))`` over ``mesh[pipe_axis]``.

    block_apply(params_i, h) -> h' must preserve h's shape (identical
    blocks); ``stacked_params`` leading dim = the pipe axis size and is
    sharded over it; ``x`` [B, ...] (B split over ``data_axis`` when
    given).  ``microbatches`` defaults to 2 x stages (bubble ~1/3)."""
    from jax.sharding import PartitionSpec as P
    n_stages = mesh.shape[pipe_axis]
    stacked_s = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if stacked_s != n_stages:
        # a larger multiple would shard "evenly" and silently run only
        # every (stacked_s/n_stages)-th block
        raise ValueError("params stack %d blocks but the %r axis has %d "
                         "stages" % (stacked_s, pipe_axis, n_stages))
    m = microbatches if microbatches is not None else 2 * n_stages
    local_b = x.shape[0] // (mesh.shape[data_axis] if data_axis else 1)
    if local_b % m:
        # validated HERE with the caller's numbers: inside shard_map the
        # batch is already the data shard, which the caller never typed
        raise ValueError(
            "per-shard batch %d (global %d%s) not divisible by %d "
            "microbatches"
            % (local_b, x.shape[0],
               " over %s=%d" % (data_axis, mesh.shape[data_axis])
               if data_axis else "", m))
    param_spec = jax.tree.map(
        lambda _: P(pipe_axis), stacked_params)
    x_spec = P(data_axis)
    fn = jax.shard_map(
        functools.partial(_gpipe_local, block_apply=block_apply,
                          n_stages=n_stages, microbatches=m,
                          axis_name=pipe_axis),
        mesh=mesh, in_specs=(param_spec, x_spec), out_specs=x_spec)
    return fn(stacked_params, x)
