"""DistributedScanStep: the epoch-scan trainer sharded over a Mesh.

Composes the two big levers: the epoch-scan path (one ``lax.scan``
dispatch per class/epoch block — znicz/scan_step.py) and mesh SPMD
(params replicated or tensor-sharded, batch split over ``data``, XLA
inserting the gradient all-reduce — parallel/dp.py).  The HBM-resident
dataset is REPLICATED across the mesh (every shard gathers its own
minibatch rows, then a sharding constraint splits the batch); for
datasets too large to replicate, use the per-step DistributedTrainStep
whose host gather feeds shards, or shard the dataset upstream.

Single-process meshes only (the scan's bulk index tensors are built
host-side); multi-host training goes through DistributedTrainStep.
"""

from ..znicz.scan_step import ScanEpochStep
from . import mesh as mesh_mod


class DistributedScanStep(ScanEpochStep):
    """ScanEpochStep over a Mesh: dp/tp shardings, scan dispatch."""

    def __init__(self, workflow, forwards, gd_units, mesh,
                 loss="softmax", data_axis="data", model_axis=None,
                 tp_mode="column", **kwargs):
        super().__init__(workflow, forwards, gd_units, loss=loss, **kwargs)
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.tp_mode = tp_mode

    def initialize(self, device=None, **kwargs):
        import jax
        if jax.process_count() > 1:
            raise ValueError(
                "epoch_scan over a mesh is single-process only (the bulk "
                "scan index tensors are host-built); multi-host training "
                "uses the per-step DistributedTrainStep (drop "
                "epoch_scan=)")
        super().initialize(device=device, **kwargs)

    # ScanEpochStep.initialize calls these AFTER the params/opt/macc and
    # the resident dataset exist, so the shardings can be computed and
    # the operands placed right here.
    def _place_operands(self):
        import jax
        if getattr(self, "_placed_", False):
            return
        param_shard, opt_shard, rep = mesh_mod.trainer_shardings(
            self.mesh, self._params_, self._opt_, self.model_axis,
            self.tp_mode)
        self._param_shard_, self._opt_shard_, self._rep_ = \
            param_shard, opt_shard, rep
        self._params_ = jax.device_put(self._params_, param_shard)
        self._opt_ = jax.device_put(self._opt_, opt_shard)
        self._macc_ = jax.device_put(self._macc_, rep)
        # the dataset gathers shard-locally: replicate it + the labels
        self._data_dev_ = jax.device_put(self._data_dev_, rep)
        self._y_dev_ = jax.device_put(self._y_dev_, rep)
        self._placed_ = True

    def _constrain_batch(self, a):
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = (self.data_axis,) + (None,) * (a.ndim - 1)
        return lax.with_sharding_constraint(
            a, NamedSharding(self.mesh, P(*spec)))

    def _jit_train_scan(self, train_scan):
        import jax
        self._place_operands()
        rep = self._rep_
        return jax.jit(
            train_scan,
            in_shardings=(rep, rep, self._param_shard_, self._opt_shard_,
                          rep, rep, rep, rep, rep),
            out_shardings=(self._param_shard_, self._opt_shard_, rep,
                           rep),
            donate_argnums=(2, 3, 4))

    def _jit_eval_scan(self, eval_scan):
        import jax
        self._place_operands()
        rep = self._rep_
        return jax.jit(
            eval_scan,
            in_shardings=(rep, rep, self._param_shard_, rep, rep, rep),
            out_shardings=(rep, rep),
            donate_argnums=(3,))
