"""DistributedScanStep: the epoch-scan trainer sharded over a Mesh.

Composes the two big levers: the epoch-scan path (one ``lax.scan``
dispatch per class/epoch block — znicz/scan_step.py) and mesh SPMD
(params replicated or tensor-sharded, batch split over ``data``, XLA
inserting the gradient all-reduce — parallel/dp.py).  The HBM-resident
dataset is REPLICATED across the mesh (every shard gathers its own
minibatch rows, then a sharding constraint splits the batch); for
datasets too large to replicate, use the per-step DistributedTrainStep
whose host gather feeds shards, or shard the dataset upstream.

Multi-host: works — this was the round-3 gap (VERDICT item 4: "the
reference scaled its slow path to 100 nodes; the TPU build should scale
its fast one").  The scan's bulk index tensors are built host-side by
EVERY process from the identically-seeded loader (the same determinism
contract the per-step DistributedTrainStep already relies on for its
replicated minibatches), then placed onto the global replicated sharding
exactly like the per-step path places its batches (parallel/dp.py).
Proven by a 2-process x 2-device CPU parity test
(tests/test_multihost.py): both hosts end bit-identical to each other,
and match the single-process scan to float-reduction tolerance (2e-5).
"""

from ..znicz.scan_step import ScanEpochStep
from . import mesh as mesh_mod


class DistributedScanStep(ScanEpochStep):
    """ScanEpochStep over a Mesh: dp/tp shardings, scan dispatch."""

    def __init__(self, workflow, forwards, gd_units, mesh,
                 loss="softmax", data_axis="data", model_axis=None,
                 tp_mode="column", **kwargs):
        super().__init__(workflow, forwards, gd_units, loss=loss, **kwargs)
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.tp_mode = tp_mode

    def __getstate__(self):
        state = super().__getstate__()
        mesh = state.get("mesh")
        if mesh is not None and not isinstance(mesh, dict):
            # Device handles are process-local: snapshot the GEOMETRY
            # and rebuild over the restoring process's devices
            state["mesh"] = mesh_mod.mesh_spec(mesh)
        return state

    def initialize(self, device=None, **kwargs):
        if isinstance(self.mesh, dict):   # restored from a snapshot
            self.mesh = mesh_mod.mesh_for_spec(self.mesh)
        return super().initialize(device=device, **kwargs)

    # ScanEpochStep.initialize calls these AFTER the params/opt/macc and
    # the resident dataset exist, so the shardings can be computed and
    # the operands placed right here.
    def _place_operands(self):
        import jax
        if getattr(self, "_placed_", False):
            return
        if jax.process_count() > 1:
            # cross-process placement accepts HOST data (every process
            # holds the same full value — identically-seeded loaders);
            # single-device jax.Arrays cannot be resharded to a global
            # sharding outside jit (same move as parallel/dp.py)
            import numpy
            self._params_ = jax.tree.map(numpy.asarray, self._params_)
            self._opt_ = jax.tree.map(numpy.asarray, self._opt_)
            self._macc_ = jax.tree.map(numpy.asarray, self._macc_)
            self._data_dev_ = numpy.asarray(self._data_dev_)
            self._y_dev_ = numpy.asarray(self._y_dev_)
        param_shard, opt_shard, rep = mesh_mod.trainer_shardings(
            self.mesh, self._params_, self._opt_, self.model_axis,
            self.tp_mode)
        self._param_shard_, self._opt_shard_, self._rep_ = \
            param_shard, opt_shard, rep
        mesh_mod.register_mesh_metrics(
            self.mesh, getattr(self._workflow, "name", "-"))
        self._params_ = jax.device_put(self._params_, param_shard)
        self._opt_ = jax.device_put(self._opt_, opt_shard)
        self._macc_ = jax.device_put(self._macc_, rep)
        # the dataset gathers shard-locally: replicate it + the labels
        self._data_dev_ = jax.device_put(self._data_dev_, rep)
        self._y_dev_ = jax.device_put(self._y_dev_, rep)
        self._placed_ = True

    def _constrain_batch(self, a):
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = (self.data_axis,) + (None,) * (a.ndim - 1)
        return lax.with_sharding_constraint(
            a, NamedSharding(self.mesh, P(*spec)))

    def _jit_train_scan(self, train_scan):
        import jax
        self._place_operands()
        rep = self._rep_
        fn = jax.jit(
            train_scan,
            in_shardings=(rep, rep, self._param_shard_, self._opt_shard_,
                          rep, rep, rep, rep, rep),
            out_shardings=(self._param_shard_, self._opt_shard_, rep,
                           rep),
            donate_argnums=(2, 3, 4))
        if jax.process_count() == 1:
            return fn

        def train_mh(data, y, params, opt, macc, idx, sizes, seeds,
                     lr_scale):
            # the bulk index tensors are per-run host numpy (identical
            # on every process); place them onto the global replicated
            # sharding before the SPMD call
            return fn(data, y, params, opt, macc,
                      jax.device_put(idx, rep),
                      jax.device_put(sizes, rep),
                      jax.device_put(seeds, rep), lr_scale)
        return train_mh

    def _jit_eval_scan(self, eval_scan):
        import jax
        self._place_operands()
        rep = self._rep_
        fn = jax.jit(
            eval_scan,
            in_shardings=(rep, rep, self._param_shard_, rep, rep, rep),
            out_shardings=(rep, rep),
            donate_argnums=(3,))
        if jax.process_count() == 1:
            return fn

        def eval_mh(data, y, params, macc, idx, sizes):
            return fn(data, y, params, macc,
                      jax.device_put(idx, rep),
                      jax.device_put(sizes, rep))
        return eval_mh
