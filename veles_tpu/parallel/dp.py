"""DistributedTrainStep: the fused train step sharded over a device mesh.

This is the TPU-native replacement for the reference's master–slave
data-parallel trainer (SURVEY.md §2.4): instead of slaves shipping pickled
gradients to a master over ZeroMQ (server.py:401-414), the batch is sharded
over the mesh's ``data`` axis, params are replicated (or sharded over
``model`` for tensor parallelism), and XLA inserts the gradient all-reduce
(psum over ICI) from the sharding annotations — the same jitted step, now
SPMD.

The synchronous all-reduce changes the *semantics* vs the reference's
asynchronous staleness-1 updates: every step sees the freshest weights,
which is strictly stronger; the reference's elastic join/leave semantics
move to checkpoint-restart (veles_tpu.distributed) because ICI collectives
are gang-scheduled (SURVEY.md §7 hard parts).
"""

from ..znicz.fused import FusedTrainStep
from . import mesh as mesh_mod


class DistributedTrainStep(FusedTrainStep):
    """FusedTrainStep over a Mesh: batch on ``data``, params replicated
    (optionally tensor-sharded over ``model``)."""

    def __init__(self, workflow, forwards, gd_units, mesh,
                 loss="softmax", data_axis="data", model_axis=None,
                 tp_mode="column", **kwargs):
        super().__init__(workflow, forwards, gd_units, loss=loss, **kwargs)
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.tp_mode = tp_mode

    def __getstate__(self):
        state = super().__getstate__()
        mesh = state.get("mesh")
        if mesh is not None and not isinstance(mesh, dict):
            # Device handles are process-local: snapshot the GEOMETRY
            # and rebuild over the restoring process's devices
            state["mesh"] = mesh_mod.mesh_spec(mesh)
        return state

    def make_trace(self):
        """Sharding survives tracing by construction: the SPMD step stays
        a natively-executed pre-compiled region, its in-program sharding
        annotations (and the ICI all-reduce XLA derives from them)
        untouched by the graph compiler."""
        from ..graphcomp.faces import OpaqueFace
        return OpaqueFace(self, "sharded fused step: one SPMD program "
                                "over the %r mesh axes"
                                % list(getattr(self.mesh, "axis_names",
                                               ())))

    def initialize(self, device=None, **kwargs):
        if isinstance(self.mesh, dict):   # restored from a snapshot
            self.mesh = mesh_mod.mesh_for_spec(self.mesh)
        super().initialize(device=device, **kwargs)
        import jax
        import numpy

        m = self.mesh
        multihost = jax.process_count() > 1
        if multihost:
            # cross-process placement accepts HOST data (every process
            # holds the same full value — loaders are identically
            # seeded); single-device jax.Arrays cannot be resharded to a
            # global sharding outside jit
            self._params_ = jax.tree.map(numpy.asarray, self._params_)
            self._opt_ = jax.tree.map(numpy.asarray, self._opt_)
            self._macc_ = jax.tree.map(numpy.asarray, self._macc_)
        param_shard, opt_shard, scalar = mesh_mod.trainer_shardings(
            m, self._params_, self._opt_, self.model_axis, self.tp_mode)
        batch_shard = mesh_mod.batch_sharding(m, self.data_axis)
        label_shard = batch_shard
        # input-pipeline hooks (loader/prefetch.py): single-host, the
        # prefetch worker device_puts minibatches straight onto the
        # batch sharding; multi-host, the step re-places host batches
        # itself below, so prefetch staging must stay off
        self._batch_sharding_ = None if multihost else batch_shard
        self._prefetch_unsupported_ = multihost
        mesh_mod.register_mesh_metrics(
            m, getattr(self._workflow, "name", "-"))

        self._params_ = jax.device_put(self._params_, param_shard)
        self._opt_ = jax.device_put(self._opt_, opt_shard)

        # re-jit the two steps with explicit shardings; XLA lowers the
        # gradient reduction to an ICI all-reduce.  ``size`` and ``seed``
        # stay DYNAMIC (replicated scalars) — a static size would trigger a
        # full recompile of the sharded step for every distinct tail-batch
        self._macc_ = jax.device_put(self._macc_, scalar)
        self._train_step_ = jax.jit(
            self._train_step_.__wrapped__,
            in_shardings=(param_shard, opt_shard, scalar, batch_shard,
                          label_shard, scalar, scalar, scalar),
            out_shardings=(param_shard, opt_shard, scalar, scalar,
                           batch_shard),
            donate_argnums=(0, 1, 2))
        self._eval_step_ = jax.jit(
            self._eval_step_.__wrapped__,
            in_shardings=(param_shard, scalar, batch_shard, label_shard,
                          scalar),
            out_shardings=(scalar, scalar, batch_shard),
            donate_argnums=(1,))
        if multihost:
            # multi-host: the per-step minibatch leaves the loader as a
            # process-local array; place it onto the global batch
            # sharding (same bytes on every process) before the SPMD call
            inner_train, inner_eval = self._train_step_, self._eval_step_

            def _global(x, shard):
                return jax.device_put(numpy.asarray(x), shard)

            def train_mh(params, opt, macc, x, y, size, seed, lr_scale):
                return inner_train(params, opt, macc,
                                   _global(x, batch_shard),
                                   _global(y, label_shard),
                                   size, seed, lr_scale)

            def eval_mh(params, macc, x, y, size):
                return inner_eval(params, macc, _global(x, batch_shard),
                                  _global(y, label_shard), size)

            self._train_step_ = train_mh
            self._eval_step_ = eval_mh
