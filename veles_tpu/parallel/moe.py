"""Expert parallelism: switch-style top-1 mixture-of-experts over a
mesh axis.

The last of the mesh quintet (data/tensor/pipeline/sequence/expert):
E experts' parameters shard over the ``expert`` axis — each device owns
ONE expert and computes only the tokens routed to it (bounded by a
capacity), so expert compute scales with the axis instead of
replicating.  Routing is switch-transformer top-1: a linear router,
softmax gate, tokens over capacity dropped (the standard trade;
capacity_factor sizes the buffer).  The combine is a masked ``psum`` —
every token's result lives on exactly one expert shard.

Tokens (x) are replicated over the expert axis (and split over ``data``
when composed dp x ep); an ``all_to_all`` dispatch variant for
token-sharded inputs is the scale-up path once token counts outgrow
replication.  Autodiff flows through routing (straight-through on the
gate probability), so the layer trains end-to-end
(tests/test_moe.py)."""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def router_probs(wr, x):
    """[B, E] softmax router probabilities."""
    return jax.nn.softmax(x @ wr, axis=-1)


def load_balance_loss(wr, x):
    """The switch-transformer auxiliary balancing loss:
    ``E * sum_e(f_e * P_e)`` with f_e the fraction of tokens routed to
    expert e and P_e its mean router probability (minimized at uniform
    routing, value 1.0).  ADD THIS (scaled ~1e-2) to the task loss when
    training through :func:`moe_apply` — top-1 routing with a capacity
    otherwise collapses onto the strongest expert and drops the rest of
    the batch."""
    probs = router_probs(wr, x)
    e = probs.shape[-1]
    assign = jnp.argmax(probs, axis=-1)
    fraction = jnp.mean(
        jax.nn.one_hot(assign, e, dtype=probs.dtype), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return e * jnp.sum(fraction * mean_prob)


def moe_reference(expert_apply, stacked_params, wr, x, capacity):
    """Single-device oracle: same top-1 routing, same capacity drops,
    experts applied in a scan."""
    e = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    probs = router_probs(wr, x)
    assign = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, assign[:, None], axis=1)[:, 0]
    out = jnp.zeros_like(expert_apply(
        jax.tree.map(lambda p: p[0], stacked_params), x))

    def per_expert(out, i):
        params_i = jax.tree.map(lambda p: p[i], stacked_params)
        mine = assign == i
        pos = jnp.cumsum(mine) - 1
        keep = jnp.logical_and(mine, pos < capacity)
        y = expert_apply(params_i, x)
        return out + jnp.where(keep[:, None], y, 0.0), None

    out, _ = lax.scan(per_expert, out, jnp.arange(e))
    return out * gate[:, None]


def _moe_local(stacked_params, wr, x, *, expert_apply, capacity,
               axis_name):
    e_idx = lax.axis_index(axis_name)
    params_e = jax.tree.map(lambda p: p[0], stacked_params)
    b, d = x.shape
    probs = router_probs(wr, x)
    assign = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, assign[:, None], axis=1)[:, 0]
    mine = assign == e_idx
    pos = jnp.cumsum(mine) - 1                  # queue slot per token
    keep = jnp.logical_and(mine, pos < capacity)
    # pack this expert's tokens into a [capacity, D] buffer (one extra
    # trash row absorbs everything dropped or foreign)
    slot = jnp.where(keep, pos, capacity)
    buf = jnp.zeros((capacity + 1, d), x.dtype).at[slot].set(x)
    y = expert_apply(params_e, buf[:capacity])
    # unpack: token i reads its slot's row; non-kept tokens contribute 0
    out = jnp.where(keep[:, None],
                    y[jnp.clip(pos, 0, capacity - 1)], 0.0)
    out = out * gate[:, None]
    # each token was computed on exactly one expert shard
    return lax.psum(out, axis_name)


def moe_apply(expert_apply, stacked_params, wr, x, mesh,
              expert_axis="expert", data_axis=None,
              capacity_factor=1.25):
    """Expert-parallel top-1 MoE over ``mesh[expert_axis]``.

    expert_apply(params_i, h[B, D]) -> [B, D']; ``stacked_params``
    leading dim = E (sharded over the expert axis); ``wr`` [D, E]
    replicated router weights; ``x`` [B, D] (B over ``data_axis`` when
    given).  capacity = ceil(B/E * capacity_factor) tokens per expert,
    overflow dropped exactly like the reference oracle.

    Training: include :func:`load_balance_loss` in the objective —
    without it top-1 routing collapses and the capacity drops most of
    the batch."""
    from jax.sharding import PartitionSpec as P
    n_experts = mesh.shape[expert_axis]
    stacked_e = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if stacked_e != n_experts or wr.shape[1] != n_experts:
        # a divisible mismatch would shard "evenly" and silently zero
        # every token routed to an expert no device owns
        raise ValueError(
            "expert count mismatch: params stack %d, router %d, mesh "
            "axis %d" % (stacked_e, wr.shape[1], n_experts))
    local_b = x.shape[0] // (mesh.shape[data_axis] if data_axis else 1)
    capacity = moe_capacity(local_b, n_experts, capacity_factor)
    param_spec = jax.tree.map(lambda _: P(expert_axis), stacked_params)
    fn = jax.shard_map(
        functools.partial(_moe_local, expert_apply=expert_apply,
                          capacity=capacity, axis_name=expert_axis),
        mesh=mesh,
        in_specs=(param_spec, P(), P(data_axis)),
        out_specs=P(data_axis))
    return fn(stacked_params, wr, x)


def moe_capacity(batch, n_experts, capacity_factor=1.25):
    """The per-expert token budget moe_apply uses (for tests/sizing)."""
    return max(1, int(-(-batch * capacity_factor // n_experts)))
