"""Expert parallelism: mixture-of-experts over a mesh axis.

The last of the mesh quintet (data/tensor/pipeline/sequence/expert):
E experts' parameters shard over the ``expert`` axis — each device owns
ONE expert and computes only the tokens routed to it (bounded by a
capacity), so expert compute scales with the axis instead of
replicating.  Routing is a linear router + softmax gate with tokens
over capacity dropped (the standard trade; capacity_factor sizes the
buffer).  Two routing depths and two dispatch layouts:

- top-1 (switch-transformer) or top-k (GShard style, ``k=2`` default
  for ``moe_apply(..., k=2)``): the k chosen gates renormalize to sum
  to 1; choice 1 fills capacity before choice 2 (the standard
  priority), so a second choice never evicts a first.
- replicated dispatch (:func:`moe_apply`): tokens live on every expert
  shard, the combine is a masked ``psum``.  Simple, right for models
  whose batch fits every device.
- token-sharded dispatch (:func:`moe_apply_a2a`): tokens are SHARDED
  over the expert axis (dp x ep: the expert axis doubles as a data
  axis), packed per destination expert into fixed-capacity buffers and
  exchanged with ``lax.all_to_all`` over ICI, expert compute runs on
  its own shard only, and a second all_to_all carries results home.
  Per-device input bandwidth now scales with E — this is the scale-up
  path the replicated layout cannot reach (VERDICT round-3 item 5).
  Capacity is per (source shard, expert): C = ceil(k * B_local * cf / E).

Autodiff flows through routing (straight-through on the gate
probability) and through both all_to_alls (their transpose is the
reverse all_to_all), so both layers train end-to-end
(tests/test_moe.py)."""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def router_probs(wr, x):
    """[B, E] softmax router probabilities."""
    return jax.nn.softmax(x @ wr, axis=-1)


def load_balance_loss(wr, x):
    """The switch-transformer auxiliary balancing loss:
    ``E * sum_e(f_e * P_e)`` with f_e the fraction of tokens routed to
    expert e and P_e its mean router probability (minimized at uniform
    routing, value 1.0).  ADD THIS (scaled ~1e-2) to the task loss when
    training through :func:`moe_apply` — top-1 routing with a capacity
    otherwise collapses onto the strongest expert and drops the rest of
    the batch."""
    probs = router_probs(wr, x)
    e = probs.shape[-1]
    assign = jnp.argmax(probs, axis=-1)
    fraction = jnp.mean(
        jax.nn.one_hot(assign, e, dtype=probs.dtype), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return e * jnp.sum(fraction * mean_prob)


def _topk_routing(probs, k):
    """(dsts[B, k], gates[B, k]) — top-k experts per token, gates
    renormalized over the chosen k (for k=1 the gate is the raw top
    probability, the switch-transformer convention)."""
    topv, topi = lax.top_k(probs, k)
    if k == 1:
        return topi, topv
    return topi, topv / jnp.sum(topv, axis=-1, keepdims=True)


def _choice_major_slots(dsts, n_experts):
    """Capacity queue positions, choice-major: ALL first choices (in
    batch order) fill an expert's slots before any second choice — a
    2nd pick never evicts a 1st (the GShard priority).  ``dsts`` is
    [B, k]; returns pos[B, k] (the token's slot in its expert's
    queue)."""
    b, k = dsts.shape
    flat = dsts.transpose(1, 0).reshape(-1)           # choice-major
    onehot = jax.nn.one_hot(flat, n_experts, dtype=jnp.int32)
    pos_flat = jnp.cumsum(onehot, axis=0) - 1
    pos_flat = jnp.take_along_axis(pos_flat, flat[:, None],
                                   axis=1)[:, 0]
    return pos_flat.reshape(k, b).transpose(1, 0)


def moe_reference(expert_apply, stacked_params, wr, x, capacity, k=1):
    """Single-device oracle: same top-k routing, same choice-major
    capacity drops, experts applied in a scan."""
    e = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    probs = router_probs(wr, x)
    dsts, gates = _topk_routing(probs, k)
    pos = _choice_major_slots(dsts, e)
    keep = pos < capacity
    out = jnp.zeros_like(expert_apply(
        jax.tree.map(lambda p: p[0], stacked_params), x))

    def per_expert(out, i):
        params_i = jax.tree_util.tree_map(lambda p: p[i], stacked_params)
        y = expert_apply(params_i, x)
        w = jnp.sum(jnp.where(jnp.logical_and(dsts == i, keep),
                              gates, 0.0), axis=1)
        return out + y * w[:, None], None

    out, _ = lax.scan(per_expert, out, jnp.arange(e))
    return out


def _moe_local(stacked_params, wr, x, *, expert_apply, capacity,
               axis_name, k):
    """The per-shard body.  Also reused (inside a caller-owned
    shard_map binding more axes) by znicz.samples.flagship — keep the
    signature and the leading-local-expert-dim-1 params convention in
    sync with it."""
    e_idx = lax.axis_index(axis_name)
    params_e = jax.tree.map(lambda p: p[0], stacked_params)
    b, d = x.shape
    probs = router_probs(wr, x)
    n_experts = probs.shape[-1]
    dsts, gates = _topk_routing(probs, k)
    pos = _choice_major_slots(dsts, n_experts)
    keep = pos < capacity
    # this shard's view: which (token, choice) pairs point at me
    mine = jnp.logical_and(dsts == e_idx, keep)
    # pack my tokens into a [capacity, D] buffer (one extra trash row
    # absorbs everything dropped or foreign); a token picking me in any
    # choice lands in its queue slot
    slot = jnp.where(mine, pos, capacity)      # [b, k]
    buf = jnp.zeros((capacity + 1, d), x.dtype)
    for j in range(k):
        buf = buf.at[slot[:, j]].set(x)
    y = expert_apply(params_e, buf[:capacity])
    # unpack: each (token, choice) routed here reads its slot's row,
    # weighted by its renormalized gate
    out = 0.0
    for j in range(k):
        row = y[jnp.clip(pos[:, j], 0, capacity - 1)]
        out = out + jnp.where(mine[:, j, None],
                              row * gates[:, j, None], 0.0)
    # every (token, choice) was computed on exactly one expert shard
    return lax.psum(out, axis_name)


def _check_expert_counts(mesh, expert_axis, stacked_params, wr):
    n_experts = mesh.shape[expert_axis]
    stacked_e = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if stacked_e != n_experts or wr.shape[1] != n_experts:
        # a divisible mismatch would shard "evenly" and silently zero
        # every token routed to an expert no device owns
        raise ValueError(
            "expert count mismatch: params stack %d, router %d, mesh "
            "axis %d" % (stacked_e, wr.shape[1], n_experts))
    return n_experts


def moe_apply(expert_apply, stacked_params, wr, x, mesh,
              expert_axis="expert", data_axis=None,
              capacity_factor=1.25, k=1):
    """Expert-parallel top-k MoE over ``mesh[expert_axis]``, replicated
    token layout.

    expert_apply(params_i, h[B, D]) -> [B, D']; ``stacked_params``
    leading dim = E (sharded over the expert axis); ``wr`` [D, E]
    replicated router weights; ``x`` [B, D] (B over ``data_axis`` when
    given).  capacity = ceil(k * B/E * capacity_factor) tokens per
    expert,
    overflow dropped exactly like the reference oracle (choice-major
    for k > 1).

    Training: include :func:`load_balance_loss` in the objective —
    without it top-1 routing collapses and the capacity drops most of
    the batch."""
    from jax.sharding import PartitionSpec as P
    n_experts = _check_expert_counts(mesh, expert_axis, stacked_params,
                                     wr)
    local_b = x.shape[0] // (mesh.shape[data_axis] if data_axis else 1)
    capacity = moe_capacity(local_b, n_experts, capacity_factor, k)
    param_spec = jax.tree.map(lambda _: P(expert_axis), stacked_params)
    fn = jax.shard_map(
        functools.partial(_moe_local, expert_apply=expert_apply,
                          capacity=capacity, axis_name=expert_axis,
                          k=k),
        mesh=mesh,
        in_specs=(param_spec, P(), P(data_axis)),
        out_specs=P(data_axis))
    return fn(stacked_params, wr, x)


def _moe_a2a_local(stacked_params, wr, x, *, expert_apply, capacity,
                   axis_name, k):
    """Token-sharded dispatch: ``x`` here is THIS device's B/E tokens.

    pack -> all_to_all -> expert -> all_to_all back -> combine; see the
    module docstring.  Capacity is per (source shard, destination
    expert), so the exchanged buffers are static [E, capacity, D]."""
    params_e = jax.tree.map(lambda p: p[0], stacked_params)
    bl, d = x.shape
    probs = router_probs(wr, x)
    n_experts = probs.shape[-1]
    dsts, gates = _topk_routing(probs, k)
    pos = _choice_major_slots(dsts, n_experts)          # [bl, k]
    keep = pos < capacity
    # pack: one [E, capacity, D] buffer of my tokens by destination
    # (+1 trash row per expert absorbs drops)
    buf = jnp.zeros((n_experts, capacity + 1, d), x.dtype)
    for j in range(k):
        slot = jnp.where(keep[:, j], pos[:, j], capacity)
        buf = buf.at[dsts[:, j], slot].set(x)
    # exchange: received[src] = the buffer shard src packed for me
    received = lax.all_to_all(buf[:, :capacity], axis_name, 0, 0,
                              tiled=True)               # [E, cap, D]
    y = expert_apply(params_e, received.reshape(n_experts * capacity, d))
    y = y.reshape(n_experts, capacity, -1)
    # return results to their source shards
    back = lax.all_to_all(y, axis_name, 0, 0, tiled=True)
    # combine: each (token, choice) reads its slot from its expert's
    # returned buffer, weighted by the renormalized gate
    out = 0.0
    for j in range(k):
        row = back[dsts[:, j], jnp.clip(pos[:, j], 0, capacity - 1)]
        out = out + jnp.where(keep[:, j, None],
                              row * gates[:, j, None], 0.0)
    return out


def moe_apply_a2a(expert_apply, stacked_params, wr, x, mesh,
                  expert_axis="expert", data_axis=None,
                  capacity_factor=1.25, k=1):
    """Expert-parallel top-k MoE with token-sharded all_to_all dispatch.

    Same contract as :func:`moe_apply` except tokens are SHARDED, not
    replicated: ``x``'s batch splits over ``(data_axis, expert_axis)``
    (or just the expert axis), each device routes only its B/(D*E)
    tokens, and the dispatch/combine ride two ``all_to_all`` collectives
    over ICI.  Per-device input bandwidth scales with the axis size —
    use this once token counts outgrow replication.  Capacity (and so
    the drop rule) is per (source shard, expert):
    ``ceil(k * B_local * capacity_factor / E)`` — vs the replicated path's
    single global queue; :func:`moe_a2a_reference` is the matching
    oracle."""
    from jax.sharding import PartitionSpec as P
    n_experts = _check_expert_counts(mesh, expert_axis, stacked_params,
                                     wr)
    shards = n_experts * (mesh.shape[data_axis] if data_axis else 1)
    if x.shape[0] % shards:
        raise ValueError("batch %d not divisible by %d token shards"
                         % (x.shape[0], shards))
    local_b = x.shape[0] // shards
    capacity = moe_capacity(local_b, n_experts, capacity_factor, k)
    param_spec = jax.tree.map(lambda _: P(expert_axis), stacked_params)
    batch_axes = (data_axis, expert_axis) if data_axis else expert_axis
    fn = jax.shard_map(
        functools.partial(_moe_a2a_local, expert_apply=expert_apply,
                          capacity=capacity, axis_name=expert_axis,
                          k=k),
        mesh=mesh,
        in_specs=(param_spec, P(), P(batch_axes)),
        out_specs=P(batch_axes))
    return fn(stacked_params, wr, x)


def moe_a2a_reference(expert_apply, stacked_params, wr, x, n_shards,
                      capacity, k=1):
    """Single-device oracle for :func:`moe_apply_a2a`: the batch is
    split into ``n_shards`` source shards, each with its own per-expert
    choice-major capacity queue."""
    parts = jnp.split(x, n_shards)
    return jnp.concatenate([
        moe_reference(expert_apply, stacked_params, wr, part, capacity,
                      k=k)
        for part in parts])


def moe_capacity(batch, n_experts, capacity_factor=1.25, k=1):
    """The per-expert token budget moe_apply uses (for tests/sizing):
    ``ceil(k * batch * capacity_factor / n_experts)`` — scaled by the
    routing depth (k * batch (token, choice) pairs compete for slots;
    the GShard sizing)."""
    return max(1, int(-(-k * batch * capacity_factor // n_experts)))
