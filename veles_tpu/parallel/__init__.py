"""Distributed execution over device meshes.

This package replaces the reference's entire distributed runtime
(/root/reference/veles/server.py, client.py, txzmq/ — a ZeroMQ+Twisted
parameter-server star, SURVEY.md §2.4) with in-program XLA collectives over
a :class:`jax.sharding.Mesh`: data-parallel gradient all-reduce rides ICI
(psum inserted by XLA from sharding annotations), tensor-parallel layer
sharding splits the MXU work, and sequence parallelism (ring attention)
handles long contexts.  The out-of-band job protocol survives separately in
:mod:`veles_tpu.distributed` for the meta-schedulers (ensembles, GA).
"""

from .mesh import make_mesh, data_parallel_sharding, batch_sharding  # noqa
from .dp import DistributedTrainStep                                 # noqa
