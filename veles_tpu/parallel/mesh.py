"""Mesh construction and sharding helpers.

The mental model follows the public scaling playbook: pick a mesh, annotate
shardings on params and batch, let XLA insert the collectives, profile,
iterate.  Axis conventions:

- ``data``  — batch (data parallelism; gradient psum over this axis)
- ``model`` — hidden/feature dims (tensor parallelism)
- ``seq``   — sequence dim (context parallelism / ring attention)

A mesh is laid out so ``data`` spans the slowest-varying device dimension
(DCN across slices in a real pod) and ``model`` the fastest (ICI
neighbors).
"""

import numpy


def make_mesh(axes=None, devices=None):
    """Build a Mesh from ``{"axis": size}``; sizes must multiply to the
    device count (one axis may be -1 to absorb the remainder)."""
    import jax
    from jax.sharding import Mesh
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    axes = dict(axes or {"data": n})
    names, sizes = list(axes.keys()), list(axes.values())
    if -1 in sizes:
        known = int(numpy.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    if int(numpy.prod(sizes)) != n:
        raise ValueError("mesh %s does not cover %d devices" %
                         (dict(zip(names, sizes)), n))
    dev_array = numpy.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def batch_sharding(mesh, data_axis="data"):
    """Sharding for a [batch, ...] array: split the leading dim."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(data_axis))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def data_parallel_sharding(mesh, params_tree):
    """Replicate every param (pure DP)."""
    import jax
    rep = replicated(mesh)
    return jax.tree.map(lambda _: rep, params_tree)


def tensor_parallel_sharding(mesh, params_tree, model_axis="model"):
    """Column-split tensor parallelism: weights split their *output*
    dim on ``model`` — 2-D FC weights on dim 1, 4-D conv kernels
    (ky, kx, c_in, n_kernels) on the kernel dim 3 (so each model-shard
    computes a slice of the output channels; XLA partitions the conv and
    gathers activations before the next layer — one collective per
    layer), 1-D biases on dim 0.  Everything indivisible replicates.
    (A Megatron alternating column/row scheme would halve the
    collectives; tracked as a future optimization.)"""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def spec(p):
        ndim = getattr(p, "ndim", 0)
        if ndim == 2 and p.shape[1] % mesh.shape[model_axis] == 0:
            return NamedSharding(mesh, P(None, model_axis))
        if ndim == 4 and p.shape[3] % mesh.shape[model_axis] == 0:
            return NamedSharding(mesh, P(None, None, None, model_axis))
        if ndim == 1 and p.shape[0] % mesh.shape[model_axis] == 0:
            return NamedSharding(mesh, P(model_axis))
        return NamedSharding(mesh, P())
    import jax
    return jax.tree.map(spec, params_tree)
