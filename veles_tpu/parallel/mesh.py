"""Mesh construction and sharding helpers.

The mental model follows the public scaling playbook: pick a mesh, annotate
shardings on params and batch, let XLA insert the collectives, profile,
iterate.  Axis conventions:

- ``data``   — batch (data parallelism; gradient psum over this axis)
- ``model``  — hidden/feature dims (tensor parallelism)
- ``seq``    — sequence dim (ring attention, parallel/ring.py)
- ``pipe``   — pipeline stages (GPipe schedule, parallel/pipeline.py)
- ``expert`` — MoE experts (switch routing, parallel/moe.py)

A mesh is laid out so ``data`` spans the slowest-varying device
dimension (DCN across slices in a real pod) and the ppermute-ring axes
(``model``, and especially ``seq``/``pipe`` whose hops are
neighbor-to-neighbor every tick) the fastest (ICI neighbors);
``expert`` sits between — its psum combine is bandwidth-bound but not
latency-critical.
"""

import numpy


def make_mesh(axes=None, devices=None):
    """Build a Mesh from ``{"axis": size}``; sizes must multiply to the
    device count (one axis may be -1 to absorb the remainder)."""
    import jax
    from jax.sharding import Mesh
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    axes = dict(axes or {"data": n})
    names, sizes = list(axes.keys()), list(axes.values())
    if -1 in sizes:
        known = int(numpy.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    if int(numpy.prod(sizes)) != n:
        raise ValueError("mesh %s does not cover %d devices" %
                         (dict(zip(names, sizes)), n))
    dev_array = numpy.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def mesh_for_spec(spec, devices=None):
    """Rebuild a Mesh from a pickled :func:`mesh_spec` on THIS process.

    Unlike :func:`make_mesh` the spec need not cover every local device:
    the first ``prod(sizes)`` devices are taken, so a snapshot written
    on a small topology restores on a bigger host unchanged (and the
    caller may always assign a different Mesh before initialize for a
    true cross-mesh restore)."""
    import jax
    sizes = [int(s) for s in dict(spec).values()]
    if -1 in sizes:
        return make_mesh(spec, devices)
    n = int(numpy.prod(sizes))
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n:
        raise ValueError("mesh %s needs %d devices; this process has %d"
                         % (dict(spec), n, len(devices)))
    return make_mesh(spec, devices[:n])


def mesh_spec(mesh):
    """Picklable ``{axis: size}`` geometry of a Mesh.  jax Device
    handles are process-local and cannot be pickled — snapshots store
    the spec and ``make_mesh(spec)`` rebuilds the mesh on the restoring
    process's devices (the sharded steps do this in initialize)."""
    return {name: int(size) for name, size in mesh.shape.items()}


def register_mesh_metrics(mesh, workflow="-"):
    """Publish the mesh topology into the observability registry (one
    gauge series per axis) and stamp a ``mesh.initialized`` instant into
    the event log — a scrape of ``/metrics`` then says exactly what
    geometry a distributed step is running on."""
    from ..logger import events
    from ..observability.registry import REGISTRY
    g = REGISTRY.gauge("veles_mesh_axis_devices",
                       "Device-mesh axis sizes of the sharded step",
                       ("workflow", "axis"))
    for axis, size in mesh.shape.items():
        g.labels(workflow=workflow, axis=axis).set(int(size))
    REGISTRY.gauge("veles_mesh_devices_total",
                   "Total devices in the sharded step's mesh",
                   ("workflow",)).labels(workflow=workflow) \
        .set(int(numpy.prod(list(mesh.shape.values()))))
    events.event("mesh.initialized", workflow=workflow,
                 axes=dict(mesh.shape))


def batch_sharding(mesh, data_axis="data"):
    """Sharding for a [batch, ...] array: split the leading dim."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(data_axis))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def trainer_shardings(mesh, params, opt, model_axis=None,
                      tp_mode="column"):
    """The fused trainers' operand shardings: params tensor-sharded over
    ``model_axis`` when given (else replicated DP), opt-state entries
    shaped like their param (momentum buffers, adadelta tuples), plus
    the replicated spec for scalars/metrics.  Shared by the per-step
    (parallel/dp.py) and epoch-scan (parallel/scan.py) mesh trainers."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if model_axis and model_axis in mesh.shape:
        param_shard = tensor_parallel_sharding(mesh, params, model_axis,
                                               mode=tp_mode)
    else:
        param_shard = data_parallel_sharding(mesh, params)
    opt_shard = [
        {name: tuple(param_shard[i][name]
                     for _ in range(len(opt[i][name])))
         if isinstance(opt[i][name], tuple)
         else param_shard[i][name]
         for name in opt[i]}
        for i in range(len(opt))]
    return param_shard, opt_shard, NamedSharding(mesh, P())


def data_parallel_sharding(mesh, params_tree):
    """Replicate every param (pure DP)."""
    import jax
    rep = replicated(mesh)
    return jax.tree.map(lambda _: rep, params_tree)


def tensor_parallel_sharding(mesh, params_tree, model_axis="model",
                             mode="column"):
    """Tensor parallelism over ``model``.

    ``mode="column"`` (default): every weight splits its *output* dim —
    2-D FC weights on dim 1, 4-D conv kernels (ky, kx, c_in, n_kernels)
    on the kernel dim 3 (each model-shard computes a slice of the output
    channels; XLA partitions the conv and gathers activations before the
    next layer — one collective per layer), 1-D biases on dim 0.

    ``mode="megatron"``: consecutive divisible 2-D FC weights ALTERNATE
    column (None, model) then row (model, None) splits — the Megatron
    MLP pairing.  A column layer's output stays feature-sharded, the
    following row layer consumes it shard-local, and only ONE psum (the
    row matmul's reduction) fires per pair instead of a gather per
    layer.  Row-split layers replicate their bias (it adds to a reduced,
    replicated activation); conv kernels keep the output-channel split.

    Everything indivisible replicates.  ``params_tree`` is the per-layer
    list of param dicts the fused trainers carry; megatron mode walks it
    in layer order to assign the alternation."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    import jax

    size = mesh.shape[model_axis]
    col2 = NamedSharding(mesh, P(None, model_axis))
    row2 = NamedSharding(mesh, P(model_axis, None))
    col1 = NamedSharding(mesh, P(model_axis))
    rep = NamedSharding(mesh, P())

    def base_spec(p):
        ndim = getattr(p, "ndim", 0)
        if ndim == 2 and p.shape[1] % size == 0:
            return col2
        if ndim == 4 and p.shape[3] % size == 0:
            return NamedSharding(mesh, P(None, None, None, model_axis))
        if ndim == 1 and p.shape[0] % size == 0:
            return col1
        return rep

    if mode not in ("column", "megatron"):
        raise ValueError("tp mode must be 'column' or 'megatron', got %r"
                         % (mode,))
    if mode == "column" or not isinstance(params_tree, (list, tuple)):
        return jax.tree.map(base_spec, params_tree)
    out = []
    want_row = False  # first eligible FC layer is column-split
    for layer in params_tree:
        if not isinstance(layer, dict):
            out.append(jax.tree.map(base_spec, layer))
            continue
        w = layer.get("weights")
        if getattr(w, "ndim", 0) != 2:
            # a non-FC layer (conv, paramless) breaks the pairing: its
            # output is not contracted-dim-sharded, so row-splitting the
            # next FC would only add resharding traffic
            want_row = False
        specs = {}
        if getattr(w, "ndim", 0) == 2 and want_row \
                and w.shape[0] % size == 0:
            specs["weights"] = row2
            # the row matmul's output is already reduced/replicated:
            # its bias must replicate too
            for name, p in layer.items():
                if name != "weights":
                    specs[name] = rep
            want_row = False
        else:
            for name, p in layer.items():
                specs[name] = base_spec(p)
            if getattr(w, "ndim", 0) == 2 and w.shape[1] % size == 0:
                want_row = True  # next divisible FC pairs as the row
        out.append(specs)
    return out
