"""Ring attention: sequence/context parallelism over the ``seq`` axis.

The reference predates attention models, but its capability surface —
"scale the model/sequence beyond one device" — maps on TPU to sequence
parallelism: shard the sequence over a mesh axis and rotate K/V blocks
around the ICI ring (`lax.ppermute`), accumulating attention with the
online-softmax (flash) recurrence so no device ever materializes the
full [T, T] score matrix or the full K/V.  This is the standard ring
attention construction (Liu et al. 2023; see PAPERS.md) expressed the
JAX-native way: `shard_map` over a Mesh axis + in-program collectives,
composable with the ``data`` axis for DP x SP meshes.

Numerics: block products in f32 (``preferred_element_type``), the
running max/denominator recurrence is exactly flash attention's, so the
result matches single-device softmax attention to f32 tolerance
(asserted in tests/test_ring_attention.py).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def attention_reference(q, k, v, causal=False, scale=None):
    """Plain single-device softmax attention, [B, T, H, D] layout —
    the parity oracle (and the small-model fallback)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(tk)[None, :] > jnp.arange(tq)[:, None]
        s = jnp.where(mask, -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _ring_attention_local(q, k, v, axis_name, causal, scale,
                          vary_axes=None):
    """Per-shard body: local Q stays put, K/V blocks ride the ring.

    q/k/v: [B, T_local, H, D] (this device's sequence chunk).  Also
    reused (inside a caller-owned shard_map binding more axes) by
    znicz.samples.flagship — keep the signature in sync with it."""
    n_dev = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    q32 = q.astype(jnp.float32)

    # flash accumulators: running max m, denominator l, output acc
    m0 = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    acc0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    # fresh zeros are unvarying over the mesh axis; the loop carry mixes
    # them with shard-varying data, so mark them varying up front (the
    # new shard_map type system requires carry in/out types to agree)
    m0, l0, acc0 = lax.pcast((m0, l0, acc0),
                             vary_axes or (axis_name,), to="varying")
    q_pos = my_idx * t_local + jnp.arange(t_local)

    def step(i, carry):
        k_blk, v_blk, m, l, acc = carry
        src = (my_idx - i) % n_dev  # which shard this K/V block came from
        s = jnp.einsum("bqhd,bkhd->bhqk", q32,
                       k_blk.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = k_pos[None, :] > q_pos[:, None]
            s = jnp.where(mask[None, None], -jnp.inf, s)
        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # guard: a fully-masked block keeps m at -inf; exp(-inf - -inf)
        # must be 0, not nan
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        p = jnp.exp(s - safe_m[..., None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        m = new_m
        # rotate K/V one hop around the ring (ICI neighbor exchange)
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, acc

    _, _, m, l, acc = lax.fori_loop(0, n_dev, step,
                                    (k, v, m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows output 0
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, seq_axis="seq", data_axis=None,
                   causal=False, scale=None):
    """Sequence-parallel attention over ``mesh[seq_axis]``.

    q/k/v: [B, T, H, D] with T divisible by the seq-axis size (and B by
    the data axis when given).  Returns [B, T, H, D], numerically equal
    to :func:`attention_reference` on one device."""
    from jax.sharding import PartitionSpec as P
    shard_map = jax.shard_map

    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    spec = P(data_axis, seq_axis, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=seq_axis,
                          causal=causal, scale=scale,
                          vary_axes=(seq_axis,) + (
                              (data_axis,) if data_axis else ())),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
