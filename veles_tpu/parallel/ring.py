"""Ring attention: sequence/context parallelism over the ``seq`` axis.

The reference predates attention models, but its capability surface —
"scale the model/sequence beyond one device" — maps on TPU to sequence
parallelism: shard the sequence over a mesh axis and rotate K/V blocks
around the ICI ring (`lax.ppermute`), accumulating attention with the
online-softmax (flash) recurrence so no device ever materializes the
full [T, T] score matrix or the full K/V.  This is the standard ring
attention construction (Liu et al. 2023; see PAPERS.md) expressed the
JAX-native way: `shard_map` over a Mesh axis + in-program collectives,
composable with the ``data`` axis for DP x SP meshes.

Numerics: block products in f32 (``preferred_element_type``), the
running max/denominator recurrence is exactly flash attention's, so the
result matches single-device softmax attention to f32 tolerance
(asserted in tests/test_ring_attention.py).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def attention_reference(q, k, v, causal=False, scale=None,
                        window=None):
    """Plain single-device softmax attention, [B, T, H, D] layout —
    the parity oracle (and the small-model fallback).  ``window``
    (requires ``causal``): sliding-window attention — position i sees
    keys in (i - window, i], the Mistral-style band."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if window is not None and window < 1:
        raise ValueError("window must be >= 1, got %r" % (window,))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        rows = jnp.arange(tq)[:, None]
        cols = jnp.arange(tk)[None, :]
        mask = cols > rows
        if window is not None:
            mask = mask | (cols <= rows - window)
        s = jnp.where(mask, -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _ring_attention_local(q, k, v, axis_name, causal, scale,
                          vary_axes=None):
    """Per-shard body: local Q stays put, K/V blocks ride the ring.

    q/k/v: [B, T_local, H, D] (this device's sequence chunk).  Also
    reused (inside a caller-owned shard_map binding more axes) by
    znicz.samples.flagship — keep the signature in sync with it."""
    n_dev = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    q32 = q.astype(jnp.float32)

    # flash accumulators: running max m, denominator l, output acc
    m0 = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    acc0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    # fresh zeros are unvarying over the mesh axis; the loop carry mixes
    # them with shard-varying data, so mark them varying up front (the
    # new shard_map type system requires carry in/out types to agree)
    m0, l0, acc0 = lax.pcast((m0, l0, acc0),
                             vary_axes or (axis_name,), to="varying")
    q_pos = my_idx * t_local + jnp.arange(t_local)

    def step(i, carry):
        k_blk, v_blk, m, l, acc = carry
        src = (my_idx - i) % n_dev  # which shard this K/V block came from
        s = jnp.einsum("bqhd,bkhd->bhqk", q32,
                       k_blk.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = k_pos[None, :] > q_pos[:, None]
            s = jnp.where(mask[None, None], -jnp.inf, s)
        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # guard: a fully-masked block keeps m at -inf; exp(-inf - -inf)
        # must be 0, not nan
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        p = jnp.exp(s - safe_m[..., None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        m = new_m
        # rotate K/V one hop around the ring (ICI neighbor exchange)
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, acc

    _, _, m, l, acc = lax.fori_loop(0, n_dev, step,
                                    (k, v, m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows output 0
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, seq_axis="seq", data_axis=None,
                   causal=False, scale=None, use_pallas=False):
    """Sequence-parallel attention over ``mesh[seq_axis]``.

    q/k/v: [B, T, H, D] with T divisible by the seq-axis size (and B by
    the data axis when given).  Returns [B, T, H, D], numerically equal
    to :func:`attention_reference` on one device.

    ``use_pallas=True`` runs each hop's block math through the Pallas
    flash kernels (ring flash attention, :mod:`znicz.flash_attention`):
    the per-hop [B, H, T_local, T_local] score tensor this module's jnp
    recurrence materializes disappears, so per-device memory stays
    O(T_local * D) — the long-context composition.  Falls back to the
    jnp recurrence when the local chunk can't tile."""
    from jax.sharding import PartitionSpec as P
    shard_map = jax.shard_map

    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    local = _ring_attention_local
    if use_pallas:
        from ..znicz.flash_attention import flash_attention_supported
        t_local = q.shape[1] // mesh.shape[seq_axis]
        if flash_attention_supported(t_local):
            local = _ring_flash_local
    spec = P(data_axis, seq_axis, None, None)
    fn = shard_map(
        functools.partial(local, axis_name=seq_axis,
                          causal=causal, scale=scale,
                          vary_axes=(seq_axis,) + (
                              (data_axis,) if data_axis else ())),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


# -- ring flash attention ----------------------------------------------------
#
# The ring recurrence above is already flash-attention math ACROSS hops;
# ring flash attention additionally makes each hop's block computation a
# Pallas flash kernel call, so nothing quadratic in T_local exists
# either.  Gradients cannot flow through raw pallas_call, so the WHOLE
# per-shard ring is one custom_vjp: the forward saves the global
# logsumexp, and the backward is a second ring pass — dk/dv accumulators
# rotate along with their K/V blocks and arrive home after n hops (no
# psum needed), exactly the published ring-flash construction (Liu et
# al. 2023), built from this repo's own flash kernel pair.


def _hop_mode(src, my_idx, causal):
    """0 = block fully visible, 1 = diagonal (causal mask), 2 = skip."""
    if not causal:
        return jnp.int32(0)
    return jnp.where(src < my_idx, 0, jnp.where(src == my_idx, 1, 2))


def _ring_flash_fwd_pass(q, k, v, axis_name, causal, scale,
                         vary_axes=None):
    from ..znicz.flash_attention import (DEFAULT_BLOCK_K,
                                         DEFAULT_BLOCK_Q, _NEG_INF,
                                         _blocks, _flash_fwd_bh,
                                         _from_bh, _to_bh)
    n_dev = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    bq, bk = _blocks(t_local, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    q_bh = _to_bh(q)

    vma = frozenset(vary_axes or (axis_name,))

    def attend(causal_flag):
        def run(k_blk, v_blk):
            out_bh, lse = _flash_fwd_bh(
                q_bh, _to_bh(k_blk), _to_bh(v_blk), scale, causal_flag,
                bq, bk, vma=vma)
            # f32 like the skip branch: lax.switch branches must agree
            return (_from_bh(out_bh, b, h).astype(jnp.float32),
                    lse.reshape(b, h, t_local))
        return run

    def skip(k_blk, v_blk):
        return lax.pcast(
            (jnp.zeros((b, t_local, h, d), jnp.float32),
             jnp.full((b, h, t_local), _NEG_INF, jnp.float32)),
            tuple(vma), to="varying")

    def step(i, carry):
        k_blk, v_blk, out, lse = carry
        src = (my_idx - i) % n_dev
        o_blk, lse_blk = lax.switch(
            _hop_mode(src, my_idx, causal),
            [attend(False), attend(True), skip], k_blk, v_blk)
        new_lse = jnp.logaddexp(lse, lse_blk)
        safe = jnp.where(jnp.isneginf(new_lse), 0.0, new_lse)
        wa = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(lse - safe))
        wb = jnp.where(jnp.isneginf(lse_blk), 0.0,
                       jnp.exp(lse_blk - safe))
        # weights are [B, H, Tl]; out is [B, Tl, H, D]
        out = (out * wa.transpose(0, 2, 1)[..., None] +
               o_blk * wb.transpose(0, 2, 1)[..., None])
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        return (lax.ppermute(k_blk, axis_name, perm),
                lax.ppermute(v_blk, axis_name, perm), out, new_lse)

    out0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t_local), _NEG_INF, jnp.float32)
    # fresh zeros are unvarying; the carry mixes them with shard-varying
    # data (same pcast dance as _ring_attention_local:59)
    out0, lse0 = lax.pcast((out0, lse0), vary_axes or (axis_name,),
                           to="varying")
    _, _, out, lse = lax.fori_loop(
        0, n_dev, step, (k, v, out0, lse0))
    return out.astype(q.dtype), lse



def _ring_flash_local(q, k, v, axis_name, causal, scale,
                      vary_axes=None):
    """Per-shard ring flash attention (signature-compatible with
    :func:`_ring_attention_local`)."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def inner(q, k, v):
        out, _ = _ring_flash_fwd_pass(q, k, v, axis_name, causal, scale,
                                      vary_axes)
        return out

    def inner_fwd(q, k, v):
        out, lse = _ring_flash_fwd_pass(q, k, v, axis_name, causal,
                                        scale, vary_axes)
        return out, (q, k, v, out, lse)

    def inner_bwd(res, g):
        from ..znicz.flash_attention import (DEFAULT_BLOCK_K,
                                             DEFAULT_BLOCK_Q,
                                             _STAT_LANES, _blocks,
                                             _flash_bwd_bh, _from_bh,
                                             _to_bh)
        q, k, v, out, lse = res
        n_dev = lax.psum(1, axis_name)
        my_idx = lax.axis_index(axis_name)
        b, t_local, h, d = q.shape
        bq, bk = _blocks(t_local, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
        q_bh, out_bh, g_bh = _to_bh(q), _to_bh(out), _to_bh(g)
        # lse/delta are hop-invariant: lane-broadcast them ONCE here,
        # not inside every hop's _flash_bwd_bh call
        lse_bh = jnp.broadcast_to(
            lse.reshape(b * h, t_local)[..., None],
            (b * h, t_local, _STAT_LANES))
        delta_bh = jnp.broadcast_to(
            jnp.sum(g_bh.astype(jnp.float32) *
                    out_bh.astype(jnp.float32), axis=-1)[..., None],
            (b * h, t_local, _STAT_LANES))

        vma = frozenset(vary_axes or (axis_name,))

        def bwd(causal_flag):
            def run(k_blk, v_blk):
                dq_bh, dk_bh, dv_bh = _flash_bwd_bh(
                    q_bh, _to_bh(k_blk), _to_bh(v_blk), out_bh, lse_bh,
                    g_bh, scale, causal_flag, bq, bk, vma=vma,
                    delta=delta_bh)
                return (_from_bh(dq_bh, b, h).astype(jnp.float32),
                        _from_bh(dk_bh, b, h).astype(jnp.float32),
                        _from_bh(dv_bh, b, h).astype(jnp.float32))
            return run

        def skip(k_blk, v_blk):
            z = jnp.zeros((b, t_local, h, d), jnp.float32)
            z = lax.pcast(z, tuple(vma), to="varying")
            return z, z, z

        def step(i, carry):
            k_blk, v_blk, dk_blk, dv_blk, dq = carry
            src = (my_idx - i) % n_dev
            dq_c, dk_c, dv_c = lax.switch(
                _hop_mode(src, my_idx, causal),
                [bwd(False), bwd(True), skip], k_blk, v_blk)
            perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
            # dk/dv accumulators RIDE THE RING with their blocks: after
            # n hops block b has visited every device and is home again
            return (lax.ppermute(k_blk, axis_name, perm),
                    lax.ppermute(v_blk, axis_name, perm),
                    lax.ppermute(dk_blk + dk_c, axis_name, perm),
                    lax.ppermute(dv_blk + dv_c, axis_name, perm),
                    dq + dq_c)

        z0 = jnp.zeros((b, t_local, h, d), jnp.float32)
        z0 = lax.pcast(z0, vary_axes or (axis_name,), to="varying")
        _, _, dk, dv, dq = lax.fori_loop(
            0, n_dev, step, (k, v, z0, z0, z0))
        return (dq.astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))

    inner.defvjp(inner_fwd, inner_bwd)
    return inner(q, k, v)
