"""Cross-host trial scheduler: a TCP job-queue master + worker clients.

Re-creation of the reference's meta-level distribution: its ZeroMQ/Twisted
master kept a job queue and farmed GA chromosomes / ensemble instances to
slave processes on other hosts, requeueing jobs whose slave dropped and
respawning dead slaves over SSH (/root/reference/veles/server.py:369-430
job queue, :637-655 respawn; ensemble/base_workflow.py:134-141 trial
farm-out; launcher.py:808-842 remote node launch).

TPU-native redesign: the *gradient* path the reference also pushed through
this channel is gone — in-program XLA collectives over the mesh own it
(``parallel/``).  What remains for an out-of-band control plane is exactly
the meta level: independent CLI trials.  So this module is deliberately
small and dependency-free — newline-delimited JSON over stdlib TCP
sockets, a worklist with drop/requeue semantics mirroring the Loader's
master-index contract, and an elastic local/remote worker pool:

- :class:`JobMaster` — binds, accepts workers, hands each an outstanding
  job, requeues a job when its worker's connection drops mid-trial
  (``max_attempts`` bounds redelivery, like the loader's requeue/drop).
- :func:`worker_loop` / ``python -m veles_tpu.jobserver HOST PORT`` —
  a worker: receives jobs, runs them via :func:`veles_tpu.subproc
  .run_trial`, reports results.  Start it on any host that can reach the
  master (the SSH analog: ``ssh h python -m veles_tpu.jobserver ...``).
- :class:`WorkerPool` — spawns N worker subprocesses (local by default,
  arbitrary launch command for remote) and respawns dead ones with
  exponential backoff, the reference's slave-respawn behavior.

Wired into ``--ensemble-train`` / ``--optimize`` through the CLI's
``--listen ADDR`` / ``--workers N`` flags (__main__.py).
"""

import json
import os
import queue
import socket
import subprocess
import sys
import threading
import time

from .observability import trace as _trace

_SENTINEL_TIMEOUT = 0.1


class Job:
    """One unit of work; ``result`` is set exactly once ``done`` fires.

    ``span`` is the job's span id in the master's trace: the worker
    adopts it as parent, so the merged per-process event files show
    dispatch (master) and execution (worker) causally linked."""

    __slots__ = ("id", "payload", "attempts", "done", "result", "worker",
                 "span")

    def __init__(self, job_id, payload):
        self.id = job_id
        self.payload = payload
        self.attempts = 0
        self.done = threading.Event()
        self.result = None
        self.worker = None
        self.span = _trace.new_id()


def _send(wfile, msg):
    wfile.write((json.dumps(msg) + "\n").encode())
    wfile.flush()


def _recv(rfile):
    line = rfile.readline()
    if not line:
        return None
    return json.loads(line)


class JobMaster:
    """Accepts workers; each connection drains the shared job queue.

    A worker that disconnects mid-job gets its job REQUEUED (attempts+1);
    after ``max_attempts`` deliveries the job fails with the last error —
    the same bounded-redelivery contract the Loader applies to minibatches
    of dropped slaves (loader/base.py requeue/drop_slave)."""

    def __init__(self, host="127.0.0.1", port=0, max_attempts=3,
                 silent=True, secret=None):
        self.max_attempts = max_attempts
        self.silent = silent
        # shared-secret handshake: a hello without the matching token is
        # dropped before any payload (argv/env) is handed out.  Defaults
        # from $VELES_JOB_SECRET so master and workers agree without
        # plumbing; unset = open (fine for the 127.0.0.1 default bind,
        # set it whenever you --listen on a routable address)
        self.secret = secret if secret is not None else \
            os.environ.get("VELES_JOB_SECRET")
        if not self.secret and host not in ("127.0.0.1", "localhost",
                                            "::1"):
            print("jobmaster: WARNING — listening on %s with NO shared "
                  "secret: any host that can reach the port will receive "
                  "trial payloads (argv + env) and can forge results. "
                  "Set VELES_JOB_SECRET on master and workers."
                  % host, file=sys.stderr)
        self.active_workers = 0
        # one trace for everything this master farms out: joins an
        # already-active context (e.g. a traced ensemble run) or starts
        # a fresh trace; carried to workers on every job message
        ctx = _trace.current()
        self.trace_id = ctx.trace_id if ctx else _trace.new_id()
        self._sock = socket.create_server((host, port))
        self.address = self._sock.getsockname()[:2]
        self._pending = queue.Queue()
        self._lock = threading.Lock()
        self._next_id = 0
        self._closing = threading.Event()
        self._conns = []
        self.workers_seen = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="jobmaster-accept")
        self._accept_thread.start()

    def _finish(self, job, result):
        """Complete ``job`` exactly once (result write + done) — the
        single completion protocol; late writers (a worker replying
        after map() timed the job out, a drop racing a timeout) become
        no-ops.  Returns whether THIS call completed the job."""
        with self._lock:
            if job.done.is_set():
                return False
            job.result = result
            job.done.set()
            return True

    # -- submission ----------------------------------------------------------
    def submit(self, payload):
        with self._lock:
            job = Job(self._next_id, payload)
            self._next_id += 1
        self._pending.put(job)
        return job

    def map(self, payloads, timeout=None):
        """Submit every payload, block until all finish, return results
        in submission order."""
        jobs = [self.submit(p) for p in payloads]
        deadline = None if timeout is None else time.monotonic() + timeout
        last_warn = time.monotonic()
        for job in jobs:
            while not job.done.is_set():
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                if job.done.wait(5.0 if remaining is None
                                 else min(5.0, remaining)):
                    break
                now = time.monotonic()
                if self.active_workers == 0 and now - last_warn >= 30.0:
                    # a hang here is otherwise silent (e.g. every pool
                    # worker crashed and the respawn budget is spent)
                    print("jobmaster: jobs pending but no workers "
                          "connected on %s:%d" % self.address,
                          file=sys.stderr)
                    last_warn = now
                if deadline is not None and now >= deadline:
                    self._finish(job, {"rc": -1, "results": None,
                                       "error": "scheduler timeout",
                                       "worker": job.worker,
                                       "attempts": job.attempts})
        return [j.result for j in jobs]

    def close(self):
        self._closing.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        # idle handlers notice _closing within _SENTINEL_TIMEOUT and say
        # bye; give them that window before cutting live connections
        time.sleep(2 * _SENTINEL_TIMEOUT)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    # -- internals -----------------------------------------------------------
    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True, name="jobmaster-worker").start()

    def _serve(self, conn):
        rfile = conn.makefile("rb")
        wfile = conn.makefile("wb")
        current = None
        name = "?"
        admitted = False
        try:
            hello = _recv(rfile)
            if not hello or hello.get("op") != "hello":
                return
            if self.secret and hello.get("token") != self.secret:
                if not self.silent:
                    print("jobmaster: rejected worker with bad token",
                          file=sys.stderr)
                return
            name = hello.get("name", "?")
            with self._lock:
                self.workers_seen += 1
                self.active_workers += 1
                admitted = True
            while not self._closing.is_set():
                try:
                    job = self._pending.get(timeout=_SENTINEL_TIMEOUT)
                except queue.Empty:
                    continue
                if job.done.is_set():  # e.g. failed by map() timeout
                    continue
                current = job
                job.attempts += 1
                job.worker = name
                t_dispatch = time.perf_counter()
                _send(wfile, {"op": "job", "id": job.id,
                              "payload": job.payload,
                              "trace": {"trace_id": self.trace_id,
                                        "parent_span": job.span}})
                msg = _recv(rfile)
                if msg is None:
                    raise ConnectionError("worker %s died mid-job" % name)
                if msg.get("op") != "result" or msg.get("id") != job.id:
                    raise ConnectionError(
                        "protocol error from %s: %r" % (name, msg))
                # map() may have already failed this job with a timeout
                # result; the late worker reply must not silently
                # overwrite what map() returned
                self._finish(job, {"rc": msg.get("rc"),
                                   "results": msg.get("results"),
                                   "error": msg.get("error"),
                                   "worker": name,
                                   "attempts": job.attempts})
                # master-side view of the same job span the worker ran
                # under — merged traces link the two via span ids
                from .logger import events
                events.span("job.dispatch",
                            time.perf_counter() - t_dispatch,
                            job=job.id, worker=name,
                            attempts=job.attempts,
                            trace_id=self.trace_id, span=job.span)
                current = None
            try:
                _send(wfile, {"op": "bye"})
            except OSError:
                pass
        except Exception as exc:  # noqa: BLE001 — ANY handler failure
            # (socket drop, bad JSON, malformed message shape) must give
            # the in-flight job back to the queue, or map() hangs forever
            if current is not None:
                self._requeue(current, "%s: %s" % (type(exc).__name__,
                                                   exc))
        finally:
            if admitted:
                with self._lock:
                    self.active_workers -= 1
            try:
                conn.close()
            except OSError:
                pass

    def _requeue(self, job, reason):
        if job.done.is_set():
            return  # e.g. map() already timed it out — nothing to redo
        if job.attempts >= self.max_attempts:
            if self._finish(job, {"rc": -1, "results": None,
                                  "error": "job failed after %d "
                                           "deliveries: %s"
                                           % (job.attempts, reason),
                                  "worker": job.worker,
                                  "attempts": job.attempts}) \
                    and not self.silent:
                print("jobmaster: dropping job %d (%s)"
                      % (job.id, reason), file=sys.stderr)
        else:
            if not self.silent:
                print("jobmaster: requeueing job %d (%s)"
                      % (job.id, reason), file=sys.stderr)
            self._pending.put(job)


# -- worker ------------------------------------------------------------------
def execute_payload(payload, python=None):
    """Run one job payload; returns {"rc", "results", "error"}.

    Kinds: ``trial`` — a CLI model trial via subproc.run_trial (the real
    workload); ``eval`` — echo ``value`` after ``sleep`` seconds (tests,
    liveness probes); ``crash_once`` — simulate a worker crash the FIRST
    time the job is delivered anywhere (flag-file guarded), used by the
    requeue drill."""
    kind = payload.get("kind", "trial")
    if kind == "trial":
        from .subproc import run_trial
        rc, results, error = run_trial(
            payload["model"], payload.get("argv", ()),
            timeout=payload.get("timeout"), python=python,
            env=payload.get("env"))
        return {"rc": rc, "results": results, "error": error}
    if kind == "eval":
        time.sleep(payload.get("sleep", 0))
        return {"rc": 0, "results": {"value": payload.get("value")},
                "error": None}
    if kind == "crash_once":
        flag = payload["flag"]
        try:
            fd = os.open(flag, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            time.sleep(payload.get("sleep", 0))
            return {"rc": 0, "results": {"value": payload.get("value")},
                    "error": None}
        os.close(fd)
        os._exit(17)  # hard crash mid-job: the master must requeue
    return {"rc": -2, "results": None,
            "error": "unknown payload kind %r" % kind}


def worker_loop(host, port, name=None, python=None, secret=None):
    """Connect to the master and serve jobs until it says bye."""
    name = name or "%s-%d" % (socket.gethostname(), os.getpid())
    secret = secret if secret is not None else \
        os.environ.get("VELES_JOB_SECRET")
    sock = socket.create_connection((host, port))
    rfile = sock.makefile("rb")
    wfile = sock.makefile("wb")
    try:
        hello = {"op": "hello", "name": name, "pid": os.getpid()}
        if secret:
            hello["token"] = secret
        _send(wfile, hello)
        while True:
            msg = _recv(rfile)
            if msg is None or msg.get("op") == "bye":
                return
            if msg.get("op") != "job":
                continue
            # run under the master's trace context: this worker's
            # events (and any trial subprocess it spawns — run_trial
            # injects the context into the child env) share the
            # master's trace_id, parented on the job's span
            with _trace.adopt(msg.get("trace")):
                t0 = time.perf_counter()
                result = execute_payload(msg["payload"], python=python)
                from .logger import events
                events.span("job.run", time.perf_counter() - t0,
                            job=msg["id"], worker=name,
                            payload_kind=msg["payload"].get("kind",
                                                            "trial"),
                            rc=result.get("rc"))
            result.update({"op": "result", "id": msg["id"]})
            _send(wfile, result)
    finally:
        try:
            sock.close()
        except OSError:
            pass


class WorkerPool:
    """Spawn ``n`` worker processes and respawn dead ones with backoff.

    ``command`` is the launch template (list; ``{host}``/``{port}``
    placeholders substituted) — the default launches local subprocesses;
    pass e.g. ``["ssh", "node7", sys.executable, "-m",
    "veles_tpu.jobserver", "{host}", "{port}"]`` for the reference's
    remote-slave behavior (server.py:637-655)."""

    def __init__(self, address, n=2, python=None, command=None,
                 max_respawns=3, backoff=0.5, env=None):
        self.address = address
        self.python = python or sys.executable
        self.command = command
        self.max_respawns = max_respawns
        self.backoff = backoff
        self.env = env
        self.respawns = 0
        self._cap_warned = False
        self._procs = [None] * n
        self._closing = threading.Event()
        for i in range(n):
            self._spawn(i)
        self._monitor = threading.Thread(target=self._watch, daemon=True,
                                         name="workerpool-monitor")
        self._monitor.start()

    def _spawn(self, i):
        host, port = self.address
        if self.command:
            cmd = [str(a).replace("{host}", str(host))
                   .replace("{port}", str(port)) for a in self.command]
        else:
            cmd = [self.python, "-m", "veles_tpu.jobserver",
                   str(host), str(port), "--name", "pool-%d" % i]
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        self._procs[i] = subprocess.Popen(cmd, cwd=repo_root, env=self.env)

    def _watch(self):
        while not self._closing.is_set():
            for i, proc in enumerate(self._procs):
                if proc is None or proc.poll() is None:
                    continue
                if proc.returncode == 0 or self._closing.is_set():
                    continue
                if self.respawns >= self.max_respawns:
                    if not self._cap_warned:
                        self._cap_warned = True
                        print("workerpool: respawn budget (%d) spent; "
                              "worker %d stays down" % (self.max_respawns,
                                                        i),
                              file=sys.stderr)
                    continue
                self.respawns += 1
                # exponential backoff per respawn, reference-style
                time.sleep(self.backoff * 2 ** (self.respawns - 1))
                if not self._closing.is_set():
                    self._spawn(i)
            time.sleep(_SENTINEL_TIMEOUT)

    def alive(self):
        return sum(1 for p in self._procs
                   if p is not None and p.poll() is None)

    def close(self, timeout=5.0):
        self._closing.set()
        deadline = time.monotonic() + timeout
        for proc in self._procs:
            if proc is None:
                continue
            try:
                proc.wait(max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(1.0)
                except subprocess.TimeoutExpired:
                    proc.kill()


def parse_address(text, default_host="127.0.0.1"):
    """'host:port' | ':port' | 'port' -> (host, port)."""
    host, _, port = str(text).rpartition(":")
    return (host or default_host), int(port)


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m veles_tpu.jobserver",
        description="Trial worker: connect to a --listen'ing master and "
                    "serve CLI trials (reference slave role).")
    p.add_argument("host")
    p.add_argument("port", type=int)
    p.add_argument("--name", default=None)
    p.add_argument("--secret", default=None,
                   help="shared handshake secret (default: "
                        "$VELES_JOB_SECRET)")
    args = p.parse_args(argv)
    worker_loop(args.host, args.port, name=args.name, secret=args.secret)
    return 0


if __name__ == "__main__":
    sys.exit(main())
