"""Forge: a model package registry (server + client).

Re-creation of /root/reference/veles/forge/ (forge_server.py Tornado
upload/fetch/service endpoints with manifest.json per model;
forge_client.py ``veles forge fetch/upload``).  Models here are the
export packages (export.export_model zips) plus a manifest; the server
is the stdlib HTTP stack the other services use (the email-confirmation
workflow of the reference is internet-era scope this build drops).

Endpoints (reference-compatible shapes):
- ``GET /service?query=list``            → JSON list of manifests
- ``GET /service?query=details&name=N``  → one manifest
- ``GET /fetch?name=N[&version=V]``      → package bytes
- ``POST /upload?name=N&version=V``      → store package (+ metadata)

CLI: ``python -m veles_tpu.forge serve|upload|fetch|list ...``.
"""

import json
import os
import shutil
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class ForgeStore:
    """Directory-backed registry: <root>/<name>/<version>/package.zip +
    manifest.json; 'latest' resolves to the newest upload."""

    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @staticmethod
    def _safe(s):
        """Validate path components STRICTLY: a name that would change
        under sanitization (traversal, separators, leading dots) is
        rejected outright — silently rewriting '../evil' to 'evil'
        would store a manifest whose name disagrees with its directory
        and alias distinct client names onto one entry."""
        out = "".join(c for c in s if c.isalnum() or c in "._-")
        if not out or out != s or out.startswith("."):
            raise KeyError("invalid name/version: %r" % s)
        return out

    def _mdir(self, name, version):
        return os.path.join(self.directory, self._safe(name),
                            self._safe(version))

    def upload(self, name, version, package_path, metadata=None):
        d = self._mdir(name, version)
        os.makedirs(d, exist_ok=True)
        shutil.copy(package_path, os.path.join(d, "package.zip"))
        manifest = {"name": name, "version": version,
                    "uploaded": time.time(),
                    "size": os.path.getsize(package_path),
                    **(metadata or {})}
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)
        return manifest

    def resolve(self, name, version=None):
        base = os.path.join(self.directory, self._safe(name))
        if not os.path.isdir(base):
            raise KeyError("no such model: %s" % name)
        if version is None or version == "latest":
            versions = sorted(
                os.listdir(base),
                key=lambda v: os.path.getmtime(os.path.join(base, v)))
            if not versions:
                raise KeyError("model %s has no versions" % name)
            version = versions[-1]
        d = os.path.join(base, self._safe(version))
        if not os.path.isdir(d):
            raise KeyError("no such version: %s/%s" % (name, version))
        return d

    def manifest(self, name, version=None):
        with open(os.path.join(self.resolve(name, version),
                               "manifest.json")) as f:
            return json.load(f)

    def package_path(self, name, version=None):
        return os.path.join(self.resolve(name, version), "package.zip")

    def list(self):
        out = []
        for name in sorted(os.listdir(self.directory)):
            base = os.path.join(self.directory, name)
            if not os.path.isdir(base):
                continue
            for version in sorted(os.listdir(base)):
                mf = os.path.join(base, version, "manifest.json")
                if os.path.exists(mf):
                    try:
                        with open(mf) as f:
                            out.append(json.load(f))
                    except ValueError:
                        # one interrupted upload's truncated manifest
                        # must not hide every healthy package
                        out.append({"name": name, "version": version,
                                    "error": "corrupt manifest"})
        return out


class _Handler(BaseHTTPRequestHandler):
    store = None

    def log_message(self, *args):
        pass

    def _send_json(self, code, payload):
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _params(self):
        return {k: v[0] for k, v in urllib.parse.parse_qs(
            urllib.parse.urlparse(self.path).query).items()}

    def do_GET(self):
        route = urllib.parse.urlparse(self.path).path
        q = self._params()
        try:
            if route == "/service":
                if q.get("query") == "list":
                    self._send_json(200, self.store.list())
                elif q.get("query") == "details":
                    if "name" not in q:
                        self._send_json(400, {"error": "name required"})
                        return
                    self._send_json(200, self.store.manifest(
                        q["name"], q.get("version")))
                else:
                    self._send_json(400, {"error": "unknown query"})
            elif route == "/fetch":
                if "name" not in q:
                    self._send_json(400, {"error": "name required"})
                    return
                path = self.store.package_path(q["name"],
                                               q.get("version"))
                with open(path, "rb") as f:
                    data = f.read()
                self.send_response(200)
                self.send_header("Content-Type", "application/zip")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._send_json(404, {"error": "not found"})
        except KeyError as e:
            self._send_json(404, {"error": str(e)})

    def do_POST(self):
        route = urllib.parse.urlparse(self.path).path
        q = self._params()
        if route != "/upload" or "name" not in q or "version" not in q:
            self._send_json(400, {"error": "upload needs name & version"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(length)
            import tempfile
            fd, tmp = tempfile.mkstemp(suffix=".zip")
            try:
                os.write(fd, data)
                os.close(fd)
                metadata = {}
                if self.headers.get("X-Forge-Metadata"):
                    metadata = json.loads(
                        self.headers["X-Forge-Metadata"])
                manifest = self.store.upload(q["name"], q["version"],
                                             tmp, metadata)
                self._send_json(200, manifest)
            finally:
                os.unlink(tmp)
        except Exception as e:  # the client must get a JSON answer
            self._send_json(400, {"error": str(e)})


class ForgeServer:
    def __init__(self, directory, port=0, host="127.0.0.1"):
        self.store = ForgeStore(directory)
        handler = type("Handler", (_Handler,), {"store": self.store})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="veles-tpu-forge")
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


# -- client ------------------------------------------------------------------
def upload(base_url, name, version, package_path, metadata=None):
    with open(package_path, "rb") as f:
        data = f.read()
    req = urllib.request.Request(
        "%s/upload?%s" % (base_url, urllib.parse.urlencode(
            {"name": name, "version": version})), data,
        {"Content-Type": "application/zip",
         "X-Forge-Metadata": json.dumps(metadata or {})})
    return json.loads(urllib.request.urlopen(req).read())


def fetch(base_url, name, dest, version=None):
    q = {"name": name}
    if version:
        q["version"] = version
    data = urllib.request.urlopen(
        "%s/fetch?%s" % (base_url, urllib.parse.urlencode(q))).read()
    with open(dest, "wb") as f:
        f.write(data)
    return dest


def list_models(base_url):
    return json.loads(urllib.request.urlopen(
        "%s/service?query=list" % base_url).read())


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(prog="veles_tpu.forge")
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("serve")
    s.add_argument("directory")
    s.add_argument("--port", type=int, default=8180)
    s.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 to serve off-box)")
    u = sub.add_parser("upload")
    u.add_argument("url")
    u.add_argument("name")
    u.add_argument("version")
    u.add_argument("package")
    f = sub.add_parser("fetch")
    f.add_argument("url")
    f.add_argument("name")
    f.add_argument("dest")
    f.add_argument("--version", default=None)
    ls = sub.add_parser("list")
    ls.add_argument("url")
    args = p.parse_args(argv)
    if args.cmd == "serve":
        server = ForgeServer(args.directory, args.port, args.host)
        print("forge serving %s on port %d" % (args.directory,
                                               server.port))
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            server.stop()
    elif args.cmd == "upload":
        print(json.dumps(upload(args.url, args.name, args.version,
                                args.package)))
    elif args.cmd == "fetch":
        print(fetch(args.url, args.name, args.dest, args.version))
    else:
        print(json.dumps(list_models(args.url), indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
