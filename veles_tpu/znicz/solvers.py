"""Gradient-descent solvers as pure update rules.

Reference: the Znicz GradientDescentBase solver knobs (SURVEY.md §2.9;
docs manualrst_veles_algorithms.rst:150-165 — momentum, AdaGrad, AdaDelta,
L1/L2 blending, ``factor_ortho``).  Each solver is a pair of pure functions
so the fused jitted train step can thread solver state through
``lax``-friendly pytrees:

- ``init(param) -> state``  (a pytree of arrays, may be empty tuple)
- ``update(grad, param, state, lr) -> (delta, new_state)`` where the caller
  applies ``param + delta``.

``xp`` selects the array namespace (jax.numpy on device, numpy for the
parity twin) so the exact same arithmetic runs on both paths.
"""

import numpy


def regularized_grad(grad, param, weights_decay, l1_vs_l2, xp=numpy,
                     factor_ortho=0.0):
    """Add the L1/L2-blended decay term (and optional soft-orthogonality
    push) to a raw gradient.

    reg = decay * ((1 - l1_vs_l2) * w + l1_vs_l2 * sign(w) / 2)
    following the Znicz blending convention; ortho term is the gradient of
    ``factor_ortho/4 * ||W^T W - I||^2`` for 2-D weights.
    """
    g = grad
    if weights_decay:
        g = g + weights_decay * ((1.0 - l1_vs_l2) * param +
                                 0.5 * l1_vs_l2 * xp.sign(param))
    if factor_ortho and param.ndim == 2:
        wtw = param.T @ param
        eye = xp.eye(wtw.shape[0], dtype=param.dtype)
        g = g + factor_ortho * (param @ (wtw - eye))
    return g


class Solver:
    name = None

    def __init__(self, **hyper):
        self.hyper = hyper

    def init(self, param, xp=numpy):
        return ()

    def update(self, grad, param, state, lr, xp=numpy):
        raise NotImplementedError


class SGD(Solver):
    name = "sgd"

    def update(self, grad, param, state, lr, xp=numpy):
        return -lr * grad, state


class Momentum(Solver):
    """Classic heavy-ball: v = mu*v - lr*g; w += v (Znicz
    ``gradient_moment``)."""

    name = "momentum"

    def init(self, param, xp=numpy):
        return (xp.zeros_like(param),)

    def update(self, grad, param, state, lr, xp=numpy):
        (v,) = state
        v = self.hyper.get("momentum", 0.9) * v - lr * grad
        return v, (v,)


class AdaGrad(Solver):
    name = "adagrad"

    def init(self, param, xp=numpy):
        return (xp.zeros_like(param),)

    def update(self, grad, param, state, lr, xp=numpy):
        (accum,) = state
        eps = self.hyper.get("epsilon", 1e-8)
        accum = accum + grad * grad
        return -lr * grad / (xp.sqrt(accum) + eps), (accum,)


class AdaDelta(Solver):
    name = "adadelta"

    def init(self, param, xp=numpy):
        return (xp.zeros_like(param), xp.zeros_like(param))

    def update(self, grad, param, state, lr, xp=numpy):
        accum_g, accum_dx = state
        rho = self.hyper.get("rho", 0.95)
        eps = self.hyper.get("epsilon", 1e-6)
        accum_g = rho * accum_g + (1 - rho) * grad * grad
        dx = -xp.sqrt(accum_dx + eps) / xp.sqrt(accum_g + eps) * grad
        accum_dx = rho * accum_dx + (1 - rho) * dx * dx
        return lr * dx, (accum_g, accum_dx)


class RProp(Solver):
    """Resilient propagation (RPropAll2All parity): per-weight step sizes
    grown/shrunk by gradient sign agreement."""

    name = "rprop"

    def init(self, param, xp=numpy):
        return (xp.full_like(param, self.hyper.get("step0", 1e-3)),
                xp.zeros_like(param))

    def update(self, grad, param, state, lr, xp=numpy):
        step, prev_g = state
        inc = self.hyper.get("eta_plus", 1.2)
        dec = self.hyper.get("eta_minus", 0.5)
        agree = grad * prev_g
        step = xp.where(agree > 0,
                        xp.minimum(step * inc,
                                   self.hyper.get("step_max", 50.0)),
                        xp.where(agree < 0,
                                 xp.maximum(step * dec,
                                            self.hyper.get("step_min",
                                                           1e-9)),
                                 step))
        return -xp.sign(grad) * step, (step, grad)


_SOLVERS = {c.name: c for c in (SGD, Momentum, AdaGrad, AdaDelta, RProp)}


def factory(name, **hyper):
    try:
        return _SOLVERS[name](**hyper)
    except KeyError:
        raise ValueError("unknown solver %r (have: %s)" %
                         (name, sorted(_SOLVERS)))
