"""Ragged paged decode attention as a Pallas TPU kernel.

The decode-serving counterpart of :mod:`.flash_attention` (PAPERS.md
"Ragged Paged Attention", arXiv 2604.15464): at decode time every
sequence contributes ONE query token, but its K/V history lives in
fixed-size blocks scattered across a preallocated device pool — the
page table (``[B, max_blocks]`` physical block ids) and the per-sequence
lengths are the only things that change shape-free from step to step,
so one compiled kernel serves ANY mix of sequence lengths with zero
recompilation.  That is what makes token-level continuous batching
(serving/decode.py) possible: admitting or retiring a sequence edits
the page table, never the executable.

Kernel structure — two sweeps over the inner block grid, page-table
indirection via scalar prefetch (the index map reads the prefetched
page table to pick which PHYSICAL pool block the next DMA fetches, the
canonical TPU paged-attention gather):

- sweep 1 streams the sequence's K blocks, scoring each against the
  query and materializing the per-sequence score row in VMEM scratch
  (decode scores are [1, T] — tiny, unlike the [T, T] training case);
- the boundary step normalizes: one max, one exp, one sum — a DENSE
  softmax over the scratch row, not an online rescale;
- sweep 2 streams the V blocks, accumulating the probability-weighted
  sum block by block.

K and V each cross HBM exactly once (same DMA bill as a fused single
sweep), and because the softmax is dense the kernel is **bitwise equal
to the dense reference** — no online-softmax rescale drift — which is
what the tier-1 parity tests assert (interpret mode on CPU, compiled on
TPU).  Blocks past a sequence's length are skipped entirely: compute
AND DMA stay O(length), so a ragged batch costs its true token count,
not ``B * max_context``.

Padding rows (``length == 0``) return zeros; padding page-table entries
must point at physical block 0, which the serving pool reserves as the
trash block (never allocated to a live sequence).

Quantized pools (ISSUE 18): the same entry points accept int8 K/V
pools plus per-(block, head) f32 scale arrays (``k_scales``/``v_scales``,
``[num_blocks]`` — one symmetric scale per PHYSICAL pool block).  The
scales ride as two extra scalar-prefetch operands and each K/V tile is
dequantized on the VMEM row right after its DMA (``int8 -> f32 *
scale[pid]``), so HBM traffic on the hot loop is the int8 bytes; the
dense-softmax structure, trash-block handling and page-table
indirection are untouched, and the quantized dense reference stages the
same dequant elementwise so parity stays bitwise.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["DEFAULT_BLOCK_SIZE", "paged_attention",
           "paged_attention_reference", "paged_prefill_attention",
           "paged_prefill_attention_reference", "paged_verify_attention",
           "paged_verify_attention_reference", "required_blocks",
           "quantize_pool", "dequantize_pool"]

_NEG_INF = float("-inf")

#: hand-picked KV page size (tokens per pool block).  The kernel reads
#: the actual size off the pool shape — this is the default the decode
#: scheduler builds pools with when nothing is pinned, and the
#: ``paged_attention`` autotune site's baseline candidate.
DEFAULT_BLOCK_SIZE = 8


def _interpret():
    return jax.default_backend() != "tpu"


def required_blocks(length, block_size):
    """Pool blocks a sequence of ``length`` tokens occupies."""
    return -(-int(length) // int(block_size))


def quantize_pool(pool):
    """Symmetric per-(block, head) int8 quantization of a
    ``[N, block_size, H, D]`` pool.

    Returns ``(q, scales)`` — ``q`` int8 with the pool's shape,
    ``scales`` f32 ``[N, H]`` with
    ``scale[i, h] = max|pool[i, :, h]| / 127`` (1.0 for an all-zero
    slice, so dequant never divides by zero).  One scale per head, not
    per block, because head projections differ in magnitude — sharing a
    scale across heads costs ~2x logit RMSE for zero bytes saved (the
    scale array is noise next to the pool either way).  The quantizer
    is deterministic (round-half-even), which is what lets prefix-chain
    keys commit to the quantized bytes: same content in, same int8
    bytes out.
    """
    if pool.ndim != 4:
        raise ValueError("expected a [N, block_size, H, D] pool, got "
                         "shape %r" % (pool.shape,))
    f = pool.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=(1, 3))      # [N, H]
    scales = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(f / scales[:, None, :, None]), -127, 127)
    return q.astype(jnp.int8), scales.astype(jnp.float32)


def dequantize_pool(q, scales):
    """Inverse of :func:`quantize_pool`: ``int8 * scale`` per
    (block, head)."""
    return (q.astype(jnp.float32)
            * scales.astype(jnp.float32)[:, None, :, None])


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   s_scr, m_scr, l_scr, acc_scr, *, block_size,
                   n_blocks, scale):
    from jax.experimental import pallas as pl

    b, j = pl.program_id(0), pl.program_id(2)
    length = len_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        s_scr[...] = jnp.full_like(s_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # -- sweep 1 (j < n_blocks): score K blocks into the scratch row ---------
    @pl.when(jnp.logical_and(j < n_blocks, j * block_size < length))
    def _score():
        q = q_ref[0, 0].astype(jnp.float32) * scale       # [D]
        kb = k_ref[0, :, 0].astype(jnp.float32)           # [bs, D]
        s = jnp.sum(q[None, :] * kb, axis=-1)             # [bs]
        pos = j * block_size + lax.broadcasted_iota(
            jnp.int32, (block_size, 1), 0)[:, 0]
        s = jnp.where(pos < length, s, _NEG_INF)
        s_scr[j] = s
        m_scr[0, 0] = jnp.maximum(m_scr[0, 0], jnp.max(s))

    # -- boundary: dense softmax over the whole scratch row ------------------
    @pl.when(j == n_blocks)
    def _normalize():
        m = m_scr[0, 0]
        safe_m = jnp.where(jnp.isneginf(m), 0.0, m)
        p = jnp.where(jnp.isneginf(s_scr[...]), 0.0,
                      jnp.exp(s_scr[...] - safe_m))
        s_scr[...] = p
        l_scr[0, 0] = jnp.sum(p)

    # -- sweep 2 (j >= n_blocks): weighted V accumulation --------------------
    jv = j - n_blocks

    @pl.when(jnp.logical_and(j >= n_blocks, jv * block_size < length))
    def _accumulate():
        vb = v_ref[0, :, 0].astype(jnp.float32)           # [bs, D]
        p = s_scr[jv]                                     # [bs]
        acc_scr[...] = acc_scr[...] + jnp.sum(
            p[:, None] * vb, axis=0, keepdims=True)

    @pl.when(j == 2 * n_blocks - 1)
    def _finish():
        l = l_scr[0, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[0] / safe_l).astype(o_ref.dtype)


def _decode_kernel_quant(pt_ref, len_ref, ks_ref, vs_ref, q_ref, k_ref,
                         v_ref, o_ref, s_scr, m_scr, l_scr, acc_scr, *,
                         block_size, n_blocks, scale):
    """The decode kernel over int8 pools: identical sweep/softmax
    structure, but each K/V tile is dequantized on the VMEM row right
    after its DMA with the per-(block, head) scale read off the two
    extra scalar-prefetch operands (``ks_ref``/``vs_ref``, indexed by
    the PHYSICAL block id the page table routed this grid step to and
    this grid step's head)."""
    from jax.experimental import pallas as pl

    b, hh, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    length = len_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        s_scr[...] = jnp.full_like(s_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # -- sweep 1: dequantize the K tile, score into the scratch row ----------
    @pl.when(jnp.logical_and(j < n_blocks, j * block_size < length))
    def _score():
        q = q_ref[0, 0].astype(jnp.float32) * scale       # [D]
        kb = k_ref[0, :, 0].astype(jnp.float32) \
            * ks_ref[pt_ref[b, j], hh]                    # [bs, D]
        s = jnp.sum(q[None, :] * kb, axis=-1)             # [bs]
        pos = j * block_size + lax.broadcasted_iota(
            jnp.int32, (block_size, 1), 0)[:, 0]
        s = jnp.where(pos < length, s, _NEG_INF)
        s_scr[j] = s
        m_scr[0, 0] = jnp.maximum(m_scr[0, 0], jnp.max(s))

    # -- boundary: dense softmax over the whole scratch row ------------------
    @pl.when(j == n_blocks)
    def _normalize():
        m = m_scr[0, 0]
        safe_m = jnp.where(jnp.isneginf(m), 0.0, m)
        p = jnp.where(jnp.isneginf(s_scr[...]), 0.0,
                      jnp.exp(s_scr[...] - safe_m))
        s_scr[...] = p
        l_scr[0, 0] = jnp.sum(p)

    # -- sweep 2: dequantize the V tile, weighted accumulation ---------------
    jv = j - n_blocks

    @pl.when(jnp.logical_and(j >= n_blocks, jv * block_size < length))
    def _accumulate():
        vb = v_ref[0, :, 0].astype(jnp.float32) \
            * vs_ref[pt_ref[b, jv], hh]                   # [bs, D]
        p = s_scr[jv]                                     # [bs]
        acc_scr[...] = acc_scr[...] + jnp.sum(
            p[:, None] * vb, axis=0, keepdims=True)

    @pl.when(j == 2 * n_blocks - 1)
    def _finish():
        l = l_scr[0, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[0] / safe_l).astype(o_ref.dtype)


def _check_quant_args(k_pool, v_pool, k_scales, v_scales):
    """-> True when the pools are quantized (int8 + scales), False for
    the f32 path; raises on half-specified or mismatched operands."""
    quantized = k_pool.dtype == jnp.int8
    if quantized != (v_pool.dtype == jnp.int8):
        raise ValueError("k_pool/v_pool dtypes differ: %r vs %r"
                         % (k_pool.dtype, v_pool.dtype))
    if not quantized:
        if k_scales is not None or v_scales is not None:
            raise ValueError(
                "k_scales/v_scales are only valid with int8 pools "
                "(got %r pools)" % str(k_pool.dtype))
        return False
    if k_scales is None or v_scales is None:
        raise ValueError("int8 pools require k_scales and v_scales")
    n_pool, heads = k_pool.shape[0], k_pool.shape[2]
    for name, s in (("k_scales", k_scales), ("v_scales", v_scales)):
        if s.shape != (n_pool, heads):
            raise ValueError(
                "%s shape %r != (num_blocks, heads) == (%d, %d)"
                % (name, s.shape, n_pool, heads))
    return True


def paged_attention(q, k_pool, v_pool, page_table, lengths, scale=None,
                    k_scales=None, v_scales=None):
    """Ragged paged decode attention.

    ``q``: [B, H, D] — one query token per sequence;
    ``k_pool``/``v_pool``: [num_blocks, block_size, H, D] — the shared
    physical block pools;
    ``page_table``: int32 [B, max_blocks] — physical block id of each
    sequence's logical block, padded with 0 (the reserved trash block);
    ``lengths``: int32 [B] — valid tokens per sequence (0 = padding
    row, returns zeros);
    ``k_scales``/``v_scales``: f32 [num_blocks, H] — required iff the
    pools are int8 (per-(block, head) symmetric scales; the kernel
    dequantizes each tile in VMEM right after its DMA).

    Returns [B, H, D].  Compiled once per (B, H, D, block_size,
    max_blocks) — sequence lengths and table contents are runtime data.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    n_pool, bs, hp, dp = k_pool.shape
    if v_pool.shape != k_pool.shape:
        raise ValueError("k_pool and v_pool shapes differ: %r vs %r"
                         % (k_pool.shape, v_pool.shape))
    if (hp, dp) != (h, d):
        raise ValueError("pool head layout %r does not match q %r"
                         % ((hp, dp), (h, d)))
    quantized = _check_quant_args(k_pool, v_pool, k_scales, v_scales)
    nb = page_table.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scratch_shapes = [
        pltpu.VMEM((nb, bs), jnp.float32),    # score / prob row
        pltpu.VMEM((1, 1), jnp.float32),      # running max
        pltpu.VMEM((1, 1), jnp.float32),      # softmax denominator
        pltpu.VMEM((1, d), jnp.float32),      # output accumulator
    ]
    if quantized:
        # the f32 structure with two extra scalar-prefetch operands
        # (per-block K/V scales) and in-VMEM dequant after each DMA
        kernel = functools.partial(_decode_kernel_quant, block_size=bs,
                                   n_blocks=nb, scale=float(scale))
        k_index = lambda b_, h_, j, pt, ln, ks, vs: (  # noqa: E731
            pt[b_, jnp.minimum(j, nb - 1)], 0, h_, 0)
        v_index = lambda b_, h_, j, pt, ln, ks, vs: (  # noqa: E731
            pt[b_, jnp.clip(j - nb, 0, nb - 1)], 0, h_, 0)
        q_index = lambda b_, h_, j, pt, ln, ks, vs: (  # noqa: E731
            b_, h_, 0)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(b, h, 2 * nb),
            in_specs=[
                pl.BlockSpec((1, 1, d), q_index),
                pl.BlockSpec((1, bs, 1, d), k_index),
                pl.BlockSpec((1, bs, 1, d), v_index),
            ],
            out_specs=pl.BlockSpec((1, 1, d), q_index),
            scratch_shapes=scratch_shapes,
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
            interpret=_interpret(),
        )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
          k_scales.astype(jnp.float32), v_scales.astype(jnp.float32),
          q, k_pool, v_pool)
    kernel = functools.partial(_decode_kernel, block_size=bs,
                               n_blocks=nb, scale=float(scale))
    # index maps see the prefetched page table: sweep 1 follows it for
    # K, sweep 2 for V; the off-sweep operand pins to an already-mapped
    # block (clipped id) so no DMA reads out of range
    k_index = lambda b_, h_, j, pt, ln: (  # noqa: E731
        pt[b_, jnp.minimum(j, nb - 1)], 0, h_, 0)
    v_index = lambda b_, h_, j, pt, ln: (  # noqa: E731
        pt[b_, jnp.clip(j - nb, 0, nb - 1)], 0, h_, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, 2 * nb),
        in_specs=[
            pl.BlockSpec((1, 1, d),
                         lambda b_, h_, j, pt, ln: (b_, h_, 0)),
            pl.BlockSpec((1, bs, 1, d), k_index),
            pl.BlockSpec((1, bs, 1, d), v_index),
        ],
        out_specs=pl.BlockSpec((1, 1, d),
                               lambda b_, h_, j, pt, ln: (b_, h_, 0)),
        scratch_shapes=scratch_shapes,
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pool, v_pool)


def _prefill_table_lengths(block_row, start, length, chunk):
    """One sequence's chunk as a ragged "batch": every chunk token
    shares the sequence's block row, and causal masking IS the ragged
    length masking — query at absolute position ``p`` attends to
    ``p + 1`` cached tokens.  Positions past ``length`` are padding
    rows (length 0 → zeros, the kernel's existing convention)."""
    table = jnp.broadcast_to(block_row.astype(jnp.int32)[None, :],
                             (chunk, block_row.shape[0]))
    pos = start + jnp.arange(chunk, dtype=jnp.int32)
    lens = jnp.where(pos < length, pos + 1, 0).astype(jnp.int32)
    return table, lens


def paged_prefill_attention(q, k_pool, v_pool, block_row, start, length,
                            scale=None, k_scales=None, v_scales=None):
    """Chunked-prefill attention over a partially-resident page table.

    ``q``: [C, H, D] — one fixed-size chunk of prompt queries for ONE
    sequence, absolute positions ``start .. start + C - 1``;
    ``block_row``: int32 [max_blocks] — the sequence's page-table row
    (resident prefix blocks + freshly written chunk blocks, 0-padded);
    ``start``/``length``: scalars — chunk origin and total prompt
    length (positions past ``length`` are padding and return zeros).

    No new kernel: the chunk is dispatched through the decode kernel
    with the chunk axis as the batch axis and per-query causal lengths
    ``start + i + 1`` — which is exactly why ragged paged attention
    (arXiv 2604.15464) serves mixed prefill/decode from ONE executable.
    The resident prefix is read straight from the pool, so a prompt
    whose first blocks are already cached prefills only its suffix.
    """
    table, lens = _prefill_table_lengths(block_row, start, length,
                                         q.shape[0])
    return paged_attention(q, k_pool, v_pool, table, lens, scale=scale,
                           k_scales=k_scales, v_scales=v_scales)


def paged_prefill_attention_reference(q, k_pool, v_pool, block_row,
                                      start, length, scale=None,
                                      k_scales=None, v_scales=None):
    """Dense oracle for :func:`paged_prefill_attention` (same staging
    as :func:`paged_attention_reference`, so parity stays bitwise)."""
    table, lens = _prefill_table_lengths(block_row, start, length,
                                         q.shape[0])
    return paged_attention_reference(q, k_pool, v_pool, table, lens,
                                     scale=scale, k_scales=k_scales,
                                     v_scales=v_scales)


def _verify_table_lengths(page_table, lengths, span):
    """A speculative verify pass as a ragged "batch": the ``span`` query
    tokens of every sequence (the fed token plus its draft tail) each
    share the sequence's block row, and the per-query causal lengths are
    ``length + i + 1`` — query ``i`` attends to the history plus the
    ``i + 1`` tokens fed so far, never to the drafts after it.  Padding
    rows (``length == 0``) stay padding at every span position."""
    b, nb = page_table.shape
    table = jnp.repeat(page_table.astype(jnp.int32), span, axis=0)
    pos = jnp.arange(span, dtype=jnp.int32)[None, :]
    lens = jnp.where(lengths[:, None] > 0,
                     lengths[:, None].astype(jnp.int32) + pos + 1, 0)
    return table, lens.reshape(b * span)


def paged_verify_attention(q, k_pool, v_pool, page_table, lengths,
                           scale=None, k_scales=None, v_scales=None):
    """Multi-token (draft-and-verify) ragged paged attention.

    ``q``: [B, S, H, D] — ``S`` query tokens per sequence (speculative
    decoding's fed token + its ``S - 1`` draft tokens), whose K/V have
    already been written at positions ``length .. length + S - 1``;
    ``page_table``/``lengths``: as :func:`paged_attention` — ``lengths``
    counts the cached tokens BEFORE this verify span.

    Returns [B, S, H, D].  No new kernel (the same move as
    :func:`paged_prefill_attention`): the span is flattened into the
    batch axis of the decode kernel with per-query causal lengths
    ``length + i + 1``, so one warm executable verifies any mix of
    sequence lengths — the ragged batching of arXiv 2604.15464 serving
    the verify pass natively.  Rejected draft positions are "rolled
    back" simply by never advancing ``lengths`` past them: the kernel's
    length masking makes their K/V writes invisible until overwritten.
    """
    b, s, h, d = q.shape
    table, lens = _verify_table_lengths(page_table, lengths, s)
    o = paged_attention(q.reshape(b * s, h, d), k_pool, v_pool,
                        table, lens, scale=scale, k_scales=k_scales,
                        v_scales=v_scales)
    return o.reshape(b, s, h, d)


def paged_verify_attention_reference(q, k_pool, v_pool, page_table,
                                     lengths, scale=None, k_scales=None,
                                     v_scales=None):
    """Dense oracle for :func:`paged_verify_attention` (same staging as
    :func:`paged_attention_reference`, so parity stays bitwise)."""
    b, s, h, d = q.shape
    table, lens = _verify_table_lengths(page_table, lengths, s)
    o = paged_attention_reference(q.reshape(b * s, h, d), k_pool,
                                  v_pool, table, lens, scale=scale,
                                  k_scales=k_scales, v_scales=v_scales)
    return o.reshape(b, s, h, d)


def paged_attention_reference(q, k_pool, v_pool, page_table, lengths,
                              scale=None, k_scales=None, v_scales=None):
    """Pure-jnp dense oracle: gather every sequence's blocks into a
    dense [B, T_max, H, D] view, materialize the full score row, dense
    softmax, weighted sum.

    The reductions are staged the way the kernel streams (per-block
    partial sums, then a sequential accumulation over the block axis)
    so the parity tests can assert BITWISE equality, not just
    tolerance — float addition is non-associative, and XLA's fused
    reduce over the block axis associates differently than the
    kernel's block-sequential accumulator.
    """
    b, h, d = q.shape
    n_pool, bs, hp, dp = k_pool.shape
    quantized = _check_quant_args(k_pool, v_pool, k_scales, v_scales)
    nb = page_table.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    k = k_pool[page_table].astype(jnp.float32)  # [B, nb, bs, H, D]
    v = v_pool[page_table].astype(jnp.float32)
    if quantized:
        # dequantize elementwise with the gathered per-block scales —
        # the same ``int8 -> f32 * scale`` product the kernel computes
        # on the VMEM tile, so parity stays bitwise
        k = k * k_scales.astype(jnp.float32)[page_table][
            :, :, None, :, None]
        v = v * v_scales.astype(jnp.float32)[page_table][
            :, :, None, :, None]
    qf = q.astype(jnp.float32) * scale
    s = jnp.sum(k * qf[:, None, None], axis=-1)
    s = jnp.moveaxis(s, 3, 1)                   # [B, H, nb, bs]
    pos = (jnp.arange(nb)[:, None] * bs +
           jnp.arange(bs)[None, :])             # [nb, bs]
    valid = pos[None, None] < lengths[:, None, None, None]
    s = jnp.where(valid, s, _NEG_INF)
    m = jnp.max(s, axis=(2, 3), keepdims=True)
    safe_m = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.where(jnp.isneginf(s), 0.0, jnp.exp(s - safe_m))
    l = jnp.sum(p, axis=(2, 3))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    vm = jnp.moveaxis(v.astype(jnp.float32), 3, 1)   # [B, H, nb, bs, D]
    pv = jnp.sum(p[..., None] * vm, axis=3)          # [B, H, nb, D]
    o = pv[:, :, 0]
    for j in range(1, nb):                      # block-sequential, like
        o = o + pv[:, :, j]                     # the kernel's sweep 2
    return (o / safe_l[..., None]).astype(q.dtype)
