"""ImageSaver: dump misclassified samples to disk per epoch.

Re-creation of the Znicz image_saver unit (SURVEY §2.9): after each
validation pass, write the wrongly-classified images into
``directory/<epoch>/<true>_as_<predicted>_<i>.png`` for eyeballing what
the model confuses.  Consumes the fused step's (or evaluator's) output
probabilities plus the loader's minibatch.
"""

import os

import numpy

from ..units import Unit
from .. import loader as loader_mod


class ImageSaver(Unit):
    MAPPING = "image_saver"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.directory = kwargs.get("directory", "image_saver")
        self.limit = int(kwargs.get("limit", 32))      # per epoch
        self.sample_shape = kwargs.get("sample_shape")  # e.g. (28, 28)
        self.minibatch_data = None   # linked from loader
        self.minibatch_labels = None
        self.minibatch_size = None
        self.minibatch_class = None
        self.epoch_number = None
        self.output = None           # linked from trainer/evaluator
        self.saved = 0
        self._epoch_saved = 0
        self._seen_epoch = -1

    def link_all(self, trainer, loader):
        self.loader = loader
        self.link_attrs(trainer, "output")
        self.link_attrs(loader, "minibatch_data", "minibatch_labels",
                        "minibatch_size", "minibatch_class",
                        "epoch_number")
        return self

    def run(self):
        if self.minibatch_class != loader_mod.VALID:
            return
        epoch = int(self.epoch_number)
        if epoch != self._seen_epoch:
            self._seen_epoch = epoch
            self._epoch_saved = 0
        if self._epoch_saved >= self.limit:
            return  # before materialize: no host gather once full
        # deferred-gather loaders never fill the host Arrays on their own
        self.loader.materialize_minibatch()
        size = int(self.minibatch_size)
        out = numpy.asarray(self.output.map_read()
                            if hasattr(self.output, "map_read")
                            else self.output)[:size]
        labels = numpy.asarray(self.minibatch_labels.map_read()[:size])
        data = numpy.asarray(self.minibatch_data.map_read()[:size])
        pred = out.argmax(axis=-1)
        wrong = numpy.nonzero(pred != labels)[0]
        if not len(wrong):
            return
        epoch_dir = os.path.join(self.directory, "epoch_%d" % epoch)
        os.makedirs(epoch_dir, exist_ok=True)
        from PIL import Image
        for i in wrong:
            if self._epoch_saved >= self.limit:
                break
            img = data[i]
            if self.sample_shape is not None:
                img = img.reshape(self.sample_shape)
            lo, hi = img.min(), img.max()
            img8 = ((img - lo) / (hi - lo + 1e-12) * 255).astype("uint8")
            if img8.ndim == 3 and img8.shape[-1] == 1:
                img8 = img8[..., 0]
            Image.fromarray(img8).save(os.path.join(
                epoch_dir, "%s_as_%s_%d.png" %
                (labels[i], pred[i], self._epoch_saved)))
            self._epoch_saved += 1
            self.saved += 1
