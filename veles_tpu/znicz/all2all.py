"""Fully-connected (all-to-all) forward units.

Re-creation of ``veles.znicz.all2all`` (absent submodule; inventory per
SURVEY.md §2.9 / docs manualrst_veles_workflow_parameters.rst:469-504):
All2All, All2AllTanh, All2AllSigmoid, All2AllRELU (softplus),
All2AllStrictRELU, All2AllSoftmax, ResizableAll2All.

The matmul is the MXU's native op: ``x @ W + b`` via jnp with weights in
the natural (in, out) layout; XLA fuses the activation into the matmul
epilogue.  ``y = act(flatten(x) @ W + b)``.
"""

import numpy

from ..memory import Array
from .nn_units import ForwardBase
from . import activations


class All2All(ForwardBase):
    """Linear fully-connected layer."""

    MAPPING = "all2all"
    ACTIVATION = "linear"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        shape = kwargs["output_sample_shape"]
        if isinstance(shape, int):
            shape = (shape,)
        self.output_sample_shape = tuple(shape)
        self.activation = activations.get(self.ACTIVATION)
        # opt-in compensated-summation GEMM (the reference's
        # PRECISION_LEVEL 1/2, znicz/gemm.py); 0 = XLA matmul, whose
        # pass-count already follows Device precision_level
        from ..config import root
        self.precise_gemm = int(kwargs.get(
            "precise_gemm", root.common.engine.get("precise_gemm", 0)))

    @property
    def neurons_number(self):
        return int(numpy.prod(self.output_sample_shape))

    def init_params(self):
        n_input = int(numpy.prod(self.input_shape[1:]))
        self.fill_array(self.weights, (n_input, self.neurons_number),
                        self.weights_stddev, self.weights_filling)
        if self.include_bias:
            self.fill_array(self.bias, (self.neurons_number,),
                            self.bias_stddev, self.bias_filling)

    def output_shape_for(self, input_shape):
        return (input_shape[0],) + self.output_sample_shape

    def apply(self, params, x):
        import jax.numpy as jnp
        x = x.reshape(x.shape[0], -1)
        if self.precise_gemm:
            from .gemm import precise_matmul
            y = precise_matmul(x, params["weights"], self.precise_gemm)
        else:
            y = x @ params["weights"]
        if "bias" in params:
            y = y + params["bias"]
        y = self.activation.fwd_jnp(y)
        if len(self.output_sample_shape) > 1:
            y = y.reshape((x.shape[0],) + self.output_sample_shape)
        return y

    def apply_numpy(self, params, x):
        x = x.reshape(x.shape[0], -1)
        y = x @ params["weights"]
        if "bias" in params:
            y = y + params["bias"]
        y = self.activation.fwd_np(y)
        if len(self.output_sample_shape) > 1:
            y = y.reshape((x.shape[0],) + self.output_sample_shape)
        return y


    def export_params(self):
        return {"neurons": int(self.neurons_number),
                "include_bias": bool(self.include_bias),
                "output_sample_shape": [
                    int(d) for d in self.output_sample_shape]}


class All2AllTanh(All2All):
    """y = 1.7159 * tanh(0.6666 * (xW + b))."""
    MAPPING = "all2all_tanh"
    ACTIVATION = "tanh"


class All2AllSigmoid(All2All):
    MAPPING = "all2all_sigmoid"
    ACTIVATION = "sigmoid"


class All2AllRELU(All2All):
    """Znicz "RELU": y = log(1 + exp(xW + b)) — softplus."""
    MAPPING = "all2all_relu"
    ACTIVATION = "relu"


class All2AllStrictRELU(All2All):
    MAPPING = "all2all_str"
    ACTIVATION = "strict_relu"


class All2AllSoftmax(All2All):
    """Softmax output layer; also exports ``max_idx`` (argmax per sample)
    the evaluator consumes (reference All2AllSoftmax contract)."""

    MAPPING = "softmax"
    ACTIVATION = "linear"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.max_idx = Array()

    def apply(self, params, x):
        import jax
        import jax.numpy as jnp
        x = x.reshape(x.shape[0], -1)
        logits = x @ params["weights"]
        if "bias" in params:
            logits = logits + params["bias"]
        return jax.nn.softmax(logits, axis=-1)

    def apply_numpy(self, params, x):
        x = x.reshape(x.shape[0], -1)
        logits = x @ params["weights"]
        if "bias" in params:
            logits = logits + params["bias"]
        logits = logits - logits.max(axis=-1, keepdims=True)
        e = numpy.exp(logits)
        return e / e.sum(axis=-1, keepdims=True)

    def apply_logits(self, params, x):
        """Pre-softmax logits — the fused trainer uses these with a
        numerically-stable fused log-softmax cross-entropy."""
        x = x.reshape(x.shape[0], -1)
        y = x @ params["weights"]
        if "bias" in params:
            y = y + params["bias"]
        return y

    def tpu_run(self):
        super().tpu_run()
        self._fill_max_idx()

    def numpy_run(self):
        super().numpy_run()
        self._fill_max_idx()

    def _fill_max_idx(self):
        self.max_idx.mem = numpy.argmax(
            self.output.map_read(), axis=-1).astype(numpy.int32)

    def make_trace(self):
        """Softmax head face: the generic forward face plus the
        ``max_idx`` side output graph-mode computes host-side (same
        first-max tie rule, so traced == interpreted bit-for-bit)."""
        from ..graphcomp.faces import (NoFace, TraceFace,
                                       forward_params_leaf)
        if not self._initialized:
            return NoFace("unit not initialized")
        if getattr(self, "_backend_run_", None) != self.tpu_run:
            return NoFace("numpy backend (no jitted path)")

        def fn(state_in, inputs, statics):
            import jax.numpy as jnp
            out = self.apply(state_in["params"], inputs["input"])
            return {}, {"output": out,
                        "max_idx": jnp.argmax(out, axis=-1).astype(
                            jnp.int32)}
        return TraceFace(self, fn, inputs=("input",),
                         outputs=("output", "max_idx"),
                         state=(forward_params_leaf(self),),
                         sync_attrs=("weights", "bias"))


class ResizableAll2All(All2All):
    """All2All whose output width can grow/shrink mid-training, preserving
    learned weights (reference resizable_all2all.ResizableAll2All)."""

    MAPPING = "all2all_resizable"

    def resize(self, new_neurons):
        old_w = self.weights.map_read()
        old_b = self.bias.map_read() if self.include_bias else None
        old_n = self.neurons_number
        self.output_sample_shape = (int(new_neurons),)
        n_input = old_w.shape[0]
        self.fill_array(self.weights, (n_input, new_neurons),
                        self.weights_stddev, self.weights_filling)
        keep = min(old_n, new_neurons)
        self.weights.map_write()[:, :keep] = old_w[:, :keep]
        if self.include_bias:
            self.fill_array(self.bias, (new_neurons,),
                            self.bias_stddev, self.bias_filling)
            self.bias.map_write()[:keep] = old_b[:keep]
        if self.output:
            # downstream units size themselves off output.shape — stale
            # old-width buffers must not survive a resize
            self.output.reset(numpy.zeros(
                (self.output.shape[0], int(new_neurons)), numpy.float32))
        if self.is_initialized and self.device is not None \
                and self.device.exists:
            self.tpu_init()
