"""Gradient-descent units for the all2all family.

Re-creation of ``veles.znicz.gd`` (absent; SURVEY.md §2.9):
GradientDescent, GDTanh, GDSigmoid, GDRELU, GDStrictRELU, GDSoftmax.

Explicit backward math (the activation derivative folded into err_output,
then one matmul each for grad_W and err_input — the same two GEMMs the
reference's CUDA kernels issue, here lowered to the MXU by XLA):

    err = err_output * act'(y)
    grad_W = x^T err / B;  grad_b = mean(err);  err_input = err W^T
"""

from .nn_units import GradientDescentBase
from . import activations


class GradientDescent(GradientDescentBase):
    """Backward for linear All2All."""

    MAPPING = "all2all"
    ACTIVATION = "linear"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.activation = activations.get(self.ACTIVATION)

    def _linear_bwd(self, params, x, err, n_valid, xp):
        """grad_W = x^T err / n_valid; padded rows are already zero in err
        (the evaluator masks them), so dividing by the *valid* count keeps
        partial minibatches consistent with the fused path's mask.sum()."""
        xf = x.reshape(x.shape[0], -1)
        grads = {"weights": xf.T @ err / n_valid}
        if "bias" in params:
            grads["bias"] = err.sum(axis=0) / n_valid
        if self.need_err_input:
            err_input = (err @ params["weights"].T).reshape(x.shape)
        else:
            err_input = None  # skip a full GEMM for first-layer units
        return err_input, grads

    def backward(self, params, x, y, err_output, n_valid=None):
        import jax.numpy as jnp
        if n_valid is None:
            n_valid = x.shape[0]
        err = err_output.reshape(err_output.shape[0], -1)
        err = err * self.activation.deriv_jnp(
            y.reshape(err.shape), None)
        return self._linear_bwd(params, x, err, n_valid, jnp)

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        import numpy
        if n_valid is None:
            n_valid = x.shape[0]
        err = err_output.reshape(err_output.shape[0], -1)
        err = err * self.activation.deriv_np(y.reshape(err.shape), None)
        return self._linear_bwd(params, x, err, n_valid, numpy)


class GDTanh(GradientDescent):
    MAPPING = "all2all_tanh"
    ACTIVATION = "tanh"


class GDSigmoid(GradientDescent):
    MAPPING = "all2all_sigmoid"
    ACTIVATION = "sigmoid"


class GDRELU(GradientDescent):
    MAPPING = "all2all_relu"
    ACTIVATION = "relu"


class GDStrictRELU(GradientDescent):
    MAPPING = "all2all_str"
    ACTIVATION = "strict_relu"


class GDSoftmax(GradientDescent):
    """Backward for All2AllSoftmax.  The evaluator emits
    ``err_output = (y - onehot)`` — the cross-entropy gradient wrt the
    logits, not yet divided by batch size; ``_linear_bwd`` performs the
    single division by the valid batch count.  No activation-derivative
    multiply happens here (reference GDSoftmax contract with
    EvaluatorSoftmax)."""

    MAPPING = "softmax"
    ACTIVATION = "linear"

    def backward(self, params, x, y, err_output, n_valid=None):
        import jax.numpy as jnp
        if n_valid is None:
            n_valid = x.shape[0]
        err = err_output.reshape(err_output.shape[0], -1)
        return self._linear_bwd(params, x, err, n_valid, jnp)

    def backward_numpy(self, params, x, y, err_output, n_valid=None):
        import numpy
        if n_valid is None:
            n_valid = x.shape[0]
        err = err_output.reshape(err_output.shape[0], -1)
        return self._linear_bwd(params, x, err, n_valid, numpy)


class RPropAll2All(GradientDescent):
    """All2All trainer with resilient propagation (reference
    rprop_all2all.RPropAll2All)."""

    MAPPING = "all2all_rprop"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("solver", "rprop")
        super().__init__(workflow, **kwargs)
