"""Decision units: epoch accounting, best-error tracking, stop conditions.

Re-creation of ``veles.znicz.decision.DecisionGD`` (absent; SURVEY.md §2.9).
The Decision sits after the evaluator, watches the loader's class/epoch
flags, and drives the control plane:

- accumulates per-class error counts over each epoch;
- on epoch end: computes percentages, tracks the best validation error,
  raises ``improved`` (gates the snapshotter) and ``complete`` (ends the
  main loop) Bools;
- stop conditions: ``max_epochs`` reached, or ``fail_iterations`` epochs
  without validation improvement (early stopping).

This unit is pure host-side control — exactly the kind of unit the TPU
build keeps *outside* the jitted step (SURVEY.md §7 "hard parts").
"""

import numpy

from ..mutable import Bool
from ..result_provider import IResultProvider
from ..units import Unit
from .. import loader as loader_mod


class DecisionBase(Unit):
    hide_from_registry = True
    view_group = "PLUMBING"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.train_improved = Bool(False)
        self.max_epochs = kwargs.get("max_epochs")
        self.fail_iterations = kwargs.get("fail_iterations", 100)
        # linked from loader:
        self.last_minibatch = None
        self.epoch_ended = None
        self.minibatch_class = None
        self.minibatch_size = None
        self.class_lengths = None
        self.epoch_number = None

    def link_loader(self, loader):
        self.link_attrs(loader, "last_minibatch", "epoch_ended",
                        "minibatch_class", "minibatch_size",
                        "class_lengths", "epoch_number")
        return self


class DecisionGD(DecisionBase, IResultProvider):
    """Decision for classification training (n_err driven)."""

    MAPPING = "decision_gd"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.evaluator = None
        self.n_err = None            # linked: evaluator.n_err Array
        self.epoch_n_err = [0, 0, 0]
        self.epoch_n_err_pt = [100.0, 100.0, 100.0]
        self.best_n_err = None
        self.best_n_err_pt = None
        self.best_epoch = -1
        self.epochs_without_improvement = 0
        self.silent = bool(kwargs.get("silent", False))

    def link_evaluator(self, evaluator):
        self.evaluator = evaluator
        self.link_attrs(evaluator, "n_err")
        return self

    def run(self):
        if not bool(self.last_minibatch):
            return
        cls = self.minibatch_class
        self.epoch_n_err[cls] = int(self.n_err[0])
        length = self.class_lengths[cls] or 1
        self.epoch_n_err_pt[cls] = 100.0 * self.epoch_n_err[cls] / length
        # reset the evaluator's accumulator for the next class/epoch
        self.n_err.map_write()[0] = 0
        if cls == loader_mod.VALID:
            self._on_validation_end()
        if bool(self.epoch_ended):
            self._on_epoch_end()

    def _on_validation_end(self):
        err = self.epoch_n_err[loader_mod.VALID]
        if self.best_n_err is None or err < self.best_n_err:
            self.best_n_err = err
            self.best_n_err_pt = self.epoch_n_err_pt[loader_mod.VALID]
            self.best_epoch = self.epoch_number
            self.epochs_without_improvement = 0
            self.improved <<= True
        else:
            self.epochs_without_improvement += 1
            self.improved <<= False

    def _on_epoch_end(self):
        if not self.silent:
            print("Epoch %d: validation %.2f%%, train %.2f%%%s" % (
                self.epoch_number,
                self.epoch_n_err_pt[loader_mod.VALID],
                self.epoch_n_err_pt[loader_mod.TRAIN],
                " *" if bool(self.improved) else ""))
        if self.max_epochs is not None and \
                self.epoch_number + 1 >= self.max_epochs:
            self.complete <<= True
        if self.epochs_without_improvement >= self.fail_iterations:
            self.complete <<= True

    def get_metric_values(self):
        return {
            "best_validation_error_pt": self.best_n_err_pt,
            "best_epoch": self.best_epoch,
            "train_error_pt": self.epoch_n_err_pt[loader_mod.TRAIN],
        }


class DecisionMSE(DecisionBase, IResultProvider):
    """Decision for regression training (rmse driven; reference
    decision.DecisionMSE)."""

    MAPPING = "decision_mse"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.metrics = None          # linked: evaluator.metrics Array
        self.epoch_rmse = [0.0, 0.0, 0.0]
        self.best_rmse = None
        self.best_epoch = -1
        self.epochs_without_improvement = 0
        self.silent = bool(kwargs.get("silent", False))

    def link_evaluator(self, evaluator):
        self.link_attrs(evaluator, "metrics")
        return self

    def run(self):
        if not bool(self.last_minibatch):
            return
        cls = self.minibatch_class
        n = (self.class_lengths[cls] or 1)
        # metrics[0] accumulates per-sample mean squared error
        self.epoch_rmse[cls] = float(numpy.sqrt(self.metrics[0] / n))
        m = self.metrics.map_write()
        m[0] = 0
        m[1] = 0
        m[2] = numpy.inf
        if cls == loader_mod.VALID:
            rmse = self.epoch_rmse[loader_mod.VALID]
            if self.best_rmse is None or rmse < self.best_rmse:
                self.best_rmse = rmse
                self.best_epoch = self.epoch_number
                self.epochs_without_improvement = 0
                self.improved <<= True
            else:
                self.epochs_without_improvement += 1
                self.improved <<= False
        if bool(self.epoch_ended):
            if not self.silent:
                print("Epoch %d: validation rmse %.4f, train rmse %.4f%s" % (
                    self.epoch_number, self.epoch_rmse[loader_mod.VALID],
                    self.epoch_rmse[loader_mod.TRAIN],
                    " *" if bool(self.improved) else ""))
            if self.max_epochs is not None and \
                    self.epoch_number + 1 >= self.max_epochs:
                self.complete <<= True
            if self.epochs_without_improvement >= self.fail_iterations:
                self.complete <<= True

    def get_metric_values(self):
        return {"best_validation_rmse": self.best_rmse,
                "best_epoch": self.best_epoch}


class TrivialDecision(DecisionBase):
    """Fixed-epoch-count decision with no metric tracking."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("max_epochs", 1)
        super().__init__(workflow, **kwargs)

    def run(self):
        if bool(self.epoch_ended) and \
                self.epoch_number + 1 >= self.max_epochs:
            self.complete <<= True
