"""Local response normalization (AlexNet LRN).

Re-creation of ``veles.znicz.normalization.LRNormalizerForward/Backward``
(absent; SURVEY.md §2.9).  Cross-channel LRN:

    y = x / (k + alpha/n * sum_{j in window} x_j^2) ** beta

Two device paths:

- the **default**: the channel-window sum as ONE banded [C, C] matmul
  (``_window_sum_mxu``) — LRN is memory-bound (round-3 ablation: ~19 %
  of an AlexNet f32 step as n shifted HBM passes), and the band form
  moves it onto the MXU for a few percent of extra (free) FLOPs.
  Round-4 on-chip A/B: the biggest single perf win of the round
  (docs/PERF.md).  Summation order differs from the numpy twin's
  shifted adds by float-reassociation noise only (parity tests use
  atol 1e-5 and pass).
- ``use_pallas=True``: a **Pallas kernel pair** (forward + analytic
  backward via ``jax.custom_vjp``) with the closed form
  ``dx = g·den^-β − 2β·(α/n)·x·W(g·x·den^-(β+1))`` (W = the same
  channel-window sum).  Since round 4 it is gridded (1024xC row tiles)
  and compiles on the tunneled chip in ~18 s — but it LOSES end-to-end
  (0.76x, docs/PERF.md): the ``pallas_call`` boundary blocks XLA from
  fusing LRN into its neighbors, which the matmul form allows.  Kept
  as the measured hand-kernel reference point
  (``root.common.engine.use_pallas`` / per-layer ``use_pallas=True``);
  on non-TPU backends it runs in Pallas interpret mode.
"""

import functools

import jax
import numpy

from .nn_units import ParamlessForward, GenericVJPBackward


def _window_sum(v, n, xp, transpose=False):
    """Channel-axis sliding-window sum via static shifted concats (the
    form that lowers cleanly inside Pallas — jnp.roll/pad do not).
    Offsets are ``-n//2 .. n-1-n//2`` — the exact (asymmetric for even
    n) window the jnp/numpy ``_den`` formula uses.  ``transpose=True``
    negates the offsets: the VJP of an asymmetric window sum is the
    window sum over the TRANSPOSED window (for odd n they coincide)."""
    C = v.shape[-1]
    half = n // 2
    offsets = range(-half, n - half)
    if transpose:
        offsets = [-o for o in offsets]
    acc = None
    for off in offsets:
        if off == 0:
            t = v
        elif off > 0:
            z = xp.zeros(v.shape[:-1] + (off,), v.dtype)
            t = xp.concatenate([v[..., off:], z], axis=-1)
        else:
            z = xp.zeros(v.shape[:-1] + (-off,), v.dtype)
            t = xp.concatenate([z, v[..., :C + off]], axis=-1)
        acc = t if acc is None else acc + t
    return acc


def _band_matrix(c, n, dtype, transpose=False):
    """The [C, C] 0/1 band whose matmul computes the channel-window sum:
    ``(v @ B)[..., i] = sum_{off} v[..., i + off]`` over the same
    asymmetric offsets as :func:`_window_sum`.  ``transpose=True`` gives
    the window-sum over the negated offsets (the VJP's window)."""
    half = n // 2
    j = numpy.arange(c)
    d = j[:, None] - j[None, :]        # B[j, i] = 1 iff j - i in window
    lo, hi = -half, n - 1 - half
    band = ((d >= lo) & (d <= hi)).astype(dtype)
    return band.T if transpose else band


def _window_sum_mxu(v, n, transpose=False):
    """The channel window sum as ONE banded matmul: LRN's window
    accumulation is the memory-bound 19 % of an AlexNet step when done
    as n shifted HBM passes (docs/PERF.md); as a [.., C] x [C, C]
    product it rides the MXU, reading and writing each activation
    exactly once for a few % extra (essentially free) FLOPs."""
    import jax.numpy as jnp
    c = v.shape[-1]
    band = jnp.asarray(_band_matrix(c, n, numpy.float32,
                                    transpose=transpose), v.dtype)
    return jnp.einsum("...c,cd->...d", v, band)


def _pallas_interpret():
    return jax.default_backend() != "tpu"


_LRN_BLOCK_ROWS = 1024


def lrn_mxu(x, n, alpha, beta, k):
    """The MXU-band LRN forward as a free function (the math of the
    default ``apply`` path) — the ``impl: "mxu"`` layout candidate of
    the ``lrn`` autotune site, and what a tuned record dispatches to
    when the band measured faster than the Pallas pair."""
    import jax.numpy as jnp
    from jax import lax
    acc = _window_sum_mxu(x * x, n)
    den = k + (alpha / n) * acc
    if beta == 0.75:
        # den^-3/4 = rsqrt(den) * sqrt(rsqrt(den)) — two cheap HW
        # ops instead of the exp/log pair a general pow lowers to
        # (AlexNet's default beta; the generic path stays below)
        r = lax.rsqrt(den)
        return x * (r * jnp.sqrt(r))
    return x / den ** beta


def _lrn_grid(x, block_rows=None):
    """Flatten [..., C] to [N, C] and tile N into VMEM-sized row blocks.

    The round-3 kernel mapped the WHOLE array into one kernel invocation
    — at production shapes (128x55x55x96 f32 = 148 MB) Mosaic ground for
    >20 min on the oversized block and the bench recorded a timeout
    every round.  A trivial gridded kernel compiles on the same tunneled
    chip in <1 s (round-4 probe), so the fix is simply a real grid:
    row tiles of ``block_rows`` (default 1024, ~0.4-1 MB VMEM; tunable
    via the ``lrn`` autotune site), rows independent because the
    LRN window runs along C only.  Block-padding rows beyond N is safe —
    padded rows produce garbage that is never written back."""
    import jax.numpy as jnp
    c = x.shape[-1]
    flat = x.reshape(-1, c)
    from jax.experimental import pallas as pl
    rows = int(block_rows or _LRN_BLOCK_ROWS)
    grid = (pl.cdiv(flat.shape[0], rows),)
    spec = pl.BlockSpec((rows, c), lambda i: (i, 0))
    return flat, grid, spec


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def pallas_lrn(x, n, alpha, beta, k, block_rows=None):
    """Fused cross-channel LRN forward (Pallas, gridded row tiles)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        xv = x_ref[...]
        acc = _window_sum(xv * xv, n, jnp)
        o_ref[...] = xv / (k + (alpha / n) * acc) ** beta

    flat, grid, spec = _lrn_grid(x, block_rows)
    out = pl.pallas_call(
        kernel, grid=grid, in_specs=[spec], out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        interpret=_pallas_interpret())(flat)
    return out.reshape(x.shape)


def _pallas_lrn_fwd(x, n, alpha, beta, k, block_rows=None):
    return pallas_lrn(x, n, alpha, beta, k, block_rows), x


def _pallas_lrn_bwd(n, alpha, beta, k, block_rows, x, g):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(x_ref, g_ref, o_ref):
        xv = x_ref[...]
        gv = g_ref[...]
        c = alpha / n
        den = k + c * _window_sum(xv * xv, n, jnp)
        inner = gv * xv * den ** (-beta - 1.0)
        o_ref[...] = (gv * den ** -beta -
                      2.0 * beta * c * xv *
                      _window_sum(inner, n, jnp, transpose=True))

    flat, grid, spec = _lrn_grid(x, block_rows)
    gflat = g.reshape(flat.shape)
    dx = pl.pallas_call(
        kernel, grid=grid, in_specs=[spec, spec], out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        interpret=_pallas_interpret())(flat, gflat)
    return (dx.reshape(x.shape),)


pallas_lrn.defvjp(_pallas_lrn_fwd, _pallas_lrn_bwd)


class LRNormalizerForward(ParamlessForward):
    MAPPING = "norm"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.alpha = float(kwargs.get("alpha", 1e-4))
        self.beta = float(kwargs.get("beta", 0.75))
        self.k = float(kwargs.get("k", 2.0))
        self.n = int(kwargs.get("n", 5))
        self.include_bias = False
        from ..config import root
        # tri-state like attention's knob (nn_units.resolve_use_pallas)
        # — but AUTO resolves False here: the Pallas pair measured a
        # LOSS vs the MXU-band XLA path (docs/PERF.md, ~0.68x)
        up = kwargs.get("use_pallas",
                        root.common.engine.get("use_pallas", None))
        self.use_pallas = up if up is None else bool(up)

    def _den(self, sq, xp):
        acc = _window_sum(sq, self.n, xp)
        return (self.k + (self.alpha / self.n) * acc) ** self.beta

    def apply(self, params, x):
        from .nn_units import resolve_use_pallas
        if resolve_use_pallas(self.use_pallas, self.device,
                              tpu_auto=False):
            # the pallas path is a TUNABLE SITE: with a tuning record
            # for this (C, n, device, versions) the measured winner
            # decides the row-tile size — or the mxu band LAYOUT, the
            # answer when the pallas_call fusion boundary loses on this
            # device class.  Tuner off = the exact hand-picked kernel.
            from ..autotune import dispatch as _autotune
            cfg, src = _autotune.resolve(
                "lrn", "c%d_n%d" % (x.shape[-1], self.n),
                default={"impl": "pallas",
                         "block_rows": _LRN_BLOCK_ROWS})
            self.config_source = src
            if cfg.get("impl") != "mxu":
                return pallas_lrn(x, self.n, self.alpha, self.beta,
                                  self.k, int(cfg["block_rows"]))
        else:
            self.config_source = "default"
        # MXU path: one banded matmul instead of n shifted HBM passes
        # (autodiff gives the transposed band for the backward)
        return lrn_mxu(x, self.n, self.alpha, self.beta, self.k)

    def apply_numpy(self, params, x):
        return x / self._den(x * x, numpy)

    def export_params(self):
        return {"alpha": self.alpha, "beta": self.beta, "k": self.k,
                "n": self.n}


class LRNormalizerBackward(GenericVJPBackward):
    MAPPING = "norm"
