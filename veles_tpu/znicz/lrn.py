"""Local response normalization (AlexNet LRN).

Re-creation of ``veles.znicz.normalization.LRNormalizerForward/Backward``
(absent; SURVEY.md §2.9).  Cross-channel LRN:

    y = x / (k + alpha/n * sum_{j in window} x_j^2) ** beta

computed with a channel-axis ``reduce_window`` — fuses cleanly in XLA.
"""

import numpy

from .nn_units import ParamlessForward, GenericVJPBackward


class LRNormalizerForward(ParamlessForward):
    MAPPING = "norm"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.alpha = float(kwargs.get("alpha", 1e-4))
        self.beta = float(kwargs.get("beta", 0.75))
        self.k = float(kwargs.get("k", 2.0))
        self.n = int(kwargs.get("n", 5))
        self.include_bias = False

    def _den(self, sq, xp):
        half = self.n // 2
        pad = [(0, 0)] * sq.ndim
        pad[-1] = (half, half)
        padded = xp.pad(sq, pad)
        acc = xp.zeros_like(sq)
        for d in range(self.n):
            acc = acc + padded[..., d:d + sq.shape[-1]]
        return (self.k + (self.alpha / self.n) * acc) ** self.beta

    def apply(self, params, x):
        import jax.numpy as jnp
        return x / self._den(x * x, jnp)

    def apply_numpy(self, params, x):
        return x / self._den(x * x, numpy)


    def export_params(self):
        return {"alpha": self.alpha, "beta": self.beta, "k": self.k,
                "n": self.n}


class LRNormalizerBackward(GenericVJPBackward):
    MAPPING = "norm"
