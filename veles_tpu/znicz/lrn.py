"""Local response normalization (AlexNet LRN).

Re-creation of ``veles.znicz.normalization.LRNormalizerForward/Backward``
(absent; SURVEY.md §2.9).  Cross-channel LRN:

    y = x / (k + alpha/n * sum_{j in window} x_j^2) ** beta

Two device paths:

- ``use_pallas=True``: a **Pallas kernel pair** (forward + analytic
  backward via ``jax.custom_vjp``): LRN is memory-bound, and the kernel
  does the window accumulation and the power in one VMEM-resident pass
  instead of the n shifted HBM reads XLA materializes for the
  padded-slice formula.  The backward uses the closed form
  ``dx = g·den^-β − 2β·(α/n)·x·W(g·x·den^-(β+1))`` (W = the same
  channel-window sum), so autodiff through the fused trainer works.
  On non-TPU backends the same kernels run in Pallas interpret mode.
- the default is the plain jnp padded-slice formula (bit-compatible
  with the numpy twin).  It stays the default because tunneled
  remote-compile environments (axon) cannot build Mosaic kernels at
  production shapes — on a directly-attached TPU flip ``use_pallas``
  on per layer or via ``root.common.engine.use_pallas``.
"""

import functools

import jax
import numpy

from .nn_units import ParamlessForward, GenericVJPBackward


def _window_sum(v, n, xp, transpose=False):
    """Channel-axis sliding-window sum via static shifted concats (the
    form that lowers cleanly inside Pallas — jnp.roll/pad do not).
    Offsets are ``-n//2 .. n-1-n//2`` — the exact (asymmetric for even
    n) window the jnp/numpy ``_den`` formula uses.  ``transpose=True``
    negates the offsets: the VJP of an asymmetric window sum is the
    window sum over the TRANSPOSED window (for odd n they coincide)."""
    C = v.shape[-1]
    half = n // 2
    offsets = range(-half, n - half)
    if transpose:
        offsets = [-o for o in offsets]
    acc = None
    for off in offsets:
        if off == 0:
            t = v
        elif off > 0:
            z = xp.zeros(v.shape[:-1] + (off,), v.dtype)
            t = xp.concatenate([v[..., off:], z], axis=-1)
        else:
            z = xp.zeros(v.shape[:-1] + (-off,), v.dtype)
            t = xp.concatenate([z, v[..., :C + off]], axis=-1)
        acc = t if acc is None else acc + t
    return acc


def _pallas_interpret():
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def pallas_lrn(x, n, alpha, beta, k):
    """Fused cross-channel LRN forward (Pallas)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        xv = x_ref[...]
        acc = _window_sum(xv * xv, n, jnp)
        o_ref[...] = xv / (k + (alpha / n) * acc) ** beta

    return pl.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_pallas_interpret())(x)


def _pallas_lrn_fwd(x, n, alpha, beta, k):
    return pallas_lrn(x, n, alpha, beta, k), x


def _pallas_lrn_bwd(n, alpha, beta, k, x, g):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(x_ref, g_ref, o_ref):
        xv = x_ref[...]
        gv = g_ref[...]
        c = alpha / n
        den = k + c * _window_sum(xv * xv, n, jnp)
        inner = gv * xv * den ** (-beta - 1.0)
        o_ref[...] = (gv * den ** -beta -
                      2.0 * beta * c * xv *
                      _window_sum(inner, n, jnp, transpose=True))

    dx = pl.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_pallas_interpret())(x, g)
    return (dx,)


pallas_lrn.defvjp(_pallas_lrn_fwd, _pallas_lrn_bwd)


class LRNormalizerForward(ParamlessForward):
    MAPPING = "norm"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.alpha = float(kwargs.get("alpha", 1e-4))
        self.beta = float(kwargs.get("beta", 0.75))
        self.k = float(kwargs.get("k", 2.0))
        self.n = int(kwargs.get("n", 5))
        self.include_bias = False
        from ..config import root
        self.use_pallas = bool(kwargs.get(
            "use_pallas", root.common.engine.get("use_pallas", False)))

    def _den(self, sq, xp):
        acc = _window_sum(sq, self.n, xp)
        return (self.k + (self.alpha / self.n) * acc) ** self.beta

    def apply(self, params, x):
        if self.use_pallas:
            return pallas_lrn(x, self.n, self.alpha, self.beta, self.k)
        import jax.numpy as jnp
        return x / self._den(x * x, jnp)

    def apply_numpy(self, params, x):
        return x / self._den(x * x, numpy)

    def export_params(self):
        return {"alpha": self.alpha, "beta": self.beta, "k": self.k,
                "n": self.n}


class LRNormalizerBackward(GenericVJPBackward):
    MAPPING = "norm"
