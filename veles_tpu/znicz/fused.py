"""FusedTrainStep: the whole forward+loss+backward+update chain as ONE
jitted, donated step function.

This is the TPU-native collapse of the reference's hot loop (SURVEY.md §3.1:
one thread-pool dispatch + gate lock per unit per minibatch).  The unit
graph remains the *build-time* description — forwards and GD configs are
taken from the same units graph mode uses — but at run time a single
``jax.jit`` function with donated params/opt-state executes per minibatch:

    (params, opt, x, labels, size) -> (params', opt', loss, n_err)

Buffer donation keeps one copy of the params in HBM; the loss for softmax
heads uses fused log-softmax cross-entropy on the *logits* (numerically
stabler and one less HBM round-trip than materializing probabilities).
Metrics surface through the same ``n_err``/``metrics`` Arrays the
evaluator exposes, so Decision units work unchanged.
"""

import numpy

from ..compilecache import AotStep, default_cache
from ..config import root
from ..memory import Array
from ..result_provider import IResultProvider
from ..units import Unit
from .. import loader as loader_mod
from .all2all import All2AllSoftmax
from .evaluator import EvaluatorSoftmax, EvaluatorMSE
from . import solvers


class FusedTrainStep(Unit, IResultProvider):
    """One-step fused trainer over a chain of forward units.

    Parameters: ``forwards`` (list of ForwardBase), ``gd_configs`` (list of
    GradientDescentBase *or* kwargs dicts, one per forward, reverse not
    required), ``loss`` ("softmax" | "mse").
    """

    def __init__(self, workflow, forwards, gd_units, loss="softmax",
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.gather_loader = None   # set by link_fused_gather
        self.forwards = list(forwards)
        self.gd_units = list(gd_units)
        assert len(self.gd_units) == len(self.forwards)
        self.loss_kind = loss
        # linked from loader:
        self.minibatch_data = None
        self.minibatch_labels = None
        self.minibatch_targets = None
        self.minibatch_size = None
        self.minibatch_class = None
        self.last_minibatch = None
        # evaluator-compatible metric surface:
        self.n_err = Array(numpy.zeros(1, numpy.int64))
        self.metrics = Array(numpy.zeros(3, numpy.float64))
        self.metrics.mem[2] = numpy.inf
        self.confusion_matrix = Array()
        self.max_err_output_sum = Array(numpy.zeros(1, numpy.float32))
        # the [C, C] accumulator rides the jitted carry; for large class
        # counts (C^2 ints per device, one_hot + scatter-add per step) turn
        # it off like the graph evaluator's knob (evaluator.py)
        self.compute_confusion_matrix = bool(
            kwargs.get("compute_confusion_matrix", True))
        self.loss = None
        self.output = Array()      # last forward's output (for consumers)
        self.max_idx = Array()
        # deterministic per-step seed for stochastic units (dropout,
        # stochastic pooling); pickles with the snapshot.  Kept within
        # int32 so it passes as a jit scalar without overflow.
        self._seed_counter = (int(kwargs.get("seed", 42)) *
                              1_000_003) % 0x7FFF0000
        # global learning-rate multiplier, set per epoch by
        # LearningRateAdjuster; 1.0 = the configured base rates
        self.lr_scale = 1.0
        # mixed precision: "bfloat16" runs the forward/backward matmuls
        # in bf16 (full MXU rate) while master params, the loss, and the
        # solver update stay f32 — the standard TPU recipe.  None = f32
        # throughout (bit-parity with graph mode).
        self.compute_dtype = kwargs.get(
            "compute_dtype", root.common.engine.get("dtype", "float32"))
        if self.compute_dtype in ("float32", None):
            self.compute_dtype = None

    def link_loader(self, loader):
        self.link_attrs(loader, "minibatch_data", "minibatch_labels",
                        "minibatch_size", "minibatch_class",
                        "last_minibatch")
        if hasattr(loader, "minibatch_targets"):
            self.link_attrs(loader, "minibatch_targets")
        return self

    def link_fused_gather(self, loader):
        """Fuse the HBM-resident minibatch gather INTO the jitted step.

        The loader then only computes shuffled indices host-side; the
        ``jnp.take`` rides inside the same executable as the forward/
        backward — one launch per step instead of two.  On tunneled or
        remote devices each executable launch costs an RTT (~14 ms
        measured through axon), so separate gather+step launches dominate
        mid-size models; fusing them is also strictly less HBM traffic
        (the gathered batch never materializes as a separate buffer
        between two executables)."""
        self.gather_loader = loader
        loader.defer_device_gather = True
        return self

    # -- jit construction ----------------------------------------------------
    def initialize(self, device=None, **kwargs):
        # forwards live outside the control graph in fused mode, so they
        # have not been initialized by the dependency walk — bring them up
        # in chain order (shapes propagate input→output)
        for fwd in self.forwards:
            if not fwd.is_initialized:
                fwd.initialize(device=device, **kwargs)
        super().initialize(**kwargs)
        self.device = device
        import jax
        import jax.numpy as jnp

        forwards = self.forwards
        gds = self.gd_units
        loss_kind = self.loss_kind
        softmax_head = isinstance(forwards[-1], All2AllSoftmax)
        has_stochastic = any(f.stochastic for f in forwards)

        cdtype = self.compute_dtype
        if cdtype is not None:
            cdtype = jnp.dtype(cdtype)

        def net_apply(params, x, with_logits, seed):
            if cdtype is not None:
                # cast once at the boundary; XLA keeps everything in
                # compute dtype through the chain (MXU native rate)
                params = jax.tree.map(lambda p: p.astype(cdtype), params)
                x = x.astype(cdtype)
            h = x
            train = seed is not None
            if train and has_stochastic:
                # rng_impl="rbg" swaps threefry for the TPU-cheap
                # hardware RBG (dropout masks cost ~4% of an AlexNet
                # step as threefry VPU work); default stays threefry —
                # reproducible across backends
                impl = root.common.engine.get("rng_impl",
                                              "threefry2x32")
                key = jax.random.key(seed, impl=impl)
            for i, fwd in enumerate(forwards[:-1]):
                if train and fwd.stochastic:
                    h = fwd.apply_train(params[i], h,
                                        jax.random.fold_in(key, i))
                else:
                    h = fwd.apply(params[i], h)
            last = forwards[-1]
            if with_logits and softmax_head:
                return last.apply_logits(params[-1], h)
            return last.apply(params[-1], h)

        def loss_fn(params, x, labels_or_targets, mask, seed=None):
            out = net_apply(params, x, True, seed)
            # the loss itself is f32: bf16 log-sum-exp/reduction noise
            # would feed straight into the gradients' scale
            out = out.astype(jnp.float32)
            if loss_kind == "softmax":
                data_loss = EvaluatorSoftmax.loss_from_logits(
                    out, labels_or_targets, mask)
            else:
                data_loss = EvaluatorMSE.loss_from_output(
                    out, labels_or_targets, mask)
            return data_loss, out

        n_classes = int(self.forwards[-1].output.shape[-1]) \
            if loss_kind == "softmax" else 0
        self._n_classes = n_classes
        with_cm = self.compute_confusion_matrix
        if loss_kind == "softmax" and with_cm and not self.confusion_matrix:
            # int32 throughout: the running total lives on device (jax
            # default integer width), bounding any one [pred, true] cell
            # at 2^31 counts — i.e. >2 billion samples routed through a
            # single cell before wraparound, far past any other counter
            self.confusion_matrix.mem = numpy.zeros(
                (n_classes, n_classes), numpy.int32)
        self._cm_dev_ = None    # device-resident running total (flush)

        def accumulate(macc, out, labels_or_targets, mask):
            """Fold one step's outputs into the device-resident metric
            accumulator.  Matches the graph evaluators' side-channels:
            softmax → (n_err, confusion[pred, true], max row |err| sum over
            probabilities); mse → (sum sample-mse, max rmse, min rmse)."""
            if loss_kind == "softmax":
                n, cm, mx = macc
                # exact integer count (float32 would lose counts past 2^24)
                pred = jnp.argmax(out, axis=-1)
                wrong = (pred != labels_or_targets) & (mask > 0)
                onehot = jax.nn.one_hot(labels_or_targets, n_classes,
                                        dtype=out.dtype)
                err_rows = jnp.abs(out - onehot).sum(axis=1) * mask
                if with_cm:
                    # scatter-add serializes on the TPU vector unit (a
                    # measured 22% hit on the MNIST scan bench); the same
                    # histogram as a one-hot outer product rides the MXU.
                    # float32 counts are exact here: per-step counts are
                    # bounded by the batch (< 2^24)
                    pred_oh = jax.nn.one_hot(
                        pred, n_classes, dtype=jnp.float32) * mask[:, None]
                    true_oh = jax.nn.one_hot(
                        labels_or_targets, n_classes, dtype=jnp.float32)
                    cm = cm + jnp.einsum(
                        "bi,bj->ij", pred_oh, true_oh).astype(jnp.int32)
                return (n + wrong.astype(jnp.int32).sum(), cm,
                        jnp.maximum(mx, err_rows.max()))
            sse, mx, mn = macc
            err = (out - labels_or_targets).reshape(out.shape[0], -1)
            sample_mse = (err * err).mean(axis=1)
            rmse = jnp.sqrt(sample_mse)
            valid = mask > 0
            return (sse + (sample_mse * mask).sum(),
                    jnp.maximum(mx, jnp.where(valid, rmse, -jnp.inf).max()),
                    jnp.minimum(mn, jnp.where(valid, rmse, jnp.inf).min()))

        def observable(out):
            """What consumers linked to ``output`` see: probabilities for a
            softmax head (graph-mode All2AllSoftmax.output parity), raw
            output otherwise.  The loss itself consumed the logits."""
            return jax.nn.softmax(out) if softmax_head else out

        def train_step(params, opt, macc, x, y, size, seed, lr_scale):
            mask = (jnp.arange(x.shape[0]) < size).astype(jnp.float32)
            (loss, out), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, x, y, mask, seed)
            new_params, new_opt = [], []
            for i, gd in enumerate(gds):
                layer_p, layer_o = {}, {}
                for name, p in params[i].items():
                    g = grads[i][name]
                    decay, l1l2, ortho = gd.decay_for(name)
                    g = solvers.regularized_grad(g, p, decay, l1l2, jnp,
                                                 ortho)
                    # lr_scale: DYNAMIC schedule knob (LearningRateAdjuster)
                    # — an argument, not a constant, so per-epoch decay
                    # never retraces the step
                    delta, st = gd.solver.update(
                        g, p, opt[i][name], gd.lr_for(name) * lr_scale,
                        jnp)
                    layer_p[name] = p + delta
                    layer_o[name] = st
                new_params.append(layer_p)
                new_opt.append(layer_o)
            out = observable(out)
            macc = accumulate(macc, out, y, mask)
            return new_params, new_opt, macc, loss, out

        def eval_step(params, macc, x, y, size):
            mask = (jnp.arange(x.shape[0]) < size).astype(jnp.float32)
            loss, out = loss_fn(params, x, y, mask)
            out = observable(out)
            return accumulate(macc, out, y, mask), loss, out

        # the metric accumulator stays ON DEVICE between steps and is
        # flushed to the host only at class boundaries — per-step int()
        # pulls would serialize the pipeline on a device sync.  int32 for
        # error counts (exact); float32 for mse sums (flushed per class,
        # so drift stays bounded by one epoch)
        self._macc_ = self._macc_init()
        self._train_step_ = jax.jit(train_step, donate_argnums=(0, 1, 2))
        self._eval_step_ = jax.jit(eval_step, donate_argnums=(1,))
        # the gather-in-step path needs the dataset resident on a real
        # device; numpy/force-numpy loaders fill minibatch_data host-side
        # instead, so fall back to the plain step there
        self._use_gather_ = (self.gather_loader is not None and
                             getattr(self.gather_loader, "_use_device",
                                     False))
        if self._use_gather_:
            # gather-in-step variants: the resident dataset rides as an
            # ARGUMENT (a closed-over jax.Array would be baked into the
            # HLO as a literal — see loader/fullbatch.py)
            ld = self.gather_loader
            self._data_dev_ = ld.original_data.devmem
            if self.loss_kind == "softmax":
                self._y_dev_ = jax.device_put(ld._dense_labels)
            else:
                self._y_dev_ = ld.original_targets.devmem

            def train_step_g(data, y_all, params, opt, macc, idx, size,
                             seed, lr_scale):
                x = jnp.take(data, idx, axis=0)
                y = jnp.take(y_all, idx, axis=0)
                return train_step(params, opt, macc, x, y, size, seed,
                                  lr_scale)

            def eval_step_g(data, y_all, params, macc, idx, size):
                x = jnp.take(data, idx, axis=0)
                y = jnp.take(y_all, idx, axis=0)
                return eval_step(params, macc, x, y, size)

            self._train_step_g_ = jax.jit(train_step_g,
                                          donate_argnums=(2, 3, 4))
            self._eval_step_g_ = jax.jit(eval_step_g, donate_argnums=(3,))
        # persistent executable cache (compilecache subsystem): wrap the
        # jitted steps so an ElasticRunner respawn / snapshot restore
        # deserializes yesterday's executable instead of recompiling.
        # AotStep keeps __wrapped__ (the scan/mesh steps re-jit from the
        # raw function) and falls back to the plain jit path on any
        # surprise; no configured cache dir = exactly the code above
        cache = default_cache()
        if cache is not None:
            self._train_step_ = AotStep(self._train_step_, cache,
                                        "fused.train_step")
            self._eval_step_ = AotStep(self._eval_step_, cache,
                                       "fused.eval_step")
            if self._use_gather_:
                self._train_step_g_ = AotStep(self._train_step_g_, cache,
                                              "fused.train_step_gather")
                self._eval_step_g_ = AotStep(self._eval_step_g_, cache,
                                             "fused.eval_step_gather")
        # copy: the step donates its param buffers, so they must not alias
        # the forward units' live weight Arrays
        self._params_ = [
            {k: jnp.array(v) for k, v in fwd.params.items()}
            for fwd in forwards]
        # solver state: restored from the GD units' pickled state when
        # resuming a snapshot, else freshly initialized
        self._opt_ = [
            {name: (tuple(jnp.asarray(s) for s in
                          gd.solver_state[name])
                    if gd.solver_state.get(name) else
                    gd.solver.init(p, jnp))
             for name, p in self._params_[i].items()}
            for i, gd in enumerate(gds)]

    def _macc_init(self):
        """Fresh on-device metric accumulator pytree."""
        import jax.numpy as jnp
        if self.loss_kind == "softmax":
            c = self._n_classes if self.compute_confusion_matrix else 0
            return (jnp.zeros((), jnp.int32),
                    jnp.zeros((c, c), jnp.int32),
                    jnp.zeros((), jnp.float32))
        return (jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
                jnp.full((), jnp.inf, jnp.float32))

    # -- run -----------------------------------------------------------------
    def _staged_seed_arg(self):
        """Device-resident seed scalar for the staged gather path.  The
        per-step H2D of the (tiny) seed costs a host sync point on
        tunneled devices; instead the NEXT train seed is device_put
        right after each dispatch, so the transfer rides under the
        in-flight step's compute.  Falls back to a synchronous
        device_put when nothing is staged (first step, restored run)."""
        import jax
        staged = getattr(self, "_staged_seed_", None)
        if staged is not None and staged[0] == self._seed_counter:
            arg = staged[1]
        else:
            arg = jax.device_put(numpy.int32(self._seed_counter))
        nxt = (self._seed_counter + 1) % 0x7FFF0000
        self._staged_seed_ = (nxt, jax.device_put(numpy.int32(nxt)))
        return arg

    def run(self):
        size = int(self.minibatch_size)
        train = self.minibatch_class == loader_mod.TRAIN
        if getattr(self, "_use_gather_", False):
            # a MinibatchPrefetcher stages idx/size on device ahead of
            # the step (the H2D overlapped the previous step's compute);
            # the synchronous path passes host values exactly as before
            staged = getattr(self.gather_loader, "prefetch_staged_", None)
            if staged is not None:
                idx, size_arg = staged
            else:
                idx, size_arg = self.gather_loader._padded_indices_, size
            if train:
                self._seed_counter = (self._seed_counter + 1) % 0x7FFF0000
                seed_arg = (self._staged_seed_arg() if staged is not None
                            else self._seed_counter)
                (self._params_, self._opt_, self._macc_, loss, out) = \
                    self._train_step_g_(
                        self._data_dev_, self._y_dev_, self._params_,
                        self._opt_, self._macc_, idx, size_arg,
                        seed_arg, float(self.lr_scale))
            else:
                self._macc_, loss, out = self._eval_step_g_(
                    self._data_dev_, self._y_dev_, self._params_,
                    self._macc_, idx, size_arg)
            self.loss = loss
            self.output.devmem = out
            if bool(self.last_minibatch):
                self._flush_metrics()
                self.sync_weights()
            return
        x = self.minibatch_data.devmem
        if self.loss_kind == "softmax":
            y = self.minibatch_labels.devmem
        else:
            y = self.minibatch_targets.devmem
        if train:
            self._seed_counter = (self._seed_counter + 1) % 0x7FFF0000
            (self._params_, self._opt_, self._macc_, loss, out) = \
                self._train_step_(self._params_, self._opt_, self._macc_,
                                  x, y, size, self._seed_counter,
                                  float(self.lr_scale))
        else:
            self._macc_, loss, out = self._eval_step_(
                self._params_, self._macc_, x, y, size)
        self.loss = loss           # device scalars; pulled lazily
        self.output.devmem = out
        if bool(self.last_minibatch):
            self._flush_metrics()
            self.sync_weights()

    def _flush_metrics(self):
        """Pull the device accumulator into the evaluator-compatible
        Arrays (one sync per class boundary, not per step)."""
        import jax
        if self.loss_kind == "softmax":
            n_err, cm, maxerr = self._macc_
            if self.compute_confusion_matrix:
                # the [C, C] matrix stays ON DEVICE: pulling it per class
                # boundary costs C²·4 bytes of D2H (4 MB for ImageNet
                # heads — ~600 ms through a tunneled link); instead the
                # running total accumulates device-side and the Array
                # transfers it lazily only when someone map_read()s it
                if self._cm_dev_ is None:
                    host = self.confusion_matrix.mem
                    if host is not None and host.any():
                        import jax.numpy as jnp  # resumed: seed from host
                        self._cm_dev_ = jnp.asarray(
                            host.astype(numpy.int32)) + cm
                    else:
                        self._cm_dev_ = cm
                else:
                    self._cm_dev_ = self._cm_dev_ + cm
                self.confusion_matrix.devmem = self._cm_dev_
            # scalars ride ONE batched device_get (per-leaf reads pay a
            # full sync RTT each on tunneled/remote devices)
            n_err, maxerr = jax.device_get((n_err, maxerr))
            self.n_err.map_write()[0] += int(n_err)
            self.max_err_output_sum.map_write()[0] = max(
                float(self.max_err_output_sum[0]), float(maxerr))
        else:
            sse, mx, mn = jax.device_get(self._macc_)
            m = self.metrics.map_write()
            m[0] += float(sse)
            m[1] = max(m[1], float(mx))
            m[2] = min(m[2], float(mn))
        self._macc_ = self._macc_init()

    def sync_weights(self):
        """Reflect the fused params back into the forward units' Arrays.
        Copies on device (cheap, once per epoch) — the fused buffers get
        donated by the next step and must not be aliased externally."""
        import jax.numpy as jnp
        for fwd, p in zip(self.forwards, self._params_):
            fwd.set_params({k: jnp.array(v) for k, v in p.items()})

    def sync_solver_state(self):
        """Pull the fused optimizer state into the GD units' picklable
        ``solver_state`` (host numpy) — called before snapshotting so a
        resumed run continues with intact momentum/accumulators."""
        import numpy
        for gd, layer in zip(self.gd_units, self._opt_):
            for name, state in layer.items():
                gd.solver_state[name] = tuple(
                    numpy.asarray(s) for s in state)

    def get_metric_values(self):
        return {"n_err": int(self.n_err[0]),
                "loss": None if self.loss is None else float(self.loss)}

    def make_trace(self):
        """The hand-fused step is already ONE compiled, donated program:
        under whole-workflow compilation it reports as a pre-compiled
        region of its own (one producer of traced regions, not a special
        case) and keeps executing natively — including its sharded and
        epoch-scan subclasses, whose in-program shardings survive
        untouched."""
        from ..graphcomp.faces import OpaqueFace
        return OpaqueFace(self, "hand-fused train step: one compiled "
                                "donated program per minibatch")
