"""Evaluator units: turn network output into a loss gradient + metrics.

Re-creation of ``veles.znicz.evaluator`` (absent; SURVEY.md §2.9):
EvaluatorSoftmax (cross-entropy, n_err / confusion matrix accounting) and
EvaluatorMSE (mean-squared error against targets).

Contract with the GD chain: ``err_output`` is the raw loss gradient wrt the
forward's output *summed over classes, not yet divided by batch size* — the
GD units divide by batch (mirrors the reference split of responsibilities).
Padded minibatch rows (beyond ``batch_size``) are masked out of both the
gradient and the metrics.
"""

import numpy

from ..memory import Array
from ..result_provider import IResultProvider
from .nn_units import NNUnitBase


class EvaluatorBase(NNUnitBase, IResultProvider):
    hide_from_registry = True
    view_group = "EVALUATOR"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.output = None           # linked: forward's output
        self.batch_size = None       # linked: loader.minibatch_size
        self.err_output = Array()
        self.testing = bool(kwargs.get("testing", False))

    def _mask(self, n_rows):
        """(max_batch,) float mask of valid rows."""
        m = numpy.zeros(n_rows, numpy.float32)
        m[:self.batch_size] = 1
        return m


class EvaluatorSoftmax(EvaluatorBase):
    """Cross-entropy evaluator for All2AllSoftmax outputs.

    err_output = (y - onehot(labels)) * row_mask; metrics: n_err (running
    per epoch reset by Decision), confusion_matrix, max_err_output_sum.
    """

    MAPPING = "evaluator_softmax"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.labels = None           # linked: loader.minibatch_labels
        self.max_idx = None          # linked: All2AllSoftmax.max_idx
        self.n_err = Array(numpy.zeros(1, numpy.int64))
        self.confusion_matrix = Array()
        self.max_err_output_sum = Array(numpy.zeros(1, numpy.float32))
        self.loss = None
        self.compute_confusion_matrix = bool(
            kwargs.get("compute_confusion_matrix", True))

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        n_classes = self.output.shape[-1]
        if self.compute_confusion_matrix:
            self.confusion_matrix.mem = numpy.zeros(
                (n_classes, n_classes), numpy.int64)

    def run(self):
        y = self._host(self.output)
        labels = self._host(self.labels).astype(numpy.int64)
        bs = int(self.batch_size)
        n_classes = y.shape[-1]
        onehot = numpy.zeros_like(y)
        valid = labels[:bs]
        onehot[numpy.arange(bs), valid] = 1
        err = y - onehot
        err[bs:] = 0
        self.err_output.mem = err.astype(numpy.float32)
        pred = self._host(self.max_idx)[:bs] if self.max_idx is not None \
            else numpy.argmax(y[:bs], axis=-1)
        errors = int((pred != valid).sum())
        self.n_err.map_write()[0] += errors
        eps = 1e-30
        self.loss = float(
            -numpy.log(y[numpy.arange(bs), valid] + eps).mean())
        self.max_err_output_sum.map_write()[0] = max(
            float(self.max_err_output_sum[0]),
            float(numpy.abs(err[:bs]).sum(axis=1).max()))
        if self.compute_confusion_matrix:
            cm = self.confusion_matrix.map_write()
            for t, p in zip(valid, pred):
                cm[p, t] += 1

    @staticmethod
    def _host(v):
        if isinstance(v, Array):
            return v.map_read()
        return numpy.asarray(v)

    def get_metric_values(self):
        return {"n_err": int(self.n_err[0]), "loss": self.loss}

    def make_trace(self):
        """Pure face of the softmax evaluator: the same masked
        ``err = y - onehot`` / error-count / confusion arithmetic as
        :meth:`run`, with the metric accumulators riding the region carry
        on device (flushed lazily — a Decision's class-boundary read
        materializes them).  Integer metrics (n_err, confusion) are exact,
        so traced == interpreted bit-for-bit."""
        from ..graphcomp.faces import NoFace, TraceFace, array_state_leaf
        if type(self).run is not EvaluatorSoftmax.run:
            return NoFace("custom evaluator run")
        if self.output is None or self.labels is None:
            return NoFace("evaluator inputs not linked")
        state = [array_state_leaf(self, "n_err"),
                 array_state_leaf(self, "max_err_output_sum")]
        with_cm = self.compute_confusion_matrix and \
            bool(self.confusion_matrix)
        if with_cm:
            state.append(array_state_leaf(self, "confusion_matrix"))
        inputs = ["output", "labels"]
        with_max_idx = self.max_idx is not None
        if with_max_idx:
            inputs.append("max_idx")

        def fn(state_in, ins, statics):
            import jax.numpy as jnp
            y = ins["output"]
            bs = int(statics["batch_size"])
            labels = ins["labels"].astype(jnp.int32)
            n = y.shape[0]
            mask = jnp.arange(n) < bs
            valid = labels[:bs]
            onehot = jnp.zeros_like(y).at[
                (jnp.arange(bs), valid)].set(1)
            err = jnp.where(mask[:, None], y - onehot, 0)
            pred_full = ins["max_idx"] if with_max_idx else \
                jnp.argmax(y, axis=-1)
            pred = pred_full[:bs].astype(jnp.int32)
            wrong = (pred != valid).sum()
            n_err = state_in["n_err"] + \
                wrong.astype(state_in["n_err"].dtype)
            eps = 1e-30
            probs = jnp.take_along_axis(y[:bs], valid[:, None],
                                        axis=-1)[:, 0]
            loss = -jnp.log(probs + eps).mean()
            row_err = jnp.abs(err[:bs]).sum(axis=1).max()
            mx = jnp.maximum(state_in["max_err_output_sum"],
                             row_err.astype(
                                 state_in["max_err_output_sum"].dtype))
            updates = {"n_err": n_err, "max_err_output_sum": mx}
            if with_cm:
                cm = state_in["confusion_matrix"]
                updates["confusion_matrix"] = cm.at[(pred, valid)].add(
                    jnp.ones((), cm.dtype))
            return updates, {"err_output": err, "loss": loss}
        return TraceFace(self, fn, inputs=tuple(inputs),
                         statics=("batch_size",),
                         outputs=("err_output", "loss"),
                         state=tuple(state),
                         config=(with_cm, with_max_idx))

    # pure loss for the fused trainer ---------------------------------------
    @staticmethod
    def loss_from_logits(logits, labels, mask):
        """Numerically-stable masked softmax cross-entropy (mean over valid
        rows) — used by the fused jitted step where the forward supplies
        logits (All2AllSoftmax.apply_logits)."""
        import jax
        import jax.numpy as jnp
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


class EvaluatorMSE(EvaluatorBase):
    """MSE evaluator (reference EvaluatorMSE): err_output = y - target."""

    MAPPING = "evaluator_mse"

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.target = None           # linked: loader.minibatch_targets
        self.metrics = Array(numpy.zeros(3, numpy.float64))
        # metrics = [sum squared error, max sample mse, min sample mse]
        self.metrics.mem[2] = numpy.inf
        self.n_err = Array(numpy.zeros(1, numpy.int64))
        self.mse = Array()
        self.root = bool(kwargs.get("root", True))  # rmse in results

    def run(self):
        y = EvaluatorSoftmax._host(self.output)
        t = EvaluatorSoftmax._host(self.target)
        bs = int(self.batch_size)
        err = (y - t).reshape(y.shape[0], -1)
        err[bs:] = 0
        self.err_output.mem = err.reshape(y.shape).astype(numpy.float32)
        sample_mse = numpy.sqrt((err[:bs] ** 2).mean(axis=1))
        self.mse.mem = sample_mse
        m = self.metrics.map_write()
        m[0] += float((err[:bs] ** 2).mean(axis=1).sum())
        m[1] = max(m[1], float(sample_mse.max(initial=0)))
        m[2] = min(m[2], float(sample_mse.min(initial=numpy.inf)))

    def get_metric_values(self):
        return {"mse_sum": float(self.metrics[0]),
                "max_mse": float(self.metrics[1]),
                "min_mse": float(self.metrics[2])}

    def make_trace(self):
        """Pure face of the MSE evaluator.  ``err_output`` (what the GD
        chain consumes) is exact; the running ``metrics`` accumulate on
        device in float32 instead of the host's float64 — weights stay
        bitwise-identical traced vs interpreted, epoch rmse agrees to
        float32 precision (documented in COMPONENTS.md)."""
        from ..graphcomp.faces import NoFace, TraceFace, array_state_leaf
        if type(self).run is not EvaluatorMSE.run:
            return NoFace("custom evaluator run")
        if self.output is None or self.target is None:
            return NoFace("evaluator inputs not linked")

        def fn(state_in, ins, statics):
            import jax.numpy as jnp
            y = ins["output"]
            t = ins["target"]
            bs = int(statics["batch_size"])
            n = y.shape[0]
            mask = jnp.arange(n) < bs
            err = (y - t).reshape(n, -1)
            err = jnp.where(mask[:, None], err, 0)
            sample_mse = (err[:bs] ** 2).mean(axis=1)
            rmse = jnp.sqrt(sample_mse)
            m = state_in["metrics"]
            m = m.at[0].add(sample_mse.sum().astype(m.dtype))
            m = m.at[1].max(rmse.max().astype(m.dtype))
            m = m.at[2].min(rmse.min().astype(m.dtype))
            return {"metrics": m}, {"err_output": err.reshape(y.shape),
                                    "mse": rmse}
        return TraceFace(self, fn, inputs=("output", "target"),
                         statics=("batch_size",),
                         outputs=("err_output", "mse"),
                         state=(array_state_leaf(self, "metrics"),))

    @staticmethod
    def loss_from_output(y, target, mask):
        """Masked MSE whose gradient wrt ``y`` is exactly ``err / n_valid``
        — the same effective gradient graph mode produces (evaluator emits
        ``err = y - t``, the GD units divide by the valid batch size), so
        fused and graph MSE training match step-for-step.  Value =
        0.5 * sum-over-features squared error, averaged over valid rows."""
        import jax.numpy as jnp
        err = (y - target).reshape(y.shape[0], -1)
        per_sample = 0.5 * (err * err).sum(axis=1)
        return (per_sample * mask).sum() / jnp.maximum(mask.sum(), 1.0)
